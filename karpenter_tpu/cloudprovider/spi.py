"""The CloudProvider service-provider interface.

Counterpart of reference pkg/cloudprovider/types.go:73-118. Controllers only
ever talk to this interface; the scheduler itself never does — it consumes
the InstanceType catalog and emits NodeClaim specs (the seam where the TPU
solver plugs in).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.cloudprovider.instancetype import InstanceType
from karpenter_tpu.models.nodeclaim import NodeClaim
from karpenter_tpu.models.nodepool import NodePool


@dataclass
class RepairPolicy:
    """An unhealthy-node condition the provider wants remediated
    (types.go:103-118)."""

    condition_type: str
    condition_status: str
    toleration_seconds: float


class CloudProvider(abc.ABC):
    """The 9-method SPI (types.go:73-101)."""

    @abc.abstractmethod
    def create(self, node_claim: NodeClaim) -> NodeClaim:
        """Launch an instance for the claim; returns the resolved claim with
        provider_id, capacity, allocatable and instance labels populated.
        Raises InsufficientCapacityError / NodeClassNotReadyError /
        CreateError."""

    @abc.abstractmethod
    def delete(self, node_claim: NodeClaim) -> None:
        """Terminate the backing instance. Raises NodeClaimNotFoundError once
        the instance no longer exists (callers retry until then)."""

    @abc.abstractmethod
    def get(self, provider_id: str) -> NodeClaim:
        """Fetch current cloud truth for one instance.
        Raises NodeClaimNotFoundError."""

    @abc.abstractmethod
    def list(self) -> list[NodeClaim]:
        """List all instances owned by this provider."""

    @abc.abstractmethod
    def get_instance_types(self, node_pool: NodePool) -> list[InstanceType]:
        """The catalog for one pool. May raise UnevaluatedNodePoolError."""

    @abc.abstractmethod
    def is_drifted(self, node_claim: NodeClaim) -> Optional[str]:
        """A drift reason string if the claim drifted from provider-side
        config, else None."""

    def repair_policies(self) -> list[RepairPolicy]:
        return []

    def registration_hooks(self) -> list:
        """NodeLifecycleHook analogs (types.go:103-118): objects exposing
        `name` and `registered(node_claim) -> bool`. Registration completes
        — and the unregistered NoExecute taint drops — only once EVERY
        hook reports ready (registration.go:96-105); until then the claim
        stays gated with its node labels/taints synced. Decorators forward
        to their inner provider automatically."""
        inner = getattr(self, "inner", None)
        if inner is not None:
            return inner.registration_hooks()
        return []

    @property
    @abc.abstractmethod
    def name(self) -> str: ...

    @property
    def unwrapped(self):
        """The innermost provider: decorators (objects exposing `inner`)
        unwrap recursively; leaf providers return themselves."""
        inner = getattr(self, "inner", None)
        if inner is None:
            return self
        return getattr(inner, "unwrapped", inner)

    def get_supported_node_classes(self) -> list[str]:
        return []
