"""CloudProvider SPI.

Counterpart of reference pkg/cloudprovider/types.go:73-101 (the 9-method
interface) and types.go:601-732 (the typed error taxonomy that drives
controller behavior).
"""

from karpenter_tpu.cloudprovider.errors import (  # noqa: F401
    CloudProviderError,
    CreateError,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    NodeClassNotReadyError,
    UnevaluatedNodePoolError,
)
from karpenter_tpu.cloudprovider.instancetype import (  # noqa: F401
    InstanceType,
    InstanceTypeOverhead,
    Offering,
    cheapest,
    compatible_instance_types,
    order_by_price,
    satisfies_min_values,
    truncate_instance_types,
    worst_launch_price,
)
from karpenter_tpu.cloudprovider.spi import CloudProvider, RepairPolicy  # noqa: F401
