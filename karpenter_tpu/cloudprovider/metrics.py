"""SPI metrics decorator: wraps every CloudProvider call in duration and
error instrumentation.

Counterpart of reference pkg/cloudprovider/metrics/cloudprovider.go — the
decorator-pattern seam a remote (gRPC) provider shim would occupy: callers
see an unchanged CloudProvider while every crossing is measured.
"""

from __future__ import annotations

import time

from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.utils.metrics import CLOUDPROVIDER_DURATION, CLOUDPROVIDER_ERRORS


class MetricsCloudProvider(CloudProvider):
    """Forwarding decorator; `inner` is the wrapped provider."""

    def __init__(self, inner: CloudProvider):
        self.inner = inner

    @property
    def name(self) -> str:
        return self.inner.name

    def _call(self, method: str, *args, **kwargs):
        start = time.perf_counter()
        try:
            return getattr(self.inner, method)(*args, **kwargs)
        except Exception as e:
            CLOUDPROVIDER_ERRORS.inc(
                controller="",
                method=method,
                provider=self.inner.name,
                error=type(e).__name__,
            )
            raise
        finally:
            CLOUDPROVIDER_DURATION.observe(
                time.perf_counter() - start,
                controller="",
                method=method,
                provider=self.inner.name,
            )

    def create(self, node_claim):
        return self._call("create", node_claim)

    def delete(self, node_claim) -> None:
        return self._call("delete", node_claim)

    def get(self, provider_id: str):
        return self._call("get", provider_id)

    def list(self):
        return self._call("list")

    def get_instance_types(self, node_pool):
        return self._call("get_instance_types", node_pool)

    def is_drifted(self, node_claim):
        return self._call("is_drifted", node_claim)

    def repair_policies(self):
        return self._call("repair_policies")

    def get_supported_node_classes(self):
        return self._call("get_supported_node_classes")
