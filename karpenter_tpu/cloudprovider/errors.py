"""Typed cloud-provider errors (reference pkg/cloudprovider/types.go:601-732).

The error type — not the message — drives controller behavior:
  NodeClaimNotFoundError    delete retries until the instance is gone
  InsufficientCapacityError launch fails fast; claim deleted; pods re-scheduled
  NodeClassNotReadyError    launch requeues until the node class is ready
  CreateError               carries a condition reason/message onto the claim
  UnevaluatedNodePoolError  overlay store has not evaluated this pool yet
"""

from __future__ import annotations


class CloudProviderError(Exception):
    pass


class NodeClaimNotFoundError(CloudProviderError):
    pass


class InsufficientCapacityError(CloudProviderError):
    pass


class NodeClassNotReadyError(CloudProviderError):
    pass


class CreateError(CloudProviderError):
    def __init__(self, message: str, reason: str = "LaunchFailed"):
        super().__init__(message)
        self.reason = reason


class UnevaluatedNodePoolError(CloudProviderError):
    pass


def instance_types_or_none(cloud, pool):
    """get_instance_types, absorbing the overlay store's unevaluated gate
    (reference store.go:64-65): callers skip the pool for this pass; the
    nodeoverlay controller's next reconcile — triggered synchronously by
    the pool event — lifts the gate."""
    try:
        return cloud.get_instance_types(pool)
    except UnevaluatedNodePoolError:
        return None


def is_insufficient_capacity(err: Exception) -> bool:
    return isinstance(err, InsufficientCapacityError)


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NodeClaimNotFoundError)
