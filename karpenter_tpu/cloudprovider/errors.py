"""Typed cloud-provider errors (reference pkg/cloudprovider/types.go:601-732).

The error type — not the message — drives controller behavior:
  NodeClaimNotFoundError    delete retries until the instance is gone
  InsufficientCapacityError launch fails fast; claim deleted; pods re-scheduled;
                            the named offerings enter the blackout cache
  TransientError            bounded retry + requeue (throttle, timeout, flake)
  TerminalError             no retry; the claim's condition carries the reason
  NodeClassNotReadyError    launch requeues until the node class is ready
  CreateError               carries a condition reason/message onto the claim
  UnevaluatedNodePoolError  overlay store has not evaluated this pool yet

The Transient/ICE/Terminal split is the retry taxonomy the fault-inject
subsystem exercises: ``is_retryable`` is the single predicate the
lifecycle controller and the disruption queue consult, so a provider
(or an injected fault) only has to pick the right type.
"""

from __future__ import annotations

from typing import Optional, Sequence


class CloudProviderError(Exception):
    pass


class NodeClaimNotFoundError(CloudProviderError):
    pass


class TransientError(CloudProviderError):
    """Retryable: the same call is expected to succeed shortly (API
    brownout, rate limit, network flake). Controllers retry with bounded
    attempts + requeue instead of failing the claim."""


class ThrottleError(TransientError):
    """Provider rate limiting (AWS ThrottlingException analog)."""


class CloudTimeoutError(TransientError):
    """The provider call timed out; the operation may or may not have
    landed — callers must stay idempotent."""


class TerminalError(CloudProviderError):
    """Not retryable: repeating the call cannot succeed (bad request,
    quota config, permanent rejection)."""


class InsufficientCapacityError(CloudProviderError):
    """No capacity for the requested offering(s). ``offerings`` names the
    (instance_type, zone, capacity_type) triples the provider attempted,
    so the lifecycle controller can blackout exactly those offerings
    (reference pkg/providers ICE cache parity)."""

    def __init__(
        self,
        message: str = "",
        offerings: Optional[Sequence[tuple[str, str, str]]] = None,
    ):
        super().__init__(message)
        self.offerings = list(offerings or [])


class NodeClassNotReadyError(CloudProviderError):
    pass


class CreateError(CloudProviderError):
    def __init__(self, message: str, reason: str = "LaunchFailed"):
        super().__init__(message)
        self.reason = reason


class UnevaluatedNodePoolError(CloudProviderError):
    pass


def instance_types_or_none(cloud, pool):
    """get_instance_types, absorbing the overlay store's unevaluated gate
    (reference store.go:64-65): callers skip the pool for this pass; the
    nodeoverlay controller's next reconcile — triggered synchronously by
    the pool event — lifts the gate."""
    try:
        return cloud.get_instance_types(pool)
    except UnevaluatedNodePoolError:
        return None


def is_retryable(err: Exception) -> bool:
    """The retry predicate: transient errors get bounded retry + requeue;
    everything else follows its own typed path (ICE fail-fast, terminal
    condition, not-found finalizer drop)."""
    return isinstance(err, TransientError)


def is_insufficient_capacity(err: Exception) -> bool:
    return isinstance(err, InsufficientCapacityError)


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NodeClaimNotFoundError)
