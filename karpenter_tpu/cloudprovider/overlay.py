"""NodeOverlay: runtime price/capacity adjustment of instance types.

Counterpart of reference pkg/apis/v1alpha1 (NodeOverlay) +
pkg/controllers/nodeoverlay (store.go:45-288) + the overlay cloudprovider
decorator (pkg/cloudprovider/overlay): overlays match instance types by
requirements and adjust offering prices (absolute / ±delta / ±percent) or
merge extra capacity; the decorator applies the evaluated store on every
GetInstanceTypes. Conflicting overlays resolve by weight, heaviest wins.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.cloudprovider.instancetype import InstanceType, Offering, adjusted_price
from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.scheduling.requirements import node_selector_requirement


@dataclass
class NodeOverlay:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="overlay"))
    requirements: list[dict] = field(default_factory=list)  # {key, operator, values}
    weight: int = 0  # heaviest wins on conflict
    price: Optional[str] = None  # absolute / "+N" / "-N" / "±N%"
    capacity: dict[str, float] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name

    def matches(self, it: InstanceType) -> bool:
        reqs = Requirements(
            *(
                node_selector_requirement(r["key"], r["operator"], r.get("values", ()))
                for r in self.requirements
            )
        )
        return it.requirements.is_compatible(reqs, l.WELL_KNOWN_LABELS)


class OverlayStore:
    """Evaluated overlays applied to a catalog (store.go:45-288)."""

    def __init__(self, overlays: list[NodeOverlay]):
        # heaviest weight first; name tie-break for determinism
        self.overlays = sorted(overlays, key=lambda o: (-o.weight, o.name))
        # parse each overlay's requirements once, not per offering
        self._overlay_reqs = [
            Requirements(
                *(
                    node_selector_requirement(r["key"], r["operator"], r.get("values", ()))
                    for r in o.requirements
                )
            )
            for o in self.overlays
        ]

    def _price_overlay_for(self, it: InstanceType, offering: Offering) -> Optional[NodeOverlay]:
        """The heaviest price overlay compatible with THIS offering — price
        updates are keyed per offering (store.go:155-167), so a spot-only
        overlay never reprices on-demand offerings of the same type."""
        combined = it.requirements.copy()
        combined.add(*offering.requirements.values())
        for o, reqs in zip(self.overlays, self._overlay_reqs):
            if o.price is None:
                continue
            if combined.is_compatible(reqs, l.WELL_KNOWN_LABELS):
                return o
        return None

    def _merged_capacity(self, it: InstanceType) -> dict[str, float]:
        """Capacity keys merge across ALL matching overlays, heaviest
        winning per key (store.go:199-207)."""
        merged: dict[str, float] = {}
        # lightest first so heavier overlays overwrite per key
        for o, reqs in reversed(list(zip(self.overlays, self._overlay_reqs))):
            if o.capacity and it.requirements.is_compatible(reqs, l.WELL_KNOWN_LABELS):
                merged.update(o.capacity)
        return merged

    def apply(self, its: list[InstanceType]) -> list[InstanceType]:
        out = []
        for it in its:
            merged_capacity = self._merged_capacity(it)
            new_offerings = []
            any_price = False
            for of in it.offerings:
                po = self._price_overlay_for(it, of)
                new_of = Offering(
                    requirements=of.requirements,
                    price=adjusted_price(of.price, po.price) if po is not None else of.price,
                    available=of.available,
                    reservation_capacity=of.reservation_capacity,
                    capacity_override=dict(of.capacity_override),
                    overhead_override=of.overhead_override,
                )
                if po is not None:
                    new_of._price_overlay_applied = True
                    any_price = True
                new_offerings.append(new_of)
            if not any_price and not merged_capacity:
                out.append(it)
                continue
            clone = InstanceType(
                name=it.name,
                requirements=it.requirements,
                offerings=new_offerings,
                capacity=dict(it.capacity),
                overhead=it.overhead,
            )
            if merged_capacity:
                clone.apply_capacity_overlay(merged_capacity)
            out.append(clone)
        return out


class OverlayCloudProvider(CloudProvider):
    """Decorator applying the overlay store on GetInstanceTypes
    (pkg/cloudprovider/overlay/cloudprovider.go; wiring kwok/main.go:36)."""

    def __init__(self, inner: CloudProvider, store):
        self.inner = inner
        self.object_store = store

    @property
    def name(self) -> str:
        return self.inner.name

    def get_instance_types(self, node_pool):
        its = self.inner.get_instance_types(node_pool)
        overlays = self.object_store.list(self.object_store.NODE_OVERLAYS)
        if not overlays:
            return its
        return OverlayStore(overlays).apply(its)

    # everything else passes through
    def create(self, node_claim):
        return self.inner.create(node_claim)

    def delete(self, node_claim):
        return self.inner.delete(node_claim)

    def get(self, provider_id):
        return self.inner.get(provider_id)

    def list(self):
        return self.inner.list()

    def is_drifted(self, node_claim):
        return self.inner.is_drifted(node_claim)

    def repair_policies(self):
        return self.inner.repair_policies()