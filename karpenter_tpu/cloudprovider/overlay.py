"""NodeOverlay: runtime price/capacity adjustment of instance types.

Counterpart of reference pkg/apis/v1alpha1 (NodeOverlay) +
pkg/controllers/nodeoverlay (store.go:45-288) + the overlay cloudprovider
decorator (pkg/cloudprovider/overlay): overlays match instance types by
requirements and adjust offering prices (absolute / ±delta / ±percent) or
merge extra capacity; the decorator applies the evaluated store on every
GetInstanceTypes. Conflicting overlays resolve by weight, heaviest wins.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.cloudprovider.instancetype import InstanceType, Offering, adjusted_price
from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.objects import ConditionSet, ObjectMeta
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.scheduling.requirements import node_selector_requirement


@dataclass
class NodeOverlay:
    metadata: ObjectMeta = field(default_factory=lambda: ObjectMeta(name="overlay"))
    requirements: list[dict] = field(default_factory=list)  # {key, operator, values}
    weight: int = 0  # heaviest wins on conflict
    price: Optional[str] = None  # absolute / "+N" / "-N" / "±N%"
    capacity: dict[str, float] = field(default_factory=dict)
    # ValidationSucceeded set by the nodeoverlay controller
    # (controller.go:271-281): False(RuntimeValidation) / False(Conflict)
    conditions: ConditionSet = field(default_factory=ConditionSet)

    @property
    def name(self) -> str:
        return self.metadata.name

    def matches(self, it: InstanceType) -> bool:
        reqs = Requirements(
            *(
                node_selector_requirement(r["key"], r["operator"], r.get("values", ()))
                for r in self.requirements
            )
        )
        return it.requirements.is_compatible(reqs, l.WELL_KNOWN_LABELS)


def pool_base_reqs(pool) -> Requirements:
    """The nodepool half of the overlay-matching surface: nodepool label +
    template labels (controller.go getOverlaidOfferings:332-344). Shared by
    the nodeoverlay controller's validation and OverlayStore.apply so the
    two can never disagree about which overlays match."""
    from karpenter_tpu.scheduling.requirements import Requirement

    reqs = Requirements(
        Requirement.new(l.NODEPOOL_LABEL_KEY, "In", pool.metadata.name)
    )
    for k, v in (pool.spec.template.labels or {}).items():
        reqs.add(Requirement.new(k, "In", v))
    return reqs


class OverlayStore:
    """Evaluated overlays applied to a catalog (store.go:45-288)."""

    def __init__(self, overlays: list[NodeOverlay]):
        # heaviest weight first; name tie-break for determinism
        self.overlays = sorted(overlays, key=lambda o: (-o.weight, o.name))
        # parse each overlay's requirements once, not per offering
        self._overlay_reqs = [
            Requirements(
                *(
                    node_selector_requirement(r["key"], r["operator"], r.get("values", ()))
                    for r in o.requirements
                )
            )
            for o in self.overlays
        ]

    def _price_overlay_for(
        self, it: InstanceType, offering: Offering, ctx: Optional[Requirements] = None
    ) -> Optional[NodeOverlay]:
        """The heaviest price overlay compatible with THIS offering — price
        updates are keyed per offering (store.go:155-167), so a spot-only
        overlay never reprices on-demand offerings of the same type."""
        combined = (ctx if ctx is not None else it.requirements).copy()
        combined.add(*offering.requirements.values())
        for o, reqs in zip(self.overlays, self._overlay_reqs):
            if o.price is None:
                continue
            if combined.is_compatible(reqs, l.WELL_KNOWN_LABELS):
                return o
        return None

    def _merged_capacity(self, it: InstanceType, ctx: Requirements) -> dict[str, float]:
        """Capacity keys merge across ALL matching overlays, heaviest
        winning per key (store.go:199-207)."""
        merged: dict[str, float] = {}
        # lightest first so heavier overlays overwrite per key
        for o, reqs in reversed(list(zip(self.overlays, self._overlay_reqs))):
            if o.capacity and ctx.is_compatible(reqs, l.WELL_KNOWN_LABELS):
                merged.update(o.capacity)
        return merged

    def apply(self, its: list[InstanceType], pool=None) -> list[InstanceType]:
        """Overlay a catalog; `pool` adds the nodepool-context requirements
        (nodepool label + template labels) overlays may select on
        (controller.go getOverlaidOfferings:332-344)."""
        pool_reqs = pool_base_reqs(pool) if pool is not None else None
        out = []
        for it in its:
            ctx = it.requirements
            if pool_reqs is not None:
                ctx = pool_reqs.copy()
                ctx.add(*it.requirements.values())
            merged_capacity = self._merged_capacity(it, ctx)
            new_offerings = []
            any_price = False
            for of in it.offerings:
                po = self._price_overlay_for(it, of, ctx)
                new_of = Offering(
                    requirements=of.requirements,
                    price=adjusted_price(of.price, po.price) if po is not None else of.price,
                    available=of.available,
                    reservation_capacity=of.reservation_capacity,
                    capacity_override=dict(of.capacity_override),
                    overhead_override=of.overhead_override,
                )
                if po is not None:
                    new_of._price_overlay_applied = True
                    any_price = True
                new_offerings.append(new_of)
            if not any_price and not merged_capacity:
                out.append(it)
                continue
            clone = InstanceType(
                name=it.name,
                requirements=it.requirements,
                offerings=new_offerings,
                capacity=dict(it.capacity),
                overhead=it.overhead,
            )
            if merged_capacity:
                clone.apply_capacity_overlay(merged_capacity)
            out.append(clone)
        return out


class OverlayCloudProvider(CloudProvider):
    """Decorator applying the overlay store on GetInstanceTypes
    (pkg/cloudprovider/overlay/cloudprovider.go; wiring kwok/main.go:36).

    Two modes:
    - evaluated (controller-managed, the reference's): the nodeoverlay
      controller publishes validated + conflict-free overlays and the set
      of evaluated pools; a pool the controller has not evaluated yet
      raises UnevaluatedNodePoolError (store.go:64-65, 84-85).
    - direct (no controller wired, e.g. bare-harness tests): every stored
      overlay applies immediately with weight precedence, ungated.
    """

    def __init__(self, inner: CloudProvider, store, evaluated_store=None):
        self.inner = inner
        self.object_store = store
        # set by Manager when the nodeoverlay controller is wired
        self.evaluated_store = evaluated_store

    @property
    def name(self) -> str:
        return self.inner.name

    def get_instance_types(self, node_pool):
        if self.evaluated_store is not None:
            from karpenter_tpu.cloudprovider.errors import UnevaluatedNodePoolError

            current = self.evaluated_store.current()
            if current is None or node_pool.metadata.name not in current.evaluated_pools:
                raise UnevaluatedNodePoolError(
                    f"node pool {node_pool.metadata.name!r} has not been "
                    "evaluated by the nodeoverlay controller yet"
                )
            its = self.inner.get_instance_types(node_pool)
            if not current.active:
                return its
            return OverlayStore(current.active).apply(its, pool=node_pool)
        its = self.inner.get_instance_types(node_pool)
        overlays = self.object_store.list(self.object_store.NODE_OVERLAYS)
        if not overlays:
            return its
        return OverlayStore(overlays).apply(its, pool=node_pool)

    # everything else passes through
    def create(self, node_claim):
        return self.inner.create(node_claim)

    def delete(self, node_claim):
        return self.inner.delete(node_claim)

    def get(self, provider_id):
        return self.inner.get(provider_id)

    def list(self):
        return self.inner.list()

    def is_drifted(self, node_claim):
        return self.inner.is_drifted(node_claim)

    def repair_policies(self):
        return self.inner.repair_policies()