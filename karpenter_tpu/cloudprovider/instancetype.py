"""InstanceType / Offering models.

Counterpart of reference pkg/cloudprovider/types.go:123-598: memoized
allocatable computation with hugepage adjustment and per-offering
capacity/overhead override groups, price ordering, compatibility filtering,
greedy minValues satisfaction, and launch-time truncation.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Iterable, Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.scheduling.requirements import (
    on_demand_requirements,
    reserved_requirements,
    spot_requirements,
)
from karpenter_tpu.utils import resources as res

RESERVATION_ID_LABEL = l.RESERVATION_ID_LABEL_KEY

MAX_FLOAT = math.inf


@dataclass
class InstanceTypeOverhead:
    """kube-reserved + system-reserved + eviction threshold
    (types.go:452-463)."""

    kube_reserved: dict[str, float] = field(default_factory=dict)
    system_reserved: dict[str, float] = field(default_factory=dict)
    eviction_threshold: dict[str, float] = field(default_factory=dict)

    def total(self) -> dict[str, float]:
        return res.merge(self.kube_reserved, self.system_reserved, self.eviction_threshold)


@dataclass
class Offering:
    """Availability of an instance type in (zone × capacity-type
    [× reservation]) at a price (types.go:470-487)."""

    requirements: Requirements
    price: float
    available: bool = True
    reservation_capacity: int = 0
    capacity_override: dict[str, float] = field(default_factory=dict)
    overhead_override: Optional[InstanceTypeOverhead] = None
    _price_overlay_applied: bool = False

    @property
    def capacity_type(self) -> str:
        return self.requirements.get(l.CAPACITY_TYPE_LABEL_KEY).any_value()

    @property
    def zone(self) -> str:
        return self.requirements.get(l.LABEL_TOPOLOGY_ZONE).any_value()

    @property
    def reservation_id(self) -> str:
        return self.requirements.get(RESERVATION_ID_LABEL).any_value()

    def apply_price_overlay(self, change: str) -> None:
        self.price = adjusted_price(self.price, change)
        self._price_overlay_applied = True

    @property
    def is_price_overlaid(self) -> bool:
        return self._price_overlay_applied


def adjusted_price(price: float, change: str) -> float:
    """NodeOverlay price arithmetic: absolute / ±delta / ±percent
    (types.go:493-525)."""
    if not change:
        return price
    if not change.startswith(("+", "-")):
        return float(change)
    if change.endswith("%"):
        adjusted = price * (1 + float(change[:-1]) / 100.0)
    else:
        adjusted = price + float(change)
    return adjusted if adjusted >= 0 else 0.0


@dataclass
class AllocatableOfferings:
    """One allocatable resource set + the offerings producing it
    (types.go:196-199)."""

    allocatable: dict[str, float]
    offerings: list[Offering]


class InstanceType:
    """One machine shape: requirements + offerings + capacity + overhead."""

    def __init__(
        self,
        name: str,
        requirements: Requirements,
        offerings: list[Offering],
        capacity: dict[str, float],
        overhead: Optional[InstanceTypeOverhead] = None,
        dra_slices: Optional[list] = None,
        dra_attribute_bindings: Optional[list] = None,
    ):
        self.name = name
        self.requirements = requirements
        self.offerings = offerings
        # DRA: potential-device ResourceSlice templates this instance type
        # would publish after launch, and attribute-binding declarations for
        # runtime-only attributes (reference types.go InstanceType
        # .DynamicResources; consumed by scheduling/dra).
        self.dra_slices = dra_slices or []
        self.dra_attribute_bindings = dra_attribute_bindings or []
        # resource dicts are float32-quantized at every model boundary so
        # host arithmetic and the f32 device tensors agree exactly
        self.capacity = res.quantize(capacity)
        overhead = overhead or InstanceTypeOverhead()
        self.overhead = InstanceTypeOverhead(
            kube_reserved=res.quantize(overhead.kube_reserved),
            system_reserved=res.quantize(overhead.system_reserved),
            eviction_threshold=res.quantize(overhead.eviction_threshold),
        )
        self._allocatable_offerings: Optional[list[AllocatableOfferings]] = None
        self._capacity_overlay_applied = False

    # -- allocatable (types.go:202-334) -----------------------------------

    def _compute_allocatable(
        self,
        capacity_override: Optional[dict[str, float]],
        overhead_override: Optional[InstanceTypeOverhead],
    ) -> dict[str, float]:
        capacity = dict(self.capacity)
        if capacity_override:
            capacity.update(res.quantize(capacity_override))
        overhead = self.overhead.total()
        if overhead_override is not None:
            overhead = {**overhead, **overhead_override.total()}
        allocatable = res.subtract(capacity, overhead)
        # hugepage reservations come out of allocatable memory (types.go:282-293)
        for name, quantity in capacity.items():
            if name.startswith(res.HUGEPAGES_PREFIX):
                mem = allocatable.get(res.MEMORY, 0.0) - quantity
                allocatable[res.MEMORY] = max(mem, 0.0)
        return allocatable

    def _precompute(self) -> list[AllocatableOfferings]:
        available = [o for o in self.offerings if o.available]
        has_overrides = any(o.capacity_override or o.overhead_override for o in self.offerings)
        if not has_overrides:
            return [AllocatableOfferings(self._compute_allocatable(None, None), available)]
        # group available offerings by their override tuple; base group first
        groups: dict[tuple, AllocatableOfferings] = {}
        base = AllocatableOfferings(self._compute_allocatable(None, None), [])
        order: list[tuple] = [()]
        groups[()] = base
        for o in available:
            if not o.capacity_override and o.overhead_override is None:
                base.offerings.append(o)
                continue
            key = (
                tuple(sorted(o.capacity_override.items())),
                tuple(sorted(o.overhead_override.total().items())) if o.overhead_override else None,
            )
            if key not in groups:
                groups[key] = AllocatableOfferings(
                    self._compute_allocatable(o.capacity_override, o.overhead_override), []
                )
                order.append(key)
            groups[key].offerings.append(o)
        return [groups[k] for k in order]

    def allocatable_offerings(self) -> list[AllocatableOfferings]:
        if self._allocatable_offerings is None:
            self._allocatable_offerings = self._precompute()
        return self._allocatable_offerings

    def allocatable(self) -> dict[str, float]:
        """Base allocatable (no offering overrides)."""
        return self.allocatable_offerings()[0].allocatable

    # -- offerings ---------------------------------------------------------

    def offering_price(self, zone: str, capacity_type: str) -> Optional[float]:
        for o in self.offerings:
            if o.zone == zone and o.capacity_type == capacity_type:
                return o.price
        return None

    def available_offerings(self) -> list[Offering]:
        return [o for o in self.offerings if o.available]

    def cheapest_offering_price(self, reqs: Requirements) -> float:
        """Cheapest available LAUNCHABLE offering compatible with reqs, inf
        if none. Reserved offerings only count when the requirements pin a
        reservation id — a provider never launches into a reservation the
        claim doesn't name (FinalizeScheduling injects the pin,
        nodeclaim.go:393-401), so an unpinned claim prices at spot/OD."""
        pinned = reqs.has(RESERVATION_ID_LABEL)
        best = MAX_FLOAT
        for o in self.offerings:
            if not o.available:
                continue
            if o.capacity_type == l.CAPACITY_TYPE_RESERVED and not pinned:
                continue
            if reqs.is_compatible(o.requirements, l.WELL_KNOWN_LABELS):
                best = min(best, o.price)
        return best

    def has_compatible_offering(self, reqs: Requirements) -> bool:
        return any(
            reqs.is_compatible(o.requirements, l.WELL_KNOWN_LABELS) for o in self.available_offerings()
        )

    def apply_capacity_overlay(self, updated: dict[str, float]) -> None:
        self.capacity = {**self.capacity, **updated}
        self._capacity_overlay_applied = True
        self._allocatable_offerings = None

    @property
    def is_capacity_overlay_applied(self) -> bool:
        return self._capacity_overlay_applied

    @property
    def is_pricing_overlay_applied(self) -> bool:
        return any(o.is_price_overlaid for o in self.offerings)

    def __repr__(self) -> str:
        return f"InstanceType({self.name})"


# -- collection operations (types.go:336-455) ------------------------------


def order_by_price(its: Iterable[InstanceType], reqs: Requirements) -> list[InstanceType]:
    """Sort by cheapest compatible available offering (types.go:336-356).

    Python's stable sort preserves input order on ties, matching Go's needs
    for deterministic downstream minValues counting.
    """
    return sorted(its, key=lambda it: it.cheapest_offering_price(reqs))


def compatible_instance_types(its: Iterable[InstanceType], reqs: Requirements) -> list[InstanceType]:
    """Instance types with >=1 available offering compatible with reqs."""
    return [it for it in its if it.has_compatible_offering(reqs)]


def satisfies_min_values(
    its: list[InstanceType], reqs: Requirements
) -> tuple[int, dict[str, int], Optional[str]]:
    """Greedy distinct-value counting over the ordered instance types
    (types.go:399-433). Returns (min needed, unsatisfiable key counts, err)."""
    if not reqs.has_min_values():
        return 0, {}, None
    min_keys = [r for r in reqs if r.min_values is not None]
    values_for_key: dict[str, set[str]] = {r.key: set() for r in min_keys}
    incompatible: dict[str, int] = {}
    for i, it in enumerate(its):
        for r in min_keys:
            values_for_key[r.key].update(it.requirements.get(r.key).values)
        incompatible = {
            k: len(v)
            for k, v in values_for_key.items()
            if len(v) < (reqs.get(k).min_values or 0)
        }
        if not incompatible:
            return i + 1, {}, None
    return len(its), incompatible, (
        f"minValues requirement is not met for label(s) {sorted(incompatible)}" if incompatible else None
    )


def truncate_instance_types(
    its: list[InstanceType],
    reqs: Requirements,
    max_items: int,
    min_values_policy_best_effort: bool = False,
) -> list[InstanceType]:
    """Order by price, truncate, verify minValues still satisfiable
    (types.go:437-455). Raises ValueError if truncation breaks minValues."""
    truncated = order_by_price(list(its), reqs)[:max_items]
    if reqs.has_min_values() and not min_values_policy_best_effort:
        _, _, err = satisfies_min_values(truncated, reqs)
        if err:
            raise ValueError(f"validating minValues, {err}")
    return truncated


def cheapest(offerings: Iterable[Offering]) -> Optional[Offering]:
    offerings = list(offerings)
    return min(offerings, key=lambda o: o.price) if offerings else None


def worst_launch_price(offerings: list[Offering], reqs: Requirements) -> float:
    """Most expensive offering of the capacity type we'd launch with;
    precedence reserved -> spot -> on-demand (types.go:587-598)."""
    for ct_reqs in (reserved_requirements(), spot_requirements(), on_demand_requirements()):
        compat = [
            o
            for o in offerings
            if reqs.is_compatible(o.requirements, l.WELL_KNOWN_LABELS)
            and ct_reqs.is_compatible(o.requirements, l.WELL_KNOWN_LABELS)
        ]
        if compat:
            return max(o.price for o in compat)
    return MAX_FLOAT
