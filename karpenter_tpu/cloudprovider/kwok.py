"""kwok-style simulated cloud provider.

Counterpart of the reference harness (kwok/cloudprovider/cloudprovider.go:
59-279): Create resolves the cheapest compatible offering and fabricates a
Node object directly into the object store; a simulated "kubelet" marks it
Ready on the next reconcile pass. This is the e2e backend the performance
suite runs against.
"""

from __future__ import annotations

import itertools
from typing import Optional

from karpenter_tpu.cloudprovider import errors
from karpenter_tpu.cloudprovider.fake import instance_types
from karpenter_tpu.faultinject import FAULT
from karpenter_tpu.cloudprovider.instancetype import RESERVATION_ID_LABEL, InstanceType, Offering
from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.node import Node, NodeSpec, NodeStatus
from karpenter_tpu.models.nodeclaim import NodeClaim
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.taints import UNREGISTERED_NO_EXECUTE_TAINT
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.state.store import ObjectStore

_instance_counter = itertools.count(1)


class KwokCloudProvider(CloudProvider):
    def __init__(self, store: ObjectStore, catalog: Optional[list[InstanceType]] = None):
        self.store = store
        self.catalog = catalog if catalog is not None else instance_types(256)

    @property
    def name(self) -> str:
        return "kwok"

    def get_instance_types(self, node_pool: NodePool) -> list[InstanceType]:
        return list(self.catalog)

    def _resolve(self, claim: NodeClaim) -> tuple[InstanceType, Offering]:
        """Cheapest compatible (type, offering) for the claim's requirements
        (kwok cloudprovider.go:59-88)."""
        reqs = Requirements.from_node_selector_requirements(claim.spec.requirements)
        # a provider only launches into a reservation the claim names
        # (the scheduler pins reservation-id at FinalizeScheduling)
        rid_pinned = reqs.has(RESERVATION_ID_LABEL)
        best: Optional[tuple[float, InstanceType, Offering]] = None
        for it in self.catalog:
            if it.requirements.intersects(reqs) is not None:
                continue
            for o in it.available_offerings():
                # a reserved offering is launchable only when the claim pins
                # its id AND a slot remains; exhausted reservations fail fast
                # with InsufficientCapacity so the lifecycle controller can
                # delete the claim and reschedule (types.go:482-487)
                if o.capacity_type == l.CAPACITY_TYPE_RESERVED and (
                    not rid_pinned or o.reservation_capacity <= 0
                ):
                    continue
                if not reqs.is_compatible(o.requirements, l.WELL_KNOWN_LABELS):
                    continue
                if best is None or o.price < best[0]:
                    best = (o.price, it, o)
        if best is None:
            raise errors.InsufficientCapacityError(
                f"no compatible instance types for {claim.name}"
            )
        return best[1], best[2]

    def create(self, claim: NodeClaim) -> NodeClaim:
        it, offering = self._resolve(claim)
        # chaos seam (mirrors fake.create): resolution first, so an
        # injected ICE carries the exact offering for the blackout cache
        try:
            FAULT.point(
                "cloud.create",
                provider="kwok",
                claim=claim.name,
                instance_type=it.name,
                zone=offering.zone,
                capacity_type=offering.capacity_type,
            )
        except errors.InsufficientCapacityError as e:
            if not e.offerings:
                e.offerings = [(it.name, offering.zone, offering.capacity_type)]
            raise
        if offering.capacity_type == l.CAPACITY_TYPE_RESERVED:
            # the provider is the source of truth for reservation usage: a
            # launch consumes a slot, so the catalog the NEXT scheduling
            # loop reads reflects it (AWS refreshes ReservationCapacity on
            # every GetInstanceTypes; types.go:482-487)
            offering.reservation_capacity -= 1
        seq = next(_instance_counter)
        provider_id = f"kwok://{claim.name}-{seq}"
        node_name = f"{claim.name}-{seq}"
        labels = dict(claim.metadata.labels)
        labels.update(
            {
                l.LABEL_INSTANCE_TYPE: it.name,
                l.LABEL_TOPOLOGY_ZONE: offering.zone,
                l.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type,
                l.LABEL_ARCH: it.requirements.get(l.LABEL_ARCH).any_value() or l.ARCH_AMD64,
                l.LABEL_OS: it.requirements.get(l.LABEL_OS).any_value() or "linux",
                l.LABEL_HOSTNAME: node_name,
            }
        )
        if offering.capacity_type == l.CAPACITY_TYPE_RESERVED:
            labels[RESERVATION_ID_LABEL] = offering.reservation_id
        claim.status.provider_id = provider_id
        claim.status.capacity = dict(it.capacity)
        claim.status.allocatable = dict(it.allocatable())
        claim.metadata.labels = labels

        node = Node(
            metadata=ObjectMeta(name=node_name, labels=dict(labels)),
            spec=NodeSpec(
                provider_id=provider_id,
                # nodes join tainted unregistered; registration removes it
                # (reference taints.go:27-40, registration.go:59-206)
                taints=[UNREGISTERED_NO_EXECUTE_TAINT] + list(claim.spec.taints),
            ),
            status=NodeStatus(
                capacity=dict(it.capacity),
                allocatable=dict(it.allocatable()),
                ready=False,
            ),
        )
        self.store.create(ObjectStore.NODES, node)
        return claim

    def delete(self, claim: NodeClaim) -> None:
        FAULT.point("cloud.delete", provider="kwok", claim=claim.name)
        node = self.store.node_by_provider_id(claim.status.provider_id)
        if node is None:
            raise errors.NodeClaimNotFoundError(claim.status.provider_id)
        # terminating a reserved instance frees its reservation slot
        labels = node.metadata.labels
        if labels.get(l.CAPACITY_TYPE_LABEL_KEY) == l.CAPACITY_TYPE_RESERVED:
            rid = labels.get(RESERVATION_ID_LABEL)
            it_name = labels.get(l.LABEL_INSTANCE_TYPE)
            for it in self.catalog:
                if it.name != it_name:
                    continue
                for o in it.offerings:
                    if (
                        o.capacity_type == l.CAPACITY_TYPE_RESERVED
                        and o.reservation_id == rid
                        and o.zone == labels.get(l.LABEL_TOPOLOGY_ZONE)
                    ):
                        o.reservation_capacity += 1
                        break
        node.metadata.finalizers = []
        self.store.delete(ObjectStore.NODES, node.name)

    def _instance_to_claim(self, node) -> NodeClaim:
        """Cloud truth is the set of fabricated nodes (the instances);
        surface each as a claim-shaped record."""
        claim = NodeClaim(metadata=ObjectMeta(name=node.name, labels=dict(node.metadata.labels)))
        claim.status.provider_id = node.spec.provider_id
        claim.status.capacity = dict(node.status.capacity)
        claim.status.allocatable = dict(node.status.allocatable)
        return claim

    def get(self, provider_id: str) -> NodeClaim:
        node = self.store.node_by_provider_id(provider_id)
        if node is None or not provider_id.startswith("kwok://"):
            raise errors.NodeClaimNotFoundError(provider_id)
        return self._instance_to_claim(node)

    def list(self) -> list[NodeClaim]:
        return [
            self._instance_to_claim(n)
            for n in self.store.nodes()
            if n.spec.provider_id.startswith("kwok://")
        ]

    def is_drifted(self, claim: NodeClaim) -> Optional[str]:
        return None

    def simulate_kubelet_ready(self) -> int:
        """Mark all not-ready kwok nodes Ready (the KWOK controller's
        heartbeat simulation). Returns how many flipped."""
        flipped = 0
        for node in self.store.nodes():
            if not node.status.ready and node.spec.provider_id.startswith("kwok://"):
                node.status.ready = True
                self.store.update(ObjectStore.NODES, node)
                flipped += 1
        return flipped
