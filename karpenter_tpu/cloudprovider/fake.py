"""In-memory test cloud provider.

Counterpart of reference pkg/cloudprovider/fake (scripted errors, synthetic
instance-type catalog) and the kwok catalog generator
(kwok/tools/gen_instance_types.go:34-120): families × cpu sizes × archs ×
zones × {spot, on-demand}, spot priced at 70% of on-demand. The catalog
shape matches what the reference scheduler benchmark uses
(fake.InstanceTypes(400), scheduling_benchmark_test.go:229) so our bench is
apples-to-apples.
"""

from __future__ import annotations

import itertools
from typing import Callable, Optional

from karpenter_tpu.cloudprovider import errors
from karpenter_tpu.faultinject import FAULT
from karpenter_tpu.cloudprovider.instancetype import InstanceType, InstanceTypeOverhead, Offering
from karpenter_tpu.cloudprovider.spi import CloudProvider, RepairPolicy
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import NodeClaim
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.objects import new_uid
from karpenter_tpu.scheduling import Operator, Requirement, Requirements
from karpenter_tpu.utils import resources as res

DEFAULT_ZONES = ("test-zone-1", "test-zone-2", "test-zone-3", "test-zone-4")
GIB = 2**30

# family -> (price multiplier, GiB memory per vCPU)
FAMILIES = {
    "c": (0.8, 2),   # compute optimized
    "s": (1.0, 4),   # standard
    "m": (1.2, 8),   # memory optimized
    "e": (0.6, 1),   # economy
}
CPU_SIZES = (1, 2, 4, 8, 16, 32, 48, 64)
ARCHS = (l.ARCH_AMD64, l.ARCH_ARM64)


def price_of(family: str, cpu: int, arch: str) -> float:
    mult, mem_ratio = FAMILIES[family]
    base = cpu * 0.035 + cpu * mem_ratio * 0.004
    if arch == l.ARCH_ARM64:
        base *= 0.85
    return round(base * mult, 5)


def new_instance_type(
    name: str,
    family: str = "s",
    cpu: int = 4,
    arch: str = l.ARCH_AMD64,
    os: str = "linux",
    zones: tuple[str, ...] = DEFAULT_ZONES,
    capacity_types: tuple[str, ...] = (l.CAPACITY_TYPE_SPOT, l.CAPACITY_TYPE_ON_DEMAND),
    extra_resources: Optional[dict[str, float]] = None,
    price_multiplier: float = 1.0,
    reservations: Optional[list[tuple[str, str, int]]] = None,
) -> InstanceType:
    """reservations: [(zone, reservation_id, capacity)] — adds reserved
    offerings (capacity-type=reserved + reservation-id requirement,
    priced 0 per the reserved->spot->on-demand launch-price precedence,
    types.go:587-598)."""
    mem_ratio = FAMILIES[family][1]
    memory = cpu * mem_ratio * GIB
    capacity = {
        res.CPU: float(cpu),
        res.MEMORY: float(memory),
        res.PODS: float(min(110, 16 + cpu * 8)),
        res.EPHEMERAL_STORAGE: 100.0 * GIB,
        **(extra_resources or {}),
    }
    od_price = price_of(family, cpu, arch) * price_multiplier
    offerings = []
    for zone, ct in itertools.product(zones, capacity_types):
        price = od_price * (0.7 if ct == l.CAPACITY_TYPE_SPOT else 1.0)
        offerings.append(
            Offering(
                requirements=Requirements(
                    Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, zone),
                    Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, ct),
                ),
                price=round(price, 5),
                available=True,
            )
        )
    for zone, rid, cap in reservations or ():
        offerings.append(
            Offering(
                requirements=Requirements(
                    Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, zone),
                    Requirement.new(
                        l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_RESERVED
                    ),
                    Requirement.new(l.RESERVATION_ID_LABEL_KEY, Operator.IN, rid),
                ),
                price=0.0,
                available=True,
                reservation_capacity=cap,
            )
        )
    capacity_types_all = tuple(capacity_types) + (
        (l.CAPACITY_TYPE_RESERVED,) if reservations else ()
    )
    requirements = Requirements(
        Requirement.new(l.LABEL_INSTANCE_TYPE, Operator.IN, name),
        Requirement.new("karpenter-tpu.sh/instance-family", Operator.IN, family),
        Requirement.new("karpenter-tpu.sh/instance-cpu", Operator.IN, str(cpu)),
        Requirement.new(l.LABEL_ARCH, Operator.IN, arch),
        Requirement.new(l.LABEL_OS, Operator.IN, os),
        Requirement.new(l.LABEL_TOPOLOGY_ZONE, Operator.IN, *zones),
        Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, *capacity_types_all),
    )
    if reservations:
        requirements.add(
            Requirement.new(
                l.RESERVATION_ID_LABEL_KEY,
                Operator.IN,
                *sorted({rid for _, rid, _ in reservations}),
            )
        )
    overhead = InstanceTypeOverhead(
        kube_reserved={res.CPU: 0.080 + cpu * 0.002, res.MEMORY: 255.0 * 2**20 + memory * 0.01},
        system_reserved={res.CPU: 0.0, res.MEMORY: 100.0 * 2**20},
        eviction_threshold={res.MEMORY: 100.0 * 2**20},
    )
    return InstanceType(name, requirements, offerings, capacity, overhead)


def instance_types(n: int = 400) -> list[InstanceType]:
    """Generate n diverse instance types (fake/instancetype.go:99 analog)."""
    out = []
    combos = itertools.cycle(
        (fam, cpu, arch)
        for cpu in CPU_SIZES
        for fam in FAMILIES
        for arch in ARCHS
    )
    seen_multiplier = 0
    for i in range(n):
        fam, cpu, arch = next(combos)
        if i and i % (len(CPU_SIZES) * len(FAMILIES) * len(ARCHS)) == 0:
            seen_multiplier += 1
        name = f"{fam}-{cpu}x-{arch}" + (f"-gen{seen_multiplier}" if seen_multiplier else "")
        out.append(
            new_instance_type(
                name, family=fam, cpu=cpu, arch=arch, price_multiplier=1.0 + 0.07 * seen_multiplier
            )
        )
    return out


class FakeCloudProvider(CloudProvider):
    """Scripted in-memory provider (fake/cloudprovider.go:51-72 analog)."""

    def __init__(self, catalog: Optional[list[InstanceType]] = None):
        self.catalog = catalog if catalog is not None else instance_types(16)
        self.created: dict[str, NodeClaim] = {}  # provider_id -> claim
        self.create_calls: list[NodeClaim] = []
        self.delete_calls: list[NodeClaim] = []
        # scripted failures
        self.next_create_err: Optional[Exception] = None
        self.create_hook: Optional[Callable[[NodeClaim], None]] = None
        self.drifted: dict[str, str] = {}  # claim name -> reason
        self._repair_policies: list[RepairPolicy] = []

    @property
    def name(self) -> str:
        return "fake"

    def get_instance_types(self, node_pool: NodePool) -> list[InstanceType]:
        return list(self.catalog)

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        self.create_calls.append(node_claim)
        if self.next_create_err is not None:
            err, self.next_create_err = self.next_create_err, None
            raise err
        if self.create_hook:
            self.create_hook(node_claim)
        reqs = Requirements.from_node_selector_requirements(node_claim.spec.requirements)
        # resolve cheapest compatible (instance type, offering)
        best: tuple[float, InstanceType, Offering] | None = None
        for it in self.catalog:
            if not it.requirements.is_compatible(reqs, l.WELL_KNOWN_LABELS):
                continue
            for o in it.available_offerings():
                if not reqs.is_compatible(o.requirements, l.WELL_KNOWN_LABELS):
                    continue
                if best is None or o.price < best[0]:
                    best = (o.price, it, o)
        if best is None:
            raise errors.InsufficientCapacityError(
                f"no compatible instance types for claim {node_claim.name}"
            )
        _, it, offering = best
        # chaos seam: fires after offering resolution so an injected ICE
        # names the REAL offering the launch would have used — the
        # lifecycle controller blackouts exactly that (it, zone, ct)
        try:
            FAULT.point(
                "cloud.create",
                provider="fake",
                claim=node_claim.name,
                instance_type=it.name,
                zone=offering.zone,
                capacity_type=offering.capacity_type,
            )
        except errors.InsufficientCapacityError as e:
            if not e.offerings:
                e.offerings = [(it.name, offering.zone, offering.capacity_type)]
            raise
        resolved = node_claim
        resolved.status.provider_id = f"fake:///{node_claim.name}/{new_uid('instance')}"
        resolved.status.capacity = dict(it.capacity)
        resolved.status.allocatable = dict(it.allocatable())
        resolved.metadata.labels.update(
            {
                l.LABEL_INSTANCE_TYPE: it.name,
                l.LABEL_TOPOLOGY_ZONE: offering.zone,
                l.CAPACITY_TYPE_LABEL_KEY: offering.capacity_type,
                l.LABEL_ARCH: it.requirements.get(l.LABEL_ARCH).any_value(),
                l.LABEL_OS: it.requirements.get(l.LABEL_OS).any_value(),
            }
        )
        self.created[resolved.status.provider_id] = resolved
        return resolved

    def delete(self, node_claim: NodeClaim) -> None:
        FAULT.point("cloud.delete", provider="fake", claim=node_claim.name)
        self.delete_calls.append(node_claim)
        pid = node_claim.status.provider_id
        if pid not in self.created:
            raise errors.NodeClaimNotFoundError(pid)
        del self.created[pid]

    def get(self, provider_id: str) -> NodeClaim:
        if provider_id not in self.created:
            raise errors.NodeClaimNotFoundError(provider_id)
        return self.created[provider_id]

    def list(self) -> list[NodeClaim]:
        return list(self.created.values())

    def is_drifted(self, node_claim: NodeClaim) -> Optional[str]:
        return self.drifted.get(node_claim.name)

    def repair_policies(self) -> list[RepairPolicy]:
        return self._repair_policies
