"""TTL'd unavailable-offerings blackout cache.

Counterpart of the reference providers' ICE cache (aws
pkg/providers/instance unavailableofferings.Cache, surfaced in kwok via
offering availability): when a launch fails with InsufficientCapacity,
the exact (instance_type, zone, capacity_type) triples the provider
attempted are blacked out for a TTL, so the very next scheduling loop
stops picking the offering that just failed instead of ping-ponging
claims into the same empty pool.

Wiring: the Manager owns one cache on the injected clock and hands it to
both the lifecycle controller (which marks on ICE) and the Provisioner
(which filters each pool's catalog through it before building the
scheduler, and folds ``generation`` into the scheduler cache signature
so a blackout change — or an expiry — rebuilds the solver's catalog).
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider.instancetype import InstanceType
from karpenter_tpu.utils.clock import Clock

# reference parity: the AWS ICE cache holds offerings out for 3 minutes
DEFAULT_BLACKOUT_TTL_SECONDS = 180.0

Key = tuple[str, str, str]  # (instance_type, zone, capacity_type)


class UnavailableOfferings:
    def __init__(
        self, clock: Optional[Clock] = None, ttl_seconds: float = DEFAULT_BLACKOUT_TTL_SECONDS
    ):
        self.clock = clock or Clock()
        self.ttl_seconds = ttl_seconds
        self._entries: dict[Key, float] = {}  # key -> expiry (clock domain)
        # bumped on every mark and on every observed expiry: the scheduler
        # cache signature folds this in, so catalog filtering can't go
        # stale in either direction
        self.generation = 0

    # -- writes ------------------------------------------------------------

    def mark(
        self,
        instance_type: str,
        zone: str,
        capacity_type: str,
        ttl_seconds: Optional[float] = None,
    ) -> None:
        ttl = self.ttl_seconds if ttl_seconds is None else ttl_seconds
        self._entries[(instance_type, zone, capacity_type)] = self.clock.now() + ttl
        self.generation += 1
        self._update_gauge()

    def mark_from_error(self, err: Exception) -> int:
        """Blackout every offering an InsufficientCapacityError names;
        returns how many were marked (an ICE without offering info — a
        fully exhausted catalog — marks nothing)."""
        marked = 0
        for entry in getattr(err, "offerings", ()) or ():
            it_name, zone, capacity_type = entry
            self.mark(it_name, zone, capacity_type)
            marked += 1
        return marked

    # -- reads -------------------------------------------------------------

    def prune(self) -> int:
        """Drop expired entries; returns how many expired. Bumps the
        generation when anything changed so cached schedulers rebuilt
        against the filtered catalog pick the offerings back up."""
        now = self.clock.now()
        expired = [k for k, exp in self._entries.items() if exp <= now]
        for k in expired:
            del self._entries[k]
        if expired:
            self.generation += 1
            self._update_gauge()
        return len(expired)

    def is_unavailable(self, instance_type: str, zone: str, capacity_type: str) -> bool:
        exp = self._entries.get((instance_type, zone, capacity_type))
        return exp is not None and exp > self.clock.now()

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self) -> list[Key]:
        return list(self._entries)

    # -- catalog filtering -------------------------------------------------

    def filter_catalog(self, its: list[InstanceType]) -> list[InstanceType]:
        """The scheduler-facing view of a pool's catalog: blacked-out
        offerings removed, instance types with no surviving offering
        dropped. The empty-cache fast path returns the input list
        untouched (the steady state pays one truthiness check)."""
        self.prune()
        if not self._entries:
            return its
        out: list[InstanceType] = []
        for it in its:
            keep = [
                o
                for o in it.offerings
                if not self.is_unavailable(it.name, o.zone, o.capacity_type)
            ]
            if len(keep) == len(it.offerings):
                out.append(it)
            elif keep:
                out.append(
                    InstanceType(
                        it.name,
                        it.requirements,
                        keep,
                        it.capacity,
                        it.overhead,
                        dra_slices=it.dra_slices,
                        dra_attribute_bindings=it.dra_attribute_bindings,
                    )
                )
            # else: every offering blacked out — the type is unlaunchable
            # for the TTL and leaves the catalog entirely
        return out

    def _update_gauge(self) -> None:
        from karpenter_tpu.utils.metrics import OFFERING_BLACKOUT

        OFFERING_BLACKOUT.values.clear()
        counts: dict[str, int] = {}
        for _, _, ct in self._entries:
            counts[ct] = counts.get(ct, 0) + 1
        for ct, n in counts.items():
            OFFERING_BLACKOUT.set(float(n), capacity_type=ct)
