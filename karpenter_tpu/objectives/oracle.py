"""Host-oracle objective scorer: the np.float32 exact mirror.

``score_opened`` recomputes ops.solver._objective_score's reduction from
the committed winner row's fetched fields — the objective-twin audit
compares it against the device-reported score (rel tolerance covers
f32 summation-order drift; a LYING scorer is off by +1.0, far outside
it). ``score_result`` scores a finished SchedulingResult from catalog
objects — the differential suite and the bench cost gate pin policy
outcomes with it.
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.objectives.scoring import min_available_price

_BIG = np.float32(1e6)


def score_opened(
    policy: str,
    base_w_open: int,
    w_open: int,
    open_mask: np.ndarray,  # [W] bool
    pods: np.ndarray,  # [W] i32
    template: np.ndarray,  # [W] i32
    its: np.ndarray,  # [W, T] bool
    price_t: np.ndarray,  # [T] f32
    n_templates: int,
) -> float:
    """The round score of the claims one fill dispatch opened — formula
    twin of ops.solver._objective_score, np.float32 end to end."""
    W = open_mask.shape[0]
    rows = np.arange(W)
    opened = (rows >= base_w_open) & (rows < w_open) & open_mask
    n_opened = np.float32(w_open - base_w_open)
    if policy == "cost_min":
        row_price = np.where(
            its, price_t[None, :].astype(np.float32), np.float32(np.inf)
        ).min(axis=1)
        return float(np.sum(np.where(opened, row_price, 0.0), dtype=np.float32))
    if policy == "frag_aware":
        landed = np.sum(np.where(opened, pods, 0), dtype=np.float32)
        return float(n_opened * _BIG - landed)
    if policy == "topo_spread":
        cnt = np.zeros(n_templates, dtype=np.float32)
        np.add.at(cnt, template[opened], np.float32(1.0))
        return float(np.sum(cnt * cnt, dtype=np.float32))
    if policy == "gang_slice":
        p_max = int(np.max(np.where(opened, pods, 0), initial=0))
        slack = np.where(opened, p_max - pods, 0).astype(np.float32)
        return float(np.sum(slack, dtype=np.float32) + n_opened)
    return 0.0


def score_result(policy: str, result) -> float:
    """Objective score of a finished solve, from decoded claim objects —
    the same formulas over the FINAL claim set (fresh claims only; the
    per-round device scores decompose over rounds for cost/frag/gang,
    and the suite uses this as the cross-engine comparator)."""
    claims = list(result.claims)
    n = np.float32(len(claims))
    if policy == "cost_min":
        total = np.float32(0.0)
        for c in claims:
            total = np.float32(
                total
                + np.float32(
                    min(
                        (min_available_price(it) for it in c.instance_types),
                        default=float("inf"),
                    )
                )
            )
        return float(total)
    if policy == "frag_aware":
        landed = np.float32(sum(len(c.pods) for c in claims))
        return float(n * _BIG - landed)
    if policy == "topo_spread":
        occ: dict = {}
        for c in claims:
            key = c.template.nodepool_name
            occ[key] = occ.get(key, 0) + 1
        return float(np.sum(np.asarray(list(occ.values()), dtype=np.float32) ** 2))
    if policy == "gang_slice":
        if not claims:
            return 0.0
        p_max = max(len(c.pods) for c in claims)
        return float(
            np.float32(sum(p_max - len(c.pods) for c in claims)) + n
        )
    return 0.0


def total_price_per_hour(result) -> float:
    """Σ cheapest member price over fresh claims — the bench stage's
    reported cost under each policy (host_scheduler's total_price uses
    requirement-aware pricing; this floor-based twin is what cost_min
    provably minimizes)."""
    total = 0.0
    for c in result.claims:
        p = min(
            (min_available_price(it) for it in c.instance_types),
            default=float("inf"),
        )
        if np.isfinite(p):
            total += p
    return total
