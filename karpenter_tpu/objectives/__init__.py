"""Pluggable batched placement objectives (ISSUE 19).

A placement objective is a template *rank* — a [G] i32 column on
``ops.solver.Templates`` that tier-3 opens in ascending order — plus a
*score*, a device-evaluated f32 the K-variant fill dispatch minimizes
over objective-perturbed rank variants riding the dp axis, and that
consolidation reuses to order candidates. ``registry`` owns the policy
table and the env/NodePool selection (quarantine-aware: a tripped
"objective" guard path falls back to ``lexical``); ``scoring`` builds
the host-side canonical rank per policy; ``oracle`` is the np.float32
exact-mirror scorer the objective-twin audit and the differential tests
pin the device scores against.
"""

from karpenter_tpu.objectives.registry import (  # noqa: F401
    ENV_OBJECTIVE,
    ENV_OBJECTIVE_K,
    POLICIES,
    active_policy,
    objective_id,
    resolve_policy,
    variant_count,
)
