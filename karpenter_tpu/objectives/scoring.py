"""Host-side objective rank construction.

Each policy turns the template list into a [G] i32 *canonical rank* —
the order tier-3 tries templates in (``ops.solver._pick_template``).
Ranks are data, not code: the device kernels stay policy-agnostic and
the rank column rides ``Templates.rank`` as a plain jit argument, so
switching policies never recompiles beyond the one-time None->array
retrace.

The K-variant fill dispatch additionally fans ``variant_ranks`` over the
dp axis: variant 0 is the canonical rank, variant k promotes the k-th
best template to the front — a one-move perturbation whose realized
score (computed on device from the actual packing) can beat the greedy
canonical order, e.g. when opening one bigger/cheaper-per-pod node
absorbs a whole chunk group.
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.models import labels as l


def min_available_price(it) -> float:
    """Cheapest available offering of one instance type, +inf when the
    catalog carries no priced available offering (the same "unknown
    prices never look cheap" rule as disruption's candidates fix)."""
    prices = [o.price for o in it.offerings if o.available]
    return float(min(prices)) if prices else float("inf")


def template_price(template) -> float:
    """Cheapest member instance type — the template's price floor."""
    prices = [min_available_price(it) for it in template.instance_types]
    return min(prices) if prices else float("inf")


def _zone_signature(template) -> tuple:
    reqs = template.requirements
    if reqs.has(l.LABEL_TOPOLOGY_ZONE):
        return tuple(sorted(reqs.get(l.LABEL_TOPOLOGY_ZONE).values))
    return ()


def _frag_size(template) -> float:
    """Best-fit proxy: the smallest member node's total allocatable —
    small nodes leave less leftover when a kind doesn't fill them."""
    sizes = [
        sum(it.allocatable().values()) for it in template.instance_types
    ]
    return min(sizes) if sizes else float("inf")


def _gang_capacity(template) -> int:
    """Per-host slice capacity for a unit pod — the gang oracle's
    closed-form shape math (slice_capacity / hosts_needed): templates
    with bigger per-host blocks need fewer hosts per gang."""
    from karpenter_tpu.gang import oracle

    return oracle.slice_capacity(
        template.instance_types,
        template.requirements,
        dict(template.daemon_requests or {}),
        {"cpu": 1.0},
    )


def _rank_from_keys(keys: list) -> np.ndarray:
    order = sorted(range(len(keys)), key=lambda g: (keys[g], g))
    rank = np.zeros(len(keys), dtype=np.int32)
    for pos, g in enumerate(order):
        rank[g] = pos
    return rank


def canonical_rank(policy: str, templates: list) -> np.ndarray:
    """[G] i32 — the policy's template order (0 = tried first). Every
    key sorts ascending with the original (weight) index as tie-break,
    so a policy that cannot distinguish two templates preserves today's
    order between them."""
    G = len(templates)
    if policy == "lexical":
        return np.arange(G, dtype=np.int32)
    if policy == "cost_min":
        keys: list = [template_price(t) for t in templates]
    elif policy == "frag_aware":
        keys = [_frag_size(t) for t in templates]
    elif policy == "topo_spread":
        # round-robin over distinct zone signatures: the g-th template of
        # a zone group ranks behind the g-th of every other group, so the
        # try-order cycles zones instead of draining one
        occ: dict = {}
        keys = []
        for t in templates:
            sig = _zone_signature(t)
            keys.append(occ.get(sig, 0))
            occ[sig] = occ.get(sig, 0) + 1
    elif policy == "gang_slice":
        # descending per-host capacity = ascending hosts-per-gang
        keys = [-_gang_capacity(t) for t in templates]
    else:
        raise ValueError(f"unknown placement objective {policy!r}")
    return _rank_from_keys(keys)


def variant_ranks(rank: np.ndarray, kv: int) -> np.ndarray:
    """[KV, G] i32 — one-move perturbations of the canonical rank: row 0
    is canonical, row k promotes the template ranked k to the front
    (rank min-1, everything else untouched). KV clamps to G — there are
    only G distinct promotions."""
    G = int(rank.shape[0])
    kv = max(1, min(kv, G))
    order = np.argsort(rank, kind="stable")
    out = np.tile(rank[None, :], (kv, 1)).astype(np.int32)
    front = np.int32(rank.min() - 1)
    for k in range(1, kv):
        out[k, order[k]] = front
    return out
