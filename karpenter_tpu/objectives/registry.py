"""Objective registry: the policy table and the selection rules.

Selection precedence (per solve, nothing cached at import so tests flip
with monkeypatch.setenv):

  1. an explicit NodePool ``placement_objective`` (threaded through the
     scheduler's ``objective=`` kwarg by the provisioner),
  2. ``KTPU_OBJECTIVE``,
  3. ``lexical`` — the legacy fewest-pods/earliest-slot tie-break with
     weight-ordered templates, pinned bit-identical to the pre-objective
     solver (no rank column is materialized at all).

A tripped "objective" quarantine (the objective-twin audit caught a
lying scorer) routes every policy back onto ``lexical`` for the TTL —
the scores are untrusted, the structural solve is not.

``KTPU_OBJECTIVE_K`` caps how many objective-perturbed rank variants the
K-variant fill dispatch fans over the dp axis (0 = size to the mesh's dp
extent; always clamped to ops.solver.VARIANT_MAX so the verdict word's
winner byte stays addressable).
"""

from __future__ import annotations

import os
from typing import Optional

ENV_OBJECTIVE = "KTPU_OBJECTIVE"
ENV_OBJECTIVE_K = "KTPU_OBJECTIVE_K"

#: policy name -> objective id, in ops.solver OBJ_* order
POLICIES = ("lexical", "cost_min", "frag_aware", "topo_spread", "gang_slice")


def objective_id(policy: str) -> int:
    """The static jit id ops.solver compiles the score formula under."""
    return POLICIES.index(policy)


def resolve_policy(nodepool_policy: Optional[str] = None) -> str:
    """NodePool > env > lexical; unknown names fall back to lexical (a
    typo'd policy must not change packing silently — lexical IS today's
    behavior)."""
    for cand in (nodepool_policy, os.environ.get(ENV_OBJECTIVE)):
        if cand and cand in POLICIES:
            return cand
    return "lexical"


def active_policy(nodepool_policy: Optional[str] = None) -> str:
    """The policy actually applied this solve: the resolved policy, or
    lexical while the "objective" guard path is quarantined."""
    policy = resolve_policy(nodepool_policy)
    if policy == "lexical":
        return policy
    from karpenter_tpu.guard.quarantine import QUARANTINE

    if QUARANTINE.active("objective"):
        return "lexical"
    return policy


def variant_count(dp_rows: int) -> int:
    """How many rank variants to fan out: KTPU_OBJECTIVE_K, defaulting to
    the dp extent (padded-idle dp rows are free variant capacity), never
    below 1 nor above the verdict word's addressable VARIANT_MAX."""
    from karpenter_tpu.ops.solver import VARIANT_MAX

    raw = os.environ.get(ENV_OBJECTIVE_K, "")
    try:
        k = int(raw) if raw else 0
    except ValueError:
        k = 0
    if k <= 0:
        k = max(dp_rows, 1)
    return max(1, min(k, VARIANT_MAX))
