"""The operator runtime: wiring + the steady-state loop.

Counterpart of reference pkg/operator + kwok/main.go:29-50: construct the
store, cloud provider (with the overlay decorator), controller manager and
the periodic loops, then run. Single process, no leader election — the
solver is stateless so HA is a deployment concern, not a code one
(SURVEY.md §2.9).

`python -m karpenter_tpu.operator` runs a self-contained kwok demo:
provisions a workload, prints the metrics exposition, consolidates after
the workload shrinks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# NOTE: jax-touching modules (manager -> scheduler -> solver) are imported
# lazily inside Operator.new so entry points can guard accelerator init
# first (a hung TPU tunnel would otherwise stall at import time).
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock, FakeClock
from karpenter_tpu.utils.options import Options


@dataclass
class Operator:
    """Everything a provider binary wires together (operator.go:126).

    All Options are consumed: batch windows and the disruption poll pace
    the Manager loops, solve_timeout_seconds bounds every Solve
    (provisioner.go:415), preference/minValues policies and the feature
    gates select scheduler behavior.
    """

    store: ObjectStore
    cloud: object
    manager: object
    options: Options = field(default_factory=Options)
    elector: object = None  # LeaderElector when leader_elect is on
    health_server: object = None
    health_port: int = 0

    @staticmethod
    def new(
        clock: Optional[Clock] = None,
        catalog=None,
        options: Optional[Options] = None,
    ) -> "Operator":
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.cloudprovider.overlay import OverlayCloudProvider
        from karpenter_tpu.controllers.manager import Manager

        clock = clock or Clock()
        options = options or Options.from_env()
        store = ObjectStore(clock)
        inner = KwokCloudProvider(store, catalog=catalog)
        from karpenter_tpu.cloudprovider.metrics import MetricsCloudProvider

        # decorator chain mirrors kwok/main.go:36-37 + the metrics
        # decorator (cloudprovider/metrics/cloudprovider.go) — the seam a
        # remote-solver shim would occupy
        cloud = MetricsCloudProvider(OverlayCloudProvider(inner, store))
        manager = Manager(store, cloud, clock, options=options)
        op = Operator(store=store, cloud=cloud, manager=manager, options=options)
        if options.enable_profiling:
            # --enable-profiling turns on the span tracer alongside the
            # pprof handlers; KTPU_TRACE_DIR enables it independently
            # (tracing/tracer.py reads the env at import)
            from karpenter_tpu.tracing.tracer import TRACER

            TRACER.enable()
            # ... and the compile observatory: jit compiles attributed to
            # named kernels, retrace-storm detection, cost analysis into
            # the round ledger (/debug/rounds)
            from karpenter_tpu.obs import observatory

            observatory.enable()
        if options.leader_elect:
            import uuid

            from karpenter_tpu.utils.runtime import LeaderElector

            op.elector = LeaderElector(store, identity=f"op-{uuid.uuid4().hex[:8]}", clock=clock)
        if options.health_probe_port:
            from karpenter_tpu.utils.runtime import HealthConfig, serve_health

            op.health_server, op.health_port = serve_health(
                HealthConfig(
                    # readiness = state convergence, the reference's cache-
                    # sync + CRD-presence gate (operator.go:225-243); the
                    # in-memory store IS the CRD layer here
                    ready_checks={"cluster-synced": manager.cluster.synced},
                    enable_profiling=options.enable_profiling,
                ),
                port=options.health_probe_port if options.health_probe_port > 0 else 0,
            )
        return op

    def tick(self) -> None:
        """One steady-state iteration: reconcile work, a disruption poll,
        housekeeping, and harness binding. With leader election on, a
        non-leader tick only runs the election round — reconcilers stay
        idle until the lease is held (operator.go:171-181)."""
        from karpenter_tpu.controllers.manager import KubeSchedulerSim
        from karpenter_tpu.tracing.tracer import TRACER

        if self.elector is not None and not self.elector.try_acquire_or_renew():
            return
        # one trace per steady-state tick when tracing is on: provisioning,
        # disruption, maintenance and binding all nest under it
        with TRACER.span("operator.tick"):
            self.manager.run_until_idle()
            self.manager.maybe_run_disruption()  # paced by disruption_poll_seconds
            self.manager.run_maintenance()
            KubeSchedulerSim(self.store, self.manager.cluster).bind_pending()

    def shutdown(self) -> None:
        if self.elector is not None:
            self.elector.release()
        if self.health_server is not None:
            self.health_server.shutdown()


def _demo() -> None:
    from karpenter_tpu.models.nodepool import Budget, NodePool
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.utils import metrics

    from karpenter_tpu.models import labels as l

    clock = FakeClock()
    op = Operator.new(clock=clock)
    pool = NodePool()
    pool.metadata.name = "default"
    pool.spec.disruption.budgets = [Budget(nodes="100%")]
    # on-demand so consolidation may replace (spot->spot is gated off)
    pool.spec.template.spec.requirements = [
        {
            "key": l.CAPACITY_TYPE_LABEL_KEY,
            "operator": "In",
            "values": [l.CAPACITY_TYPE_ON_DEMAND],
        }
    ]
    op.store.create(ObjectStore.NODEPOOLS, pool)

    print("== provisioning 60 pods ==")
    for i in range(60):
        op.store.create(ObjectStore.PODS, make_pod(f"demo-{i}", cpu=0.5, memory="512Mi"))
    op.tick()
    op.cloud.unwrapped.simulate_kubelet_ready()
    op.tick()
    print(f"nodes: {len(op.store.nodes())}, claims: {len(op.store.nodeclaims())}, "
          f"bound: {sum(1 for p in op.store.pods() if p.spec.node_name)}/60")

    print("== workload shrinks to 10 pods; consolidating ==")
    for pod in list(op.store.pods()):
        if int(pod.name.split("-")[1]) >= 10:
            pod.status.phase = "Succeeded"
            op.store.update(ObjectStore.PODS, pod)
            op.store.delete(ObjectStore.PODS, pod.name)
    clock.step(60.0)
    for _ in range(8):
        op.tick()
        op.cloud.unwrapped.simulate_kubelet_ready()
        clock.step(20.0)
    op.tick()
    cpu = sum(n.status.capacity["cpu"] for n in op.store.nodes())
    print(f"nodes: {len(op.store.nodes())} ({cpu:.0f} cpu), "
          f"bound: {sum(1 for p in op.store.pods() if p.spec.node_name)}/10")
    print("== metrics ==")
    for line in metrics.REGISTRY.expose().splitlines():
        if line.startswith("#") or "_bucket{" in line:
            continue  # demo summary: skip comments + per-bucket series
        value = line.rsplit(" ", 1)[-1]
        if value not in ("0.0", "0"):
            print(" ", line)


if __name__ == "__main__":
    from karpenter_tpu.utils.accel import force_cpu_if_unavailable

    fallback = force_cpu_if_unavailable()
    if fallback:
        print(f"(accelerator unusable: {fallback}; demo on CPU)")
    _demo()
