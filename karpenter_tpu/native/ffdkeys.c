/* _ktpu_native: C fast paths for the scheduler's host-side hot loops.
 *
 * The solve pipeline's remaining host cost at the 100k-pod north star is
 * pure Python loop overhead: one pass over every pod object reading the
 * cached (content-sig, FFD-size) tuple into numpy buffers
 * (scheduler._encode / host_scheduler.ffd_sort). This module does that
 * pass with direct C-API calls — no bytecode dispatch, no boxing — and
 * falls back to the Python implementation for any pod missing the cache
 * (the caller re-runs those through pod_ffd_key).
 *
 * Built lazily by karpenter_tpu/native/__init__.py with the baked-in gcc;
 * everything degrades to the pure-Python loop when the build is
 * unavailable. (The reference is pure Go — SURVEY.md notes the only
 * native-code obligation is the solver runtime itself; this is that.)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

/* ffd_keys(pods, sig_buf, size_buf) -> n_missing
 *
 * pods:     list of Pod objects
 * sig_buf:  writable int64 buffer, len >= len(pods)
 * size_buf: writable float64 buffer, len >= len(pods)
 *
 * For every pod with a cached `_ktpu_ffd == (int, float)` in its
 * __dict__, writes sig/size; positions without a cache entry are left
 * untouched and counted (caller fills them via the Python path, which
 * also populates the cache for next time).
 */
static PyObject *
ffd_keys(PyObject *self, PyObject *args)
{
    PyObject *pods;
    Py_buffer sig_buf, size_buf;
    if (!PyArg_ParseTuple(args, "O!w*w*", &PyList_Type, &pods, &sig_buf, &size_buf))
        return NULL;

    Py_ssize_t n = PyList_GET_SIZE(pods);
    if (sig_buf.len < (Py_ssize_t)(n * sizeof(long long)) ||
        size_buf.len < (Py_ssize_t)(n * sizeof(double))) {
        PyBuffer_Release(&sig_buf);
        PyBuffer_Release(&size_buf);
        PyErr_SetString(PyExc_ValueError, "output buffers too small");
        return NULL;
    }
    long long *sig = (long long *)sig_buf.buf;
    double *size = (double *)size_buf.buf;

    Py_ssize_t missing = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pod = PyList_GET_ITEM(pods, i);        /* borrowed */
        PyObject **dictp = _PyObject_GetDictPtr(pod);
        PyObject *entry = NULL;
        if (dictp != NULL && *dictp != NULL) {
            entry = PyDict_GetItemString(*dictp, "_ktpu_ffd"); /* borrowed */
        }
        if (entry == NULL || !PyTuple_Check(entry) || PyTuple_GET_SIZE(entry) != 2) {
            missing++;
            sig[i] = -1; /* sentinel: caller fills via the Python path */
            continue;
        }
        PyObject *s = PyTuple_GET_ITEM(entry, 0);
        PyObject *z = PyTuple_GET_ITEM(entry, 1);
        long long sv = PyLong_AsLongLong(s);
        double zv = PyFloat_AsDouble(z);
        if ((sv == -1 || zv == -1.0) && PyErr_Occurred()) {
            PyErr_Clear();
            missing++;
            sig[i] = -1;
            continue;
        }
        sig[i] = sv;
        size[i] = zv;
    }
    PyBuffer_Release(&sig_buf);
    PyBuffer_Release(&size_buf);
    return PyLong_FromSsize_t(missing);
}

static PyMethodDef Methods[] = {
    {"ffd_keys", ffd_keys, METH_VARARGS,
     "Gather cached (sig, size) FFD keys from pods into numpy buffers."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_ktpu_native", NULL, -1, Methods,
};

PyMODINIT_FUNC
PyInit__ktpu_native(void)
{
    return PyModule_Create(&moduledef);
}
