"""Native (C) fast paths, lazily built with the system toolchain.

`ffd_keys` is the C gather for the encode hot loop; `None` when the
extension is unavailable (missing compiler, failed build) — every caller
keeps a pure-Python fallback, so this is strictly an accelerator.
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

ffd_keys = None


def _so_path() -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(os.path.dirname(__file__), f"_ktpu_native{suffix}")


def _build() -> bool:
    src = os.path.join(os.path.dirname(__file__), "ffdkeys.c")
    include = sysconfig.get_paths()["include"]
    cc = os.environ.get("CC", "gcc")
    try:
        subprocess.run(
            [cc, "-O2", "-shared", "-fPIC", f"-I{include}", src, "-o", _so_path()],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:  # noqa: BLE001 — any build failure -> Python fallback
        return False


def _load() -> None:
    global ffd_keys
    if not os.path.exists(_so_path()) and not _build():
        return
    try:
        sys.path.insert(0, os.path.dirname(__file__))
        try:
            import _ktpu_native  # noqa: PLC0415
        finally:
            sys.path.pop(0)
        ffd_keys = _ktpu_native.ffd_keys
    except Exception:  # noqa: BLE001
        ffd_keys = None


if os.environ.get("KTPU_DISABLE_NATIVE") != "1":
    _load()
