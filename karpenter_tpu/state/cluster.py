"""The in-memory cluster state mirror.

Counterpart of reference pkg/controllers/state (cluster.go:54-604,
statenode.go:126-513): StateNode fuses a Node with its NodeClaim; Cluster
tracks bindings, per-nodepool usage, nomination TTLs, and the
marked-for-deletion set that guards against double-launches during
disruption.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.models.node import Node
from karpenter_tpu.models.nodeclaim import (
    COND_INITIALIZED,
    COND_REGISTERED,
    NodeClaim,
)
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import Clock

NOMINATION_WINDOW_SECONDS = 20.0  # reference nomination TTL ballpark


@dataclass
class StateNode:
    """Node + NodeClaim fusion (statenode.go:126)."""

    node: Optional[Node] = None
    node_claim: Optional[NodeClaim] = None
    pods: dict[str, Pod] = field(default_factory=dict)  # bound pods by uid
    marked_for_deletion: bool = False
    nominated_until: float = 0.0

    @property
    def name(self) -> str:
        if self.node is not None:
            return self.node.name
        return self.node_claim.status.node_name or self.node_claim.name if self.node_claim else ""

    @property
    def provider_id(self) -> str:
        if self.node is not None and self.node.spec.provider_id:
            return self.node.spec.provider_id
        return self.node_claim.status.provider_id if self.node_claim else ""

    @property
    def nodepool_name(self) -> Optional[str]:
        obj = self.node or self.node_claim
        return obj.metadata.labels.get(l.NODEPOOL_LABEL_KEY) if obj else None

    @property
    def registered(self) -> bool:
        return self.node_claim is None or self.node_claim.conditions.is_true(COND_REGISTERED)

    @property
    def initialized(self) -> bool:
        return self.node_claim is None or self.node_claim.conditions.is_true(COND_INITIALIZED)

    @property
    def managed(self) -> bool:
        return self.node_claim is not None

    def capacity(self) -> dict[str, float]:
        if self.node is not None and self.node.status.capacity:
            return self.node.status.capacity
        return self.node_claim.status.capacity if self.node_claim else {}

    def allocatable(self) -> dict[str, float]:
        if self.node is not None and self.node.status.allocatable:
            return self.node.status.allocatable
        return self.node_claim.status.allocatable if self.node_claim else {}

    def pod_requests(self) -> dict[str, float]:
        return res.merge(*(p.total_requests() for p in self.pods.values())) if self.pods else {}

    def available(self) -> dict[str, float]:
        """allocatable - pod requests (statenode.go:359-397)."""
        return res.subtract(self.allocatable(), self.pod_requests())

    def is_disrupted(self) -> bool:
        node = self.node
        return node is not None and any(
            t.match(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints
        )

    def nominate(self, now: float) -> None:
        self.nominated_until = now + NOMINATION_WINDOW_SECONDS

    def is_nominated(self, now: float) -> bool:
        return self.nominated_until > now


class Cluster:
    """The mirror (cluster.go:54-104). Updated synchronously from ObjectStore
    watch events by the informer wiring in controllers/manager.py."""

    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self._by_provider_id: dict[str, StateNode] = {}
        self._claim_to_provider_id: dict[str, str] = {}
        self._node_name_to_provider_id: dict[str, str] = {}
        self._bindings: dict[str, str] = {}  # pod uid -> node name
        self._unsynced_claims: set[str] = set()
        self._consolidation_state = 0
        # pod uid -> (target name, nomination expiry): scheduling decisions
        # from prior passes (cluster.go:472 MarkPodSchedulingDecisions) so
        # the provisioner doesn't double-provision for in-flight claims
        self._pod_nominations: dict[str, tuple[str, float]] = {}
        # node name -> virtual buffer pods placed there by the last solve
        # (cluster.go UpdateBufferPodCounts): the emptiness path must not
        # delete nodes that merely host headroom. None = no provisioning
        # pass observed yet (e.g. fresh restart): with buffers present,
        # emptiness can't tell headroom nodes apart and must defer
        self.buffer_pod_counts: "dict[str, int] | None" = None

    # -- sync gate (cluster.go:134) -----------------------------------------

    def synced(self) -> bool:
        """All launched claims have their cloud state reflected."""
        return not self._unsynced_claims

    # -- updates (informer entry points) -------------------------------------

    def update_nodeclaim(self, claim: NodeClaim) -> None:
        pid = claim.status.provider_id
        if not pid:
            # created but not launched yet
            self._unsynced_claims.add(claim.name)
            return
        self._unsynced_claims.discard(claim.name)
        old_pid = self._claim_to_provider_id.get(claim.name)
        if old_pid and old_pid != pid:
            self._by_provider_id.pop(old_pid, None)
        self._claim_to_provider_id[claim.name] = pid
        sn = self._by_provider_id.setdefault(pid, StateNode())
        sn.node_claim = claim

    def delete_nodeclaim(self, claim_name: str) -> None:
        self._unsynced_claims.discard(claim_name)
        pid = self._claim_to_provider_id.pop(claim_name, None)
        if pid is None:
            return
        sn = self._by_provider_id.get(pid)
        if sn is not None:
            sn.node_claim = None
            if sn.node is None:
                del self._by_provider_id[pid]

    def update_node(self, node: Node) -> None:
        pid = node.spec.provider_id or f"node://{node.name}"
        old_pid = self._node_name_to_provider_id.get(node.name)
        if old_pid and old_pid != pid:
            self._by_provider_id.pop(old_pid, None)
        self._node_name_to_provider_id[node.name] = pid
        sn = self._by_provider_id.setdefault(pid, StateNode())
        sn.node = node

    def delete_node(self, node_name: str) -> None:
        pid = self._node_name_to_provider_id.pop(node_name, None)
        if pid is None:
            return
        sn = self._by_provider_id.get(pid)
        if sn is not None:
            sn.node = None
            if sn.node_claim is None:
                del self._by_provider_id[pid]

    def update_pod(self, pod: Pod) -> None:
        node_name = pod.spec.node_name
        old = self._bindings.get(pod.uid)
        if old and old != node_name:
            old_sn = self.node_by_name(old)
            if old_sn is not None:
                old_sn.pods.pop(pod.uid, None)
        if not node_name or pod.is_terminal():
            self._bindings.pop(pod.uid, None)
            sn = self.node_by_name(node_name) if node_name else None
            if sn is not None:
                sn.pods.pop(pod.uid, None)
            return
        self._pod_nominations.pop(pod.uid, None)  # bound: nomination fulfilled
        newly_bound = old != node_name
        self._bindings[pod.uid] = node_name
        sn = self.node_by_name(node_name)
        if sn is not None:
            sn.pods[pod.uid] = pod
            # consolidateAfter idle timing (podevents controller analog)
            if newly_bound and sn.node_claim is not None:
                sn.node_claim.status.last_pod_event_time = self.clock.now()

    def delete_pod(self, pod: Pod) -> None:
        node_name = self._bindings.pop(pod.uid, None)
        if node_name:
            sn = self.node_by_name(node_name)
            if sn is not None:
                sn.pods.pop(pod.uid, None)
                if sn.node_claim is not None:
                    sn.node_claim.status.last_pod_event_time = self.clock.now()

    # -- reads ----------------------------------------------------------------

    def nodes(self) -> list[StateNode]:
        return list(self._by_provider_id.values())

    def node_by_provider_id(self, pid: str) -> Optional[StateNode]:
        return self._by_provider_id.get(pid)

    def node_by_name(self, name: str) -> Optional[StateNode]:
        pid = self._node_name_to_provider_id.get(name)
        if pid is not None:
            return self._by_provider_id.get(pid)
        # fall back to claims whose node hasn't joined yet
        for sn in self._by_provider_id.values():
            if sn.name == name:
                return sn
        return None

    def nodepool_usage(self, nodepool: str) -> dict[str, float]:
        """Aggregate capacity per nodepool incl. the synthetic 'nodes'
        resource (for NodePool.Limits)."""
        usage: dict[str, float] = {"nodes": 0.0}
        for sn in self._by_provider_id.values():
            if sn.nodepool_name == nodepool and not sn.marked_for_deletion:
                usage = res.merge(usage, sn.capacity())
                usage["nodes"] += 1.0
        return usage

    # -- disruption coordination (cluster.go:591-604) -------------------------

    def mark_for_deletion(self, *provider_ids: str) -> None:
        for pid in provider_ids:
            sn = self._by_provider_id.get(pid)
            if sn is not None:
                sn.marked_for_deletion = True

    def unmark_for_deletion(self, *provider_ids: str) -> None:
        for pid in provider_ids:
            sn = self._by_provider_id.get(pid)
            if sn is not None:
                sn.marked_for_deletion = False

    def nominate_pod(self, pod_uid: str, target: str, window: float = 120.0) -> None:
        self._pod_nominations[pod_uid] = (target, self.clock.now() + window)

    def pod_nomination(self, pod_uid: str) -> Optional[str]:
        entry = self._pod_nominations.get(pod_uid)
        if entry is None:
            return None
        target, expiry = entry
        if expiry <= self.clock.now():
            del self._pod_nominations[pod_uid]
            return None
        return target

    def clear_pod_nomination(self, pod_uid: str) -> None:
        self._pod_nominations.pop(pod_uid, None)

    def nomination_targets(self) -> set[str]:
        """Names (claims or nodes) with live pod nominations — capacity that
        pending pods are counting on and disruption must not take."""
        now = self.clock.now()
        return {t for t, exp in self._pod_nominations.values() if exp > now}

    def clear_nominations_for(self, target: str) -> None:
        """Drop nominations to a claim/node that went away so its pods
        become provisionable again immediately."""
        self._pod_nominations = {
            uid: (t, exp) for uid, (t, exp) in self._pod_nominations.items() if t != target
        }

    def mark_unconsolidated(self) -> int:
        self._consolidation_state += 1
        return self._consolidation_state

    def consolidation_state(self) -> int:
        return self._consolidation_state
