"""Incremental cluster-cost ledger and launch-health tracking.

Counterparts of reference pkg/state/cost (cost.go:68-315) and
pkg/state/nodepoolhealth (tracker.go:32-145 with pkg/utils/ringbuffer).
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Optional


class ClusterCost:
    """Per-nodepool hourly price ledger, updated on claim events."""

    def __init__(self) -> None:
        self._by_pool: dict[str, dict[str, float]] = defaultdict(dict)  # pool -> claim -> price

    def set_claim(self, pool: str, claim_name: str, price: float) -> None:
        self._by_pool[pool][claim_name] = price

    def remove_claim(self, pool: Optional[str], claim_name: str) -> None:
        if pool is None:
            for claims in self._by_pool.values():
                claims.pop(claim_name, None)
            return
        self._by_pool[pool].pop(claim_name, None)

    def pool_cost(self, pool: str) -> float:
        """GetNodepoolCost (cost.go:315) — feeds Balanced denominators."""
        return sum(self._by_pool.get(pool, {}).values())

    def total(self) -> float:
        return sum(self.pool_cost(p) for p in self._by_pool)


RING_CAPACITY = 4  # tracker.go BufferSize
FAILURE_THRESHOLD = 0.5  # tracker.go ThresholdFalse


class NodePoolHealth:
    """Fixed-capacity ring buffer of launch outcomes per pool
    (tracker.go:32-145): a pool goes unhealthy when failures reach 50% of
    the buffer SIZE (not of the recorded count — two failures flip a
    4-slot buffer even before it fills)."""

    def __init__(self, capacity: int = RING_CAPACITY):
        self.capacity = capacity
        self._rings: dict[str, deque[bool]] = {}

    def record(self, pool: str, success: bool) -> None:
        ring = self._rings.setdefault(pool, deque(maxlen=self.capacity))
        ring.append(success)

    def healthy(self, pool: str) -> Optional[bool]:
        """None with no data; False when failures / capacity >= threshold."""
        ring = self._rings.get(pool)
        if not ring:
            return None
        failures = sum(1 for ok in ring if not ok)
        return failures / self.capacity < FAILURE_THRESHOLD
