"""In-memory object store — the kube-apiserver equivalent.

Typed buckets with resource-version bumps and synchronous watch callbacks.
Controllers register interest per kind; the Manager (controllers/manager.py)
drains reconcile queues until the system is idle, which is the in-process
analog of controller-runtime's event-driven reconcile loops.

Deletion follows Kubernetes semantics: delete() sets deletion_timestamp and
the object lingers while finalizers remain; remove_finalizer() drops it for
real once the list empties.
"""

from __future__ import annotations

import enum
from collections import defaultdict
from typing import Callable, Iterable, Optional, TypeVar

from karpenter_tpu.faultinject import FAULT
from karpenter_tpu.utils.clock import Clock

T = TypeVar("T")


class EventType(str, enum.Enum):
    ADDED = "Added"
    MODIFIED = "Modified"
    DELETED = "Deleted"


class ObjectStore:
    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self._buckets: dict[str, dict[str, object]] = defaultdict(dict)  # kind -> name -> obj
        self._watchers: dict[str, list[Callable]] = defaultdict(list)
        self._rv = 0
        # secondary index: provider_id -> node name (hot lookup for the
        # lifecycle controllers; avoids O(nodes x claims) scans)
        self._node_by_pid: dict[str, str] = {}

    def _index(self, kind: str, obj) -> None:
        if kind == self.NODES and getattr(obj.spec, "provider_id", ""):
            self._node_by_pid[obj.spec.provider_id] = obj.metadata.name

    def _unindex(self, kind: str, obj) -> None:
        if kind == self.NODES and getattr(obj.spec, "provider_id", ""):
            if self._node_by_pid.get(obj.spec.provider_id) == obj.metadata.name:
                del self._node_by_pid[obj.spec.provider_id]

    def node_by_provider_id(self, provider_id: str):
        name = self._node_by_pid.get(provider_id)
        return self._buckets[self.NODES].get(name) if name else None

    # -- watch -------------------------------------------------------------

    def watch(self, kind: str, fn: Callable[[EventType, object], None]) -> None:
        self._watchers[kind].append(fn)

    def _notify(self, kind: str, event: EventType, obj) -> None:
        for fn in self._watchers[kind]:
            fn(event, obj)

    # -- CRUD --------------------------------------------------------------

    def create(self, kind: str, obj) -> object:
        name = obj.metadata.name
        # apiserver fault seams: fired BEFORE any mutation, so an injected
        # "API error" is atomic — a failed write leaves no partial state
        # (exactly what a real 429/503 from the apiserver guarantees)
        FAULT.point("api.create", kind=kind, name=name)
        if name in self._buckets[kind]:
            raise ValueError(f"{kind}/{name} already exists")
        self._rv += 1
        obj.metadata.resource_version = self._rv
        # stamp from the injected clock: ObjectMeta's default is wall time,
        # which would mix clock domains under FakeClock (liveness TTL math)
        obj.metadata.creation_timestamp = self.clock.now()
        self._buckets[kind][name] = obj
        self._index(kind, obj)
        self._notify(kind, EventType.ADDED, obj)
        return obj

    def update(self, kind: str, obj) -> object:
        name = obj.metadata.name
        FAULT.point("api.patch", kind=kind, name=name)
        if name not in self._buckets[kind]:
            raise KeyError(f"{kind}/{name} not found")
        self._rv += 1
        obj.metadata.resource_version = self._rv
        self._buckets[kind][name] = obj
        self._index(kind, obj)
        self._notify(kind, EventType.MODIFIED, obj)
        return obj

    def get(self, kind: str, name: str):
        return self._buckets[kind].get(name)

    def list(self, kind: str, predicate: Optional[Callable[[object], bool]] = None) -> list:
        objs = list(self._buckets[kind].values())
        return [o for o in objs if predicate(o)] if predicate else objs

    def delete(self, kind: str, name: str) -> bool:
        """Graceful delete: stamps deletion_timestamp; object is removed only
        once no finalizers remain (Kubernetes semantics the reference's
        termination flows depend on)."""
        FAULT.point("api.delete", kind=kind, name=name)
        obj = self._buckets[kind].get(name)
        if obj is None:
            return False
        if obj.metadata.deletion_timestamp is None:
            obj.metadata.deletion_timestamp = self.clock.now()
        if obj.metadata.finalizers:
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._notify(kind, EventType.MODIFIED, obj)
            return False
        del self._buckets[kind][name]
        self._unindex(kind, obj)
        self._notify(kind, EventType.DELETED, obj)
        return True

    def remove_finalizer(self, kind: str, name: str, finalizer: str) -> None:
        obj = self._buckets[kind].get(name)
        if obj is None:
            return
        if finalizer in obj.metadata.finalizers:
            obj.metadata.finalizers.remove(finalizer)
        if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
            del self._buckets[kind][name]
            self._unindex(kind, obj)
            self._notify(kind, EventType.DELETED, obj)
        else:
            self.update(kind, obj)

    # -- convenience kinds ---------------------------------------------------

    PODS = "pods"
    NODES = "nodes"
    NODECLAIMS = "nodeclaims"
    NODEPOOLS = "nodepools"
    CAPACITY_BUFFERS = "capacitybuffers"
    DAEMONSETS = "daemonsets"
    NODE_OVERLAYS = "nodeoverlays"
    PDBS = "poddisruptionbudgets"
    PVCS = "persistentvolumeclaims"
    STORAGE_CLASSES = "storageclasses"
    RESOURCE_CLAIMS = "resourceclaims"
    RESOURCE_SLICES = "resourceslices"
    DEVICE_CLASSES = "deviceclasses"
    POD_TEMPLATES = "podtemplates"  # CapacityBuffer podTemplateRef targets
    VOLUME_ATTACHMENTS = "volumeattachments"
    SCALABLES = "scalables"  # CapacityBuffer scalableRef targets

    def pods(self) -> list:
        return self.list(self.PODS)

    def nodes(self) -> list:
        return self.list(self.NODES)

    def nodeclaims(self) -> list:
        return self.list(self.NODECLAIMS)

    def nodepools(self) -> list:
        return self.list(self.NODEPOOLS)

    def bind_pod(self, pod_name: str, node_name: str) -> None:
        pod = self.get(self.PODS, pod_name)
        if pod is None:
            raise KeyError(f"pod {pod_name} not found")
        pod.spec.node_name = node_name
        pod.status.phase = "Running"
        pod.status.conditions["PodScheduled"] = "True"
        self.update(self.PODS, pod)
