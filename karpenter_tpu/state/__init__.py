"""Cluster state: the in-memory object store + state mirror.

The reference's durable state lives in the Kubernetes API and its hot state
in an in-memory Cluster mirror rebuilt from watches (SURVEY.md §5). We keep
the same two-tier shape: ObjectStore is the API-server equivalent (typed
buckets, resource versions, watch callbacks); Cluster is the mirror the
scheduler and disruption engine read.
"""

from karpenter_tpu.state.store import ObjectStore, EventType  # noqa: F401
from karpenter_tpu.state.cluster import Cluster, StateNode  # noqa: F401
