"""Host-side slice-shape math — the gang oracle's capacity kernel.

The exact host twin of the device gang step's closed-form slice shape
(ops/solver.py solve_gang): per-host capacity ``f`` is the largest pod
count whose f32 multiply-add total fits some viable (instance type,
allocatable group) cell with a compatible available offering, and a gang
of ``size`` members needs ``ceil(size / f)`` hosts. Both engines share
the one-multiply-add accumulation convention (utils.resources.merge /
scheduler._merge_scaled), so the capacity predicate — and therefore the
slice shape — is bit-identical on the differentially-tested path.
"""

from __future__ import annotations

import numpy as np

from karpenter_tpu.models import labels as l

# the fill kernels' "unbounded" cap (ops/solver.py COUNT_CAP)
COUNT_CAP = 2**22


def merge_scaled(base: dict, req: dict, c: int) -> dict:
    """base + c*req per resource in the f32 one-multiply-add convention
    (the batch-placement accumulation both engines decode with)."""
    out = dict(base)
    cf = np.float32(c)
    for k, v in req.items():
        out[k] = float(np.float32(np.float32(out.get(k, 0.0)) + cf * np.float32(v)))
    return out


def slice_capacity(
    its: list,
    requirements,
    daemon: dict,
    req: dict,
    host_ports: bool = False,
) -> int:
    """Max pods per host: the largest c with ``daemon + c*req`` fitting an
    allocatable group of some viable instance type that keeps a compatible
    available offering. Monotone in c, so a doubling + binary search over
    the shared predicate lands on the same count as the device kernel's
    corrected float estimate. Host-port-carrying pods self-conflict, so
    they cap at one per host (the device's self_conf clamp)."""
    from karpenter_tpu.controllers.provisioning.host_scheduler import (
        _fits_and_offering,
    )

    def ok(c: int) -> bool:
        total = merge_scaled(daemon, req, c)
        return any(
            _fits_and_offering(it.allocatable_offerings(), requirements, total)
            for it in its
        )

    if not its or not ok(1):
        return 0
    if host_ports:
        return 1
    lo, hi = 1, 2
    while hi < COUNT_CAP and ok(hi):
        lo, hi = hi, hi * 2
    # invariant: ok(lo), not ok(hi) (or hi hit the cap)
    if hi >= COUNT_CAP and ok(hi):
        return COUNT_CAP
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if ok(mid):
            lo = mid
        else:
            hi = mid
    return lo


def hosts_needed(size: int, per_host: int) -> int:
    return -(-size // per_host) if per_host > 0 else 0


def rank_blocks(pods: list, per_host: int) -> list[list]:
    """Contiguous rank blocks: host j takes ranks [j*f, (j+1)*f) — the
    deterministic rank -> slice-host mapping both engines emit."""
    return [pods[i : i + per_host] for i in range(0, len(pods), per_host)]


def gang_requirements(template, pod_reqs):
    """Template ∩ pod requirements (hostname added per host claim)."""
    combined = template.requirements.copy()
    combined.add(*pod_reqs.values())
    return combined


def claim_annotation_value(gang_key: str) -> str:
    return gang_key


def hostname_requirement(hostname: str):
    from karpenter_tpu.scheduling import Operator, Requirement

    return Requirement.new(l.LABEL_HOSTNAME, Operator.IN, hostname)
