"""Gang-aware multi-host slice scheduling: the pod-group layer.

Training jobs arrive as all-or-nothing GANGS: a set of identically-specced
pods carrying a shared gang id, a total size, and a per-member rank, that
must land together on one multi-host TPU slice (Rank-Aware Resource
Scheduling for MPI on Kubernetes, PAPERS.md 2603.22691; VirtualFlow
2009.09523). This package owns the pod-group annotation contract and the
host-side orchestration primitives:

  * annotation parsing + validation (``gang_of`` / ``collect_gangs``)
  * the deterministic gang solve order shared by BOTH engines
    (``order_gangs``) — gangs place before singleton pods, largest slice
    first, members in rank order
  * the straggler wait (``GangWaitTracker``): a partial gang is held out
    of the solve until every member has arrived or the wait timeout
    expires (KTPU_GANG_WAIT_SECONDS)

Placement semantics (enforced by both engines, differentially tested in
tests/test_gang.py):

  * a gang places ONLY on freshly-opened dedicated claims (a multi-host
    slice is never shared with singleton pods, and gang claims never
    accept later tier-2 adds);
  * rank r lands on slice host ``r // pods_per_host`` — contiguous rank
    blocks per claim, so co-ranked pods sit on adjacent chips via the
    hostname-slot layout;
  * the gang either fully places in one dispatch or every member cleanly
    fails together — no partial placement ever decodes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

GANG_NAME_ANNOTATION = "ktpu.dev/gang-name"
GANG_SIZE_ANNOTATION = "ktpu.dev/gang-size"
GANG_RANK_ANNOTATION = "ktpu.dev/gang-rank"
# stamped on every NodeClaim of a gang slice so disruption/lifecycle can
# treat the claim group atomically
GANG_CLAIM_ANNOTATION = "ktpu.dev/gang"

# how long a partial gang waits for stragglers before the wait times out
# (the timer restarts: the gang keeps waiting, but the timeout is observed
# in metrics/events so operators see stuck gangs)
GANG_WAIT_SECONDS_DEFAULT = 30.0

# unschedulable reasons (explainer slugs map them in tracing/explainer.py)
GANG_SPILL_REASON = "gang does not fit: no slice shape can hold every member"
GANG_INVALID_REASON = "invalid gang annotations"
GANG_WAITING_REASON = "gang waiting for stragglers"


def gang_wait_seconds() -> float:
    try:
        return float(os.environ.get("KTPU_GANG_WAIT_SECONDS", GANG_WAIT_SECONDS_DEFAULT))
    except ValueError:
        return GANG_WAIT_SECONDS_DEFAULT


def gang_of(pod) -> Optional[tuple[str, int, int]]:
    """(gang key, size, rank) parsed from the pod-group annotations, or
    None for singleton pods. Malformed annotations return None too —
    ``collect_gangs`` separately surfaces them as invalid."""
    ann = pod.metadata.annotations
    name = ann.get(GANG_NAME_ANNOTATION)
    if not name:
        return None
    try:
        size = int(ann.get(GANG_SIZE_ANNOTATION, ""))
        rank = int(ann.get(GANG_RANK_ANNOTATION, ""))
    except (TypeError, ValueError):
        return None
    if size <= 0 or rank < 0 or rank >= size:
        return None
    return (f"{pod.metadata.namespace}/{name}", size, rank)


def is_gang_pod(pod) -> bool:
    return bool(pod.metadata.annotations.get(GANG_NAME_ANNOTATION))


@dataclass
class GangSpec:
    """One gang's membership as observed in a pod set."""

    key: str
    size: int
    members: dict[int, object] = field(default_factory=dict)  # rank -> Pod
    first_index: int = 0  # first appearance in the input order (tie-break)

    @property
    def complete(self) -> bool:
        return len(self.members) == self.size

    @property
    def missing(self) -> int:
        return self.size - len(self.members)

    def pods_in_rank_order(self) -> list:
        return [self.members[r] for r in sorted(self.members)]


def collect_gangs(pods) -> tuple[list[GangSpec], list, list]:
    """Partition a pod list into (gangs, singletons, invalid).

    ``gangs`` holds one GangSpec per gang key in first-appearance order.
    ``invalid`` is [(pod, reason)] for pods whose gang annotations cannot
    be honored: malformed name/size/rank, duplicate ranks, conflicting
    sizes, or members whose specs are not content-identical (a slice hosts
    one uniform worker kind; heterogeneous gangs are rejected loudly
    instead of silently losing the all-or-nothing guarantee).
    """
    from karpenter_tpu.controllers.provisioning.host_scheduler import pod_content_sig

    gangs: dict[str, GangSpec] = {}
    singles: list = []
    invalid: list = []
    for i, pod in enumerate(pods):
        if not is_gang_pod(pod):
            singles.append(pod)
            continue
        parsed = gang_of(pod)
        if parsed is None:
            invalid.append((pod, f"{GANG_INVALID_REASON}: bad name/size/rank"))
            continue
        key, size, rank = parsed
        g = gangs.get(key)
        if g is None:
            g = gangs[key] = GangSpec(key=key, size=size, first_index=i)
        if g.size != size:
            invalid.append((pod, f"{GANG_INVALID_REASON}: conflicting gang-size"))
            continue
        if rank in g.members:
            invalid.append((pod, f"{GANG_INVALID_REASON}: duplicate rank {rank}"))
            continue
        g.members[rank] = pod
    # uniformity: every member must be content-identical (one pod kind)
    out: list[GangSpec] = []
    for g in gangs.values():
        sigs = {pod_content_sig(p) for p in g.members.values()}
        if len(sigs) > 1:
            for p in g.pods_in_rank_order():
                invalid.append((p, f"{GANG_INVALID_REASON}: members not identical"))
            continue
        out.append(g)
    return out, singles, invalid


def order_gangs(gangs: list[GangSpec]) -> list[GangSpec]:
    """The deterministic gang solve order both engines share: largest
    slice footprint first (member FFD size x gang size — the gang analog
    of the FFD sort), first-appearance tie-break. Gangs always solve
    BEFORE singleton pods."""
    from karpenter_tpu.controllers.provisioning.host_scheduler import pod_ffd_key

    def footprint(g: GangSpec) -> float:
        any_member = next(iter(g.members.values()))
        return pod_ffd_key(any_member)[1] * g.size

    return sorted(gangs, key=lambda g: (-footprint(g), g.first_index))


class GangWaitTracker:
    """Straggler wait for partial gangs (clock-injected, fake-clock
    testable). ``admit`` splits the observed gangs into (ready, waiting,
    timed_out); a gang that completes observes its wait duration into the
    gang wait histogram; a wait that exceeds the timeout is reported once
    per timeout interval (the timer restarts so the metric/event repeats
    instead of firing forever)."""

    def __init__(self, clock, timeout_s: Optional[float] = None):
        self.clock = clock
        self.timeout_s = timeout_s if timeout_s is not None else gang_wait_seconds()
        self._first_seen: dict[str, float] = {}

    def admit(
        self, gangs: list[GangSpec]
    ) -> tuple[list[GangSpec], list[GangSpec], list[GangSpec]]:
        from karpenter_tpu.utils.metrics import GANG_WAIT_DURATION

        now = self.clock.now()
        ready: list[GangSpec] = []
        waiting: list[GangSpec] = []
        timed_out: list[GangSpec] = []
        live = set()
        for g in gangs:
            live.add(g.key)
            if g.complete:
                started = self._first_seen.pop(g.key, None)
                if started is not None:
                    GANG_WAIT_DURATION.observe(max(now - started, 0.0))
                ready.append(g)
                continue
            started = self._first_seen.setdefault(g.key, now)
            if now - started >= self.timeout_s:
                timed_out.append(g)
                self._first_seen[g.key] = now  # restart the wait window
            else:
                waiting.append(g)
        # gangs that vanished (scheduled or deleted) release their timers
        for key in list(self._first_seen):
            if key not in live:
                del self._first_seen[key]
        return ready, waiting, timed_out


def partially_bound_gangs(pods) -> dict[str, tuple[int, int]]:
    """Gangs violating the all-or-nothing bind invariant: gang key ->
    (bound members, gang size) for every gang with SOME but not all
    members bound to a node. Empty means every gang is fully bound or
    fully pending — the e2e/chaos suites assert this at every
    observable point."""
    bound: dict[str, int] = {}
    sizes: dict[str, int] = {}
    for p in pods:
        parsed = gang_of(p)
        if parsed is None:
            continue
        key, size, _rank = parsed
        sizes[key] = size
        if p.spec.node_name:
            bound[key] = bound.get(key, 0) + 1
    return {
        key: (bound.get(key, 0), size)
        for key, size in sizes.items()
        if 0 < bound.get(key, 0) < size
    }


def make_gang_pods(
    name: str,
    size: int,
    cpu: "str | float" = 1.0,
    memory: "str | float" = "1Gi",
    namespace: str = "default",
    **kwargs,
):
    """Test/bench factory: one complete gang of `size` rank-annotated,
    content-identical pods."""
    from karpenter_tpu.models.pod import make_pod

    pods = []
    for rank in range(size):
        p = make_pod(f"{name}-{rank}", cpu=cpu, memory=memory, **kwargs)
        p.metadata.namespace = namespace
        p.metadata.annotations.update(
            {
                GANG_NAME_ANNOTATION: name,
                GANG_SIZE_ANNOTATION: str(size),
                GANG_RANK_ANNOTATION: str(rank),
            }
        )
        pods.append(p)
    return pods
