"""Canonical JSON codec for the DRA wire surface.

VERDICT r4 #6: the DRA allocator must be snapshot-based and RPC-safe. A
DRAProblem built by the client (scheduling/dra/integration.py — already a
point-in-time snapshot of slices/classes/claims) serializes here into
SolveRequest.dra_problem_json; the server reconstructs it, runs the host
DFS (allocator.go:231-296 semantics), and ships the winning round's
per-claim allocation metadata back in SolveResponse.dra_metadata_json so
the client's deviceallocation controller can collapse the launches exactly
as in-process solves do. Same canonical-JSON altitude as codec.py.
"""

from __future__ import annotations

import json
from typing import Optional

from karpenter_tpu.rpc.codec import (
    requirement_to_dict,
    requirements_from_list,
    requirements_to_list,
)
from karpenter_tpu.scheduling.dra.allocator import (
    DeviceAllocationResult,
    ResourceClaimAllocationMetadata,
)
from karpenter_tpu.scheduling.dra.constraints import AttributeBindingDecl
from karpenter_tpu.scheduling.dra.tracker import AllocatedDeviceState
from karpenter_tpu.scheduling.dra.types import (
    AllocatedDevice,
    CounterConsumption,
    CounterSet,
    Device,
    DeviceCapacity,
    DeviceClaimStatus,
    DeviceClass,
    DeviceID,
    DeviceRequest,
    DeviceSubRequest,
    MatchConstraintSpec,
    RequestName,
    RequestPolicy,
    ResourceClaim,
    ResourceSlice,
    Version,
)

# -- attribute values (str | int | bool | Version) ---------------------------


def _attr_to_obj(v):
    if isinstance(v, Version):
        return {"version": v.value}
    return v


def _attr_from_obj(o):
    if isinstance(o, dict) and "version" in o:
        return Version(value=o["version"])
    return o


# -- devices / slices --------------------------------------------------------


def _policy_to_dict(p: Optional[RequestPolicy]):
    if p is None:
        return None
    return {
        "default": p.default,
        "min": p.valid_range_min,
        "max": p.valid_range_max,
        "step": p.valid_range_step,
        "values": p.valid_values,
    }


def _policy_from_dict(d) -> Optional[RequestPolicy]:
    if d is None:
        return None
    return RequestPolicy(
        default=d.get("default"),
        valid_range_min=d.get("min"),
        valid_range_max=d.get("max"),
        valid_range_step=d.get("step"),
        valid_values=d.get("values"),
    )


def device_to_dict(d: Device) -> dict:
    return {
        "name": d.name,
        "attributes": {k: _attr_to_obj(v) for k, v in d.attributes.items()},
        "capacity": {
            k: {"value": c.value, "policy": _policy_to_dict(c.request_policy)}
            for k, c in d.capacity.items()
        },
        "multi": d.allow_multiple_allocations,
        "consumes": [
            {"set": c.counter_set, "counters": c.counters} for c in d.consumes_counters
        ],
    }


def device_from_dict(d: dict) -> Device:
    return Device(
        name=d["name"],
        attributes={k: _attr_from_obj(v) for k, v in d.get("attributes", {}).items()},
        capacity={
            k: DeviceCapacity(value=c["value"], request_policy=_policy_from_dict(c.get("policy")))
            for k, c in d.get("capacity", {}).items()
        },
        allow_multiple_allocations=d.get("multi", False),
        consumes_counters=[
            CounterConsumption(counter_set=c["set"], counters=dict(c["counters"]))
            for c in d.get("consumes", [])
        ],
    )


def slice_to_dict(s: ResourceSlice) -> dict:
    return {
        "name": getattr(s.metadata, "name", f"{s.driver}-{s.pool}"),
        "driver": s.driver,
        "pool": s.pool,
        "devices": [device_to_dict(d) for d in s.devices],
        "generation": s.generation,
        "slice_count": s.resource_slice_count,
        "node_name": s.node_name,
        "node_selector_terms": (
            [requirements_to_list(r) for r in s.node_selector_terms]
            if s.node_selector_terms is not None
            else None
        ),
        "all_nodes": s.all_nodes,
        "shared_counters": (
            [{"name": c.name, "counters": c.counters} for c in s.shared_counters]
            if s.shared_counters is not None
            else None
        ),
        "potential": s.potential,
    }


def slice_from_dict(d: dict) -> ResourceSlice:
    s = ResourceSlice(
        driver=d["driver"],
        pool=d["pool"],
        devices=[device_from_dict(x) for x in d.get("devices", [])],
        generation=d.get("generation", 0),
        resource_slice_count=d.get("slice_count", 1),
        node_name=d.get("node_name", ""),
        node_selector_terms=(
            [requirements_from_list(r) for r in d["node_selector_terms"]]
            if d.get("node_selector_terms") is not None
            else None
        ),
        all_nodes=d.get("all_nodes", False),
        shared_counters=(
            [CounterSet(name=c["name"], counters=dict(c["counters"])) for c in d["shared_counters"]]
            if d.get("shared_counters") is not None
            else None
        ),
        potential=d.get("potential", False),
    )
    s.metadata.name = d.get("name", s.metadata.name)
    return s


def binding_decl_to_dict(b: AttributeBindingDecl) -> dict:
    return {"attribute": b.attribute, "devices": [list(x) for x in b.devices]}


def binding_decl_from_dict(d: dict) -> AttributeBindingDecl:
    return AttributeBindingDecl(
        attribute=d["attribute"], devices=[tuple(x) for x in d["devices"]]
    )


# -- claims ------------------------------------------------------------------


def _subrequest_to_dict(r: DeviceSubRequest) -> dict:
    return {
        "name": r.name,
        "device_class": r.device_class,
        "selectors": list(r.selectors),
        "mode": r.allocation_mode,
        "count": r.count,
        "capacity_requests": r.capacity_requests,
    }


def _subrequest_from_dict(d: dict) -> DeviceSubRequest:
    return DeviceSubRequest(
        name=d["name"],
        device_class=d.get("device_class", ""),
        selectors=list(d.get("selectors", [])),
        allocation_mode=d.get("mode", "ExactCount"),
        count=d.get("count", 1),
        capacity_requests=d.get("capacity_requests"),
    )


def claim_to_dict(c: ResourceClaim) -> dict:
    return {
        "name": c.name,
        "namespace": c.namespace,
        "requests": [
            {
                "name": r.name,
                "device_class": r.device_class,
                "selectors": list(r.selectors),
                "mode": r.allocation_mode,
                "count": r.count,
                "capacity_requests": r.capacity_requests,
                "first_available": [_subrequest_to_dict(s) for s in r.first_available],
            }
            for r in c.requests
        ],
        "constraints": [
            {
                "attribute": m.attribute,
                "requests": list(m.requests),
                "distinct": m.distinct_attribute,
            }
            for m in c.constraints
        ],
        "allocation": (
            {
                "devices": [
                    {
                        "request": a.request,
                        "driver": a.driver,
                        "pool": a.pool,
                        "device": a.device,
                        "consumed_capacity": a.consumed_capacity,
                    }
                    for a in c.allocation.devices
                ],
                "node_selector_terms": (
                    [requirements_to_list(r) for r in c.allocation.node_selector_terms]
                    if c.allocation.node_selector_terms is not None
                    else None
                ),
            }
            if c.allocation is not None
            else None
        ),
        "reserved_for": list(c.reserved_for),
    }


def claim_from_dict(d: dict) -> ResourceClaim:
    alloc = None
    if d.get("allocation") is not None:
        a = d["allocation"]
        alloc = DeviceClaimStatus(
            devices=[
                AllocatedDevice(
                    request=x["request"],
                    driver=x["driver"],
                    pool=x["pool"],
                    device=x["device"],
                    consumed_capacity=x.get("consumed_capacity"),
                )
                for x in a.get("devices", [])
            ],
            node_selector_terms=(
                [requirements_from_list(r) for r in a["node_selector_terms"]]
                if a.get("node_selector_terms") is not None
                else None
            ),
        )
    return ResourceClaim(
        name=d["name"],
        namespace=d.get("namespace", "default"),
        requests=[
            DeviceRequest(
                name=r["name"],
                device_class=r.get("device_class", ""),
                selectors=list(r.get("selectors", [])),
                allocation_mode=r.get("mode", "ExactCount"),
                count=r.get("count", 1),
                capacity_requests=r.get("capacity_requests"),
                first_available=[
                    _subrequest_from_dict(s) for s in r.get("first_available", [])
                ],
            )
            for r in d.get("requests", [])
        ],
        constraints=[
            MatchConstraintSpec(
                attribute=m["attribute"],
                requests=list(m.get("requests", [])),
                distinct_attribute=m.get("distinct"),
            )
            for m in d.get("constraints", [])
        ],
        allocation=alloc,
        reserved_for=list(d.get("reserved_for", [])),
    )


# -- the problem -------------------------------------------------------------


def encode_dra_problem(problem) -> bytes:
    """DRAProblem -> canonical JSON. Attribute bindings are NOT shipped:
    the server rebuilds them from its own (Configure-shipped) templates'
    dra_attribute_bindings, exactly like the in-process build."""
    doc = {
        "slices": [slice_to_dict(s) for s in problem.in_cluster_slices],
        "classes": [
            {"name": c.name, "selectors": list(c.selectors)}
            for c in problem.device_classes.values()
        ],
        "claims_by_pod": {
            uid: [claim_to_dict(c) for c in claims]
            for uid, claims in problem.claims_by_pod.items()
        },
        "errors_by_pod": dict(problem.errors_by_pod),
        "deleting_pod_uids": sorted(problem.deleting_pod_uids),
        "allocated": {
            "exclusive": [list(d) for d in sorted(problem.allocated_state.exclusive_devices)],
            "consumed": [
                {"device": list(k), "dims": v}
                for k, v in sorted(problem.allocated_state.consumed_capacity.items())
            ],
        },
    }
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


def decode_dra_problem(data: bytes, templates) -> object:
    """JSON -> DRAProblem, rebinding attribute bindings from the given
    (server-side) templates."""
    from karpenter_tpu.scheduling.dra.integration import (
        DRAProblem,
        build_attribute_bindings,
    )

    doc = json.loads(data.decode())
    catalogs_by_pool: dict[str, list] = {}
    for t in templates:
        catalogs_by_pool.setdefault(t.nodepool_name, []).extend(t.instance_types)
    problem = DRAProblem(
        in_cluster_slices=[slice_from_dict(s) for s in doc["slices"]],
        device_classes={
            c["name"]: DeviceClass(name=c["name"], selectors=list(c["selectors"]))
            for c in doc["classes"]
        },
        claims_by_pod={
            uid: [claim_from_dict(c) for c in claims]
            for uid, claims in doc["claims_by_pod"].items()
        },
        errors_by_pod=dict(doc["errors_by_pod"]),
        deleting_pod_uids=set(doc["deleting_pod_uids"]),
        attribute_bindings=build_attribute_bindings(catalogs_by_pool),
    )
    problem.allocated_state = AllocatedDeviceState(
        exclusive_devices={DeviceID(*d) for d in doc["allocated"]["exclusive"]},
        consumed_capacity={
            DeviceID(*e["device"]): dict(e["dims"]) for e in doc["allocated"]["consumed"]
        },
    )
    return problem


# -- the result metadata -----------------------------------------------------


def encode_dra_metadata(metadata: dict) -> bytes:
    """claim_key -> ResourceClaimAllocationMetadata, the surface the
    provisioner's deviceallocation handoff consumes
    (provisioner.py:_register_device_allocations)."""
    doc = {}
    for key, m in metadata.items():
        doc[key] = {
            "nodeclaim_id": m.nodeclaim_id,
            "contributed": {
                it: requirements_to_list(r)
                for it, r in m.contributed_requirements.items()
            },
            "total": requirements_to_list(m.total_requirements),
            "used_template_devices": m.used_template_devices,
            "devices": {
                it: [
                    {
                        "device": list(r.device_id),
                        "request": list(r.request_name),
                        "consumed_capacity": r.consumed_capacity,
                    }
                    for r in results
                ]
                for it, results in m.devices.items()
            },
        }
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


def decode_dra_metadata(data: bytes) -> dict:
    doc = json.loads(data.decode())
    out = {}
    for key, m in doc.items():
        out[key] = ResourceClaimAllocationMetadata(
            nodeclaim_id=m["nodeclaim_id"],
            contributed_requirements={
                it: requirements_from_list(r) for it, r in m["contributed"].items()
            },
            total_requirements=requirements_from_list(m["total"]),
            used_template_devices=m["used_template_devices"],
            devices={
                it: [
                    DeviceAllocationResult(
                        device_id=DeviceID(*r["device"]),
                        request_name=RequestName(*r["request"]),
                        consumed_capacity=r.get("consumed_capacity"),
                    )
                    for r in results
                ]
                for it, results in m["devices"].items()
            },
        )
    return out


class RemoteDRARound:
    """The client-side stand-in for the winning DRARound: exposes exactly
    the `.allocator.claim_allocation_metadata` surface the provisioner's
    device-allocation handoff reads."""

    class _Allocator:
        def __init__(self, metadata: dict):
            self.claim_allocation_metadata = metadata

    def __init__(self, metadata: dict):
        self.allocator = RemoteDRARound._Allocator(metadata)
