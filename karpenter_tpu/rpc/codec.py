"""Canonical JSON codec for the RPC cold config path.

Serializes the template/catalog set (list[ClaimTemplate], including every
InstanceType with offerings, overrides and overheads) for the Configure
RPC. The solve hot path is typed protobuf (solver.proto); this is the
rarely-crossed config plane, so a readable canonical JSON keyed by the
dataclass fields is the right altitude.

The codec is lossless for everything scheduling consumes, including the
DRA device templates (InstanceType.dra_slices / dra_attribute_bindings)
the remote host solve allocates from (rpc/dra_codec.py carries the rest
of the DRA wire surface: problems in, allocation metadata out).
"""

from __future__ import annotations

import json
import math
from typing import Optional

from karpenter_tpu.cloudprovider.instancetype import (
    InstanceType,
    InstanceTypeOverhead,
    Offering,
)
from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import ClaimTemplate
from karpenter_tpu.models.taints import Taint
from karpenter_tpu.scheduling import Requirement, Requirements

# -- requirements (internal compressed form, lossless) -----------------------


def requirement_to_dict(r: Requirement) -> dict:
    out: dict = {"key": r.key}
    if r.complement:
        out["complement"] = True
    if r.values:
        out["values"] = sorted(r.values)
    if r.gte is not None:
        out["gte"] = r.gte
    if r.lte is not None:
        out["lte"] = r.lte
    if r.min_values is not None:
        out["minValues"] = r.min_values
    return out


def requirement_from_dict(d: dict) -> Requirement:
    return Requirement(
        key=d["key"],
        complement=bool(d.get("complement", False)),
        values=frozenset(d.get("values", ())),
        gte=d.get("gte"),
        lte=d.get("lte"),
        min_values=d.get("minValues"),
    )


def requirements_to_list(reqs: Requirements) -> list[dict]:
    return [requirement_to_dict(r) for r in sorted(reqs.values(), key=lambda r: r.key)]


def requirements_from_list(items: list[dict]) -> Requirements:
    return Requirements(*(requirement_from_dict(d) for d in items))


# -- catalog -----------------------------------------------------------------


def _num(v: float):
    """inf-safe float for JSON (offering prices can be inf in tests)."""
    if v == math.inf:
        return "inf"
    if v == -math.inf:
        return "-inf"
    return v


def _denum(v) -> float:
    if v == "inf":
        return math.inf
    if v == "-inf":
        return -math.inf
    return float(v)


def _overhead_to_dict(o: InstanceTypeOverhead) -> dict:
    return {
        "kubeReserved": o.kube_reserved,
        "systemReserved": o.system_reserved,
        "evictionThreshold": o.eviction_threshold,
    }


def _overhead_from_dict(d: dict) -> InstanceTypeOverhead:
    return InstanceTypeOverhead(
        kube_reserved=dict(d.get("kubeReserved", {})),
        system_reserved=dict(d.get("systemReserved", {})),
        eviction_threshold=dict(d.get("evictionThreshold", {})),
    )


def offering_to_dict(o: Offering) -> dict:
    out: dict = {
        "requirements": requirements_to_list(o.requirements),
        "price": _num(o.price),
        "available": o.available,
    }
    if o.reservation_capacity:
        out["reservationCapacity"] = o.reservation_capacity
    if o.capacity_override:
        out["capacityOverride"] = o.capacity_override
    if o.overhead_override is not None:
        out["overheadOverride"] = _overhead_to_dict(o.overhead_override)
    return out


def offering_from_dict(d: dict) -> Offering:
    return Offering(
        requirements=requirements_from_list(d["requirements"]),
        price=_denum(d["price"]),
        available=bool(d.get("available", True)),
        reservation_capacity=int(d.get("reservationCapacity", 0)),
        capacity_override=dict(d.get("capacityOverride", {})),
        overhead_override=(
            _overhead_from_dict(d["overheadOverride"])
            if "overheadOverride" in d
            else None
        ),
    )


def instance_type_to_dict(it: InstanceType) -> dict:
    out = {
        "name": it.name,
        "requirements": requirements_to_list(it.requirements),
        "offerings": [offering_to_dict(o) for o in it.offerings],
        "capacity": it.capacity,
        "overhead": _overhead_to_dict(it.overhead),
    }
    # DRA device templates: the remote host solve needs per-IT potential
    # slices and attribute-binding declarations to allocate template
    # devices exactly like the in-process engine (rpc/dra_codec.py)
    if getattr(it, "dra_slices", None):
        from karpenter_tpu.rpc import dra_codec

        out["draSlices"] = [dra_codec.slice_to_dict(s) for s in it.dra_slices]
    if getattr(it, "dra_attribute_bindings", None):
        from karpenter_tpu.rpc import dra_codec

        out["draBindings"] = [
            dra_codec.binding_decl_to_dict(b) for b in it.dra_attribute_bindings
        ]
    return out


def instance_type_from_dict(d: dict) -> InstanceType:
    dra_slices = None
    dra_bindings = None
    if "draSlices" in d or "draBindings" in d:
        from karpenter_tpu.rpc import dra_codec

        dra_slices = [dra_codec.slice_from_dict(s) for s in d.get("draSlices", [])]
        dra_bindings = [
            dra_codec.binding_decl_from_dict(b) for b in d.get("draBindings", [])
        ]
    return InstanceType(
        name=d["name"],
        requirements=requirements_from_list(d["requirements"]),
        offerings=[offering_from_dict(o) for o in d["offerings"]],
        capacity=dict(d["capacity"]),
        overhead=_overhead_from_dict(d["overhead"]),
        dra_slices=dra_slices,
        dra_attribute_bindings=dra_bindings,
    )


def _taint_to_dict(t: Taint) -> dict:
    return {"key": t.key, "value": t.value, "effect": t.effect}


def _taint_from_dict(d: dict) -> Taint:
    return Taint(key=d["key"], value=d.get("value", ""), effect=d["effect"])


def template_to_dict(t: ClaimTemplate, it_index: dict[str, int]) -> dict:
    return {
        "nodepoolName": t.nodepool_name,
        "weight": t.weight,
        "requirements": requirements_to_list(t.requirements),
        "instanceTypes": [it_index[it.name] for it in t.instance_types],
        "taints": [_taint_to_dict(x) for x in t.taints],
        "startupTaints": [_taint_to_dict(x) for x in t.startup_taints],
        "labels": t.labels,
        "daemonRequests": t.daemon_requests,
        "isStatic": t.is_static,
        "expireAfterSeconds": t.expire_after_seconds,
        "terminationGracePeriodSeconds": t.termination_grace_period_seconds,
        "nodepoolHash": t.nodepool_hash,
    }


def encode_templates(templates: list[ClaimTemplate]) -> bytes:
    """list[ClaimTemplate] -> canonical JSON. The instance-type catalog is
    deduped by name (templates share catalog objects; identity matters for
    the scheduler's union-catalog memoization)."""
    catalog: dict[str, InstanceType] = {}
    for t in templates:
        for it in t.instance_types:
            catalog.setdefault(it.name, it)
    it_index = {name: i for i, name in enumerate(catalog)}
    doc = {
        "catalog": [instance_type_to_dict(it) for it in catalog.values()],
        "templates": [template_to_dict(t, it_index) for t in templates],
    }
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()


def decode_templates(data: bytes) -> list[ClaimTemplate]:
    doc = json.loads(data.decode())
    catalog = [instance_type_from_dict(d) for d in doc["catalog"]]
    out = []
    for td in doc["templates"]:
        out.append(
            ClaimTemplate(
                nodepool_name=td["nodepoolName"],
                weight=td["weight"],
                requirements=requirements_from_list(td["requirements"]),
                instance_types=[catalog[i] for i in td["instanceTypes"]],
                taints=[_taint_from_dict(x) for x in td["taints"]],
                startup_taints=[_taint_from_dict(x) for x in td["startupTaints"]],
                labels=dict(td["labels"]),
                daemon_requests=dict(td["daemonRequests"]),
                is_static=bool(td["isStatic"]),
                expire_after_seconds=td["expireAfterSeconds"],
                termination_grace_period_seconds=td["terminationGracePeriodSeconds"],
                nodepool_hash=td["nodepoolHash"],
            )
        )
    return out


# -- SolveStream columnar chunk tables (ISSUE 7 satellite) -------------------
#
# The legacy chunk frame re-encoded each decoded chunk group's per-pod
# tables as a partial SolveResponse protobuf, which the client walked
# per-field in Python. The columnar layout flattens the same three tables
# (claim fragments, existing assignments, unschedulable entries) into
# little-endian int32 column arrays plus one UTF-8 string blob, so the
# client rebuilds them from numpy views over the frame buffer — one
# np.frombuffer per column instead of a protobuf parse + per-message
# Python loops. The server is columnar-only since the frame soaked a
# release (ISSUE 8 satellite: the KTPU_RPC_COLUMNAR=0 branch and its
# protobuf re-encode are gone); the CLIENT still decodes the legacy
# FRAME_CHUNK tag so a downgraded server interops.
#
# Layout (all u32/i32 little-endian):
#   header: n_claim_groups, n_claim_uids, n_exist, n_unsched, blob_len
#   i32[n_claim_groups]  claim slot per group
#   i32[n_claim_groups]  uid count per group
#   i32[n_claim_uids]    uid byte length (claim uids, group order)
#   i32[n_exist]         uid byte length      (existing pairs)
#   i32[n_exist]         node-name byte length
#   i32[n_unsched]       uid byte length      (unschedulable pairs)
#   i32[n_unsched]       reason byte length
#   u8[blob_len]         all strings, concatenated in the order above
#     (claim uids, then per-pair uid+node, then per-pair uid+reason)


def encode_chunk_columnar(delta: dict) -> bytes:
    import numpy as np

    claims = delta["claims"]
    exist = delta["existing"]
    unsched = delta["unsched"]
    slots = np.asarray([slot for slot, _uids in claims], dtype="<i4")
    counts = np.asarray([len(uids) for _slot, uids in claims], dtype="<i4")
    blob_parts: list[bytes] = []
    claim_uid_lens: list[int] = []
    for _slot, uids in claims:
        for u in uids:
            b = u.encode("utf-8")
            claim_uid_lens.append(len(b))
            blob_parts.append(b)
    exist_lens: list[int] = []
    node_lens: list[int] = []
    for uid, node in exist:
        bu, bn = uid.encode("utf-8"), node.encode("utf-8")
        exist_lens.append(len(bu))
        node_lens.append(len(bn))
        blob_parts.append(bu)
        blob_parts.append(bn)
    uns_lens: list[int] = []
    reason_lens: list[int] = []
    for uid, reason in unsched:
        bu, br = uid.encode("utf-8"), reason.encode("utf-8")
        uns_lens.append(len(bu))
        reason_lens.append(len(br))
        blob_parts.append(bu)
        blob_parts.append(br)
    blob = b"".join(blob_parts)
    header = np.asarray(
        [len(claims), len(claim_uid_lens), len(exist), len(unsched), len(blob)],
        dtype="<u4",
    )
    return b"".join(
        [
            header.tobytes(),
            slots.tobytes(),
            counts.tobytes(),
            np.asarray(claim_uid_lens, dtype="<i4").tobytes(),
            np.asarray(exist_lens, dtype="<i4").tobytes(),
            np.asarray(node_lens, dtype="<i4").tobytes(),
            np.asarray(uns_lens, dtype="<i4").tobytes(),
            np.asarray(reason_lens, dtype="<i4").tobytes(),
            blob,
        ]
    )


def decode_chunk_columnar(buf: bytes) -> dict:
    """Inverse of encode_chunk_columnar: numpy views over the frame buffer
    rebuild the chunk tables (strings materialize once, from one blob)."""
    import numpy as np

    buf = memoryview(buf)
    n_groups, n_uids, n_exist, n_unsched, blob_len = np.frombuffer(
        buf[:20], dtype="<u4"
    ).tolist()
    off = 20

    def col(n: int):
        nonlocal off
        out = np.frombuffer(buf[off : off + 4 * n], dtype="<i4")
        off += 4 * n
        return out

    slots = col(n_groups)
    counts = col(n_groups)
    claim_uid_lens = col(n_uids)
    exist_lens = col(n_exist)
    node_lens = col(n_exist)
    uns_lens = col(n_unsched)
    reason_lens = col(n_unsched)
    blob = bytes(buf[off : off + blob_len])
    pos = 0

    def take(n: int) -> str:
        nonlocal pos
        out = blob[pos : pos + n].decode("utf-8")
        pos += n
        return out

    claims: list[tuple[int, list[str]]] = []
    li = 0
    for g in range(n_groups):
        c = int(counts[g])
        claims.append(
            (int(slots[g]), [take(int(claim_uid_lens[li + j])) for j in range(c)])
        )
        li += c
    existing = [
        (take(int(exist_lens[i])), take(int(node_lens[i]))) for i in range(n_exist)
    ]
    unsched = [
        (take(int(uns_lens[i])), take(int(reason_lens[i]))) for i in range(n_unsched)
    ]
    return {"claims": claims, "existing": existing, "unsched": unsched}
