"""The solver service: hosts a TPUScheduler behind gRPC.

Control/solver split (SURVEY.md §2.9): this process sits next to the TPU;
the control plane (Provisioner + controllers) talks to it over DCN via
solver.proto. The service is STATELESS between solves — each request
carries the full cluster-side problem; only the Configure'd
template/catalog set (the cold config) persists, exactly like the
reference scheduler consumes a per-loop instance-type snapshot
(provisioner.go:293).

Run standalone:  python -m karpenter_tpu.rpc.service --port 18632
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from concurrent import futures
from typing import Optional

import grpc

from karpenter_tpu.rpc import solver_pb2 as pb
from karpenter_tpu.rpc import convert
from karpenter_tpu.rpc.codec import decode_templates

SERVICE_NAME = "karpenter_tpu.solver.v1.Solver"

# SolveStream frame tags. The stream is hand-framed: each item is one tag
# byte + (for chunk/reset frames) a 4-byte big-endian ROUND + (for
# chunk/final frames) SolveResponse bytes. Reusing the existing message
# keeps the frozen protoc-generated pb2 module untouched (no protoc in
# this image) while letting per-chunk partial results cross the wire as
# the server's pipelined decode produces them. The round tag makes the
# client's stitching state machine robust to stale frames: a chunk whose
# round predates the last reset is discarded, never stitched (the
# mid-stream-recovery hazard — see rpc/client.StreamStitcher).
# LEGACY chunk tag: the server stopped emitting these after the columnar
# frame soaked a release (ISSUE 8 satellite), but the tag stays reserved
# and the CLIENT still decodes it so a downgraded/old server interops
FRAME_CHUNK = b"\x01"  # round + partial per-pod tables from one chunk group
FRAME_FINAL_SLIM = b"\x02"  # final response MINUS the already-streamed tables
FRAME_RESET = b"\x03"  # round; a relaxation round/fallback invalidated chunks
FRAME_FINAL_FULL = b"\x04"  # complete response (nothing was streamed)
# zero-copy chunk tables (ISSUE 7 satellite): round + flat columnar
# layout (rpc/codec.encode_chunk_columnar) instead of a per-chunk partial
# SolveResponse — the client rebuilds the tables from numpy views over
# the frame buffer. The server is columnar-ONLY (the KTPU_RPC_COLUMNAR=0
# opt-out and its protobuf re-encode path were deleted once the frame
# soaked a release).
FRAME_CHUNK_COL = b"\x05"


def _round_bytes(round_no: int) -> bytes:
    return round_no.to_bytes(4, "big")


def _session_cap() -> int:
    """Registry bound (KTPU_SESSION_CAP, default 8, floor 1): each
    resident session pins device-resident SolverState, so the cap is a
    memory knob, not a correctness one — an evicted session's next round
    surfaces as SESSION_LOST (or a fleet handoff) and re-snapshots."""
    try:
        return max(1, int(os.environ.get("KTPU_SESSION_CAP", "8")))
    except ValueError:
        return 8


class SolverService:
    """RPC method implementations. Holds the Configure'd scheduler.

    Fleet wiring (fleet/, ISSUE 16): ``fleet`` is a FleetMember whose bus
    carries quarantine trips, audit verdicts, session capsules, and
    compile announcements across replicas — pumped once per solve RPC, so
    a peer's divergence trips the local breaker within one round.
    ``admission`` is an AdmissionQueue bounding how many rounds may wait
    on the device; a shed round runs the host-solve ladder instead. Both
    default from env (KTPU_FLEET_BUS/KTPU_FLEET_BUS_DIR, KTPU_FLEET_QUEUE)
    and stay None — zero new moving parts — when unconfigured.
    """

    def __init__(self, fleet=None, admission=None):
        from collections import OrderedDict

        self._lock = threading.Lock()
        # Serializes solves: TPUScheduler.solve mutates instance state
        # (reserved_mode swap, _n_claims_override) and the device is a
        # serial resource anyway — overlapping RPCs (client retries, two
        # control planes) must queue, not interleave.
        self._solve_lock = threading.Lock()
        self._scheduler = None
        self._version = 0
        self._epoch = ""
        # server-side resident sessions (ISSUE 7), keyed by the client's
        # ktpu-session-id metadata: remote Solve reuses the on-device
        # SolverState across rounds. Stateless downgrade is structural —
        # no metadata (old client) or KTPU_RESIDENT=0 routes straight to
        # the scheduler, and a session falls back to a bit-identical full
        # solve for anything it cannot prove delta-safe. LRU keyed on
        # last use, bounded by KTPU_SESSION_CAP.
        self._sessions: OrderedDict = OrderedDict()
        if fleet is None and os.environ.get("KTPU_FLEET_BUS") == "file":
            bus_dir = os.environ.get("KTPU_FLEET_BUS_DIR", "")
            if bus_dir:
                from karpenter_tpu.fleet import FileBus, FleetMember

                fleet = FleetMember(FileBus(bus_dir))
        self._fleet = fleet
        if admission is None:
            try:
                depth = int(os.environ.get("KTPU_FLEET_QUEUE", "0"))
            except ValueError:
                depth = 0
            if depth > 0:
                from karpenter_tpu.fleet import AdmissionQueue

                admission = AdmissionQueue(depth)
        self._admission = admission
        # what gets stamped as "replica" on this service's ledger records:
        # the fleet member's id when there is one (the name peers see on
        # the bus), else the env/pid fallback in obs.ledger
        self._replica_id = getattr(self._fleet, "replica_id", "") or ""

    @contextlib.contextmanager
    def _obs_scope(self, context):
        """Observability scope for one RPC: adopt the client's fleet trace
        context (ktpu-fleet-trace metadata, one hop further along) and
        stamp this replica's id on every ledger record the solve makes."""
        from karpenter_tpu.obs import ledger as obs_ledger
        from karpenter_tpu.obs import tracectx

        md = dict(context.invocation_metadata() or ())
        ctx = tracectx.TraceContext.from_wire(md.get(tracectx.METADATA_KEY, ""))
        if ctx is not None:
            ctx = ctx.child()
        with tracectx.activate(ctx), obs_ledger.replica_scope(self._replica_id):
            yield

    def _publish_round(self, ledger_seq0) -> None:
        """Announce this RPC's local ledger records (adoption replays
        included) as telemetry frames, so peers and fleetobs can stitch
        the fleet timeline without sharing a spill directory."""
        if self._fleet is None:
            return
        from karpenter_tpu.obs import ledger as obs_ledger

        for rec in obs_ledger.LEDGER.since(ledger_seq0):
            if rec.get("source") == "local":
                self._fleet.publish_round(rec)

    def _session_for(self, context, sched):
        from karpenter_tpu.controllers.provisioning.scheduler import (
            ResidentSession,
            resident_enabled,
        )
        from karpenter_tpu.utils.metrics import SESSION_EVICTIONS

        if not resident_enabled():
            return None
        md = dict(context.invocation_metadata() or ())
        sid = md.get("ktpu-session-id")
        if not sid:
            return None
        # the fingerprint of the resident state the CLIENT believes this
        # session holds (echoed back to it after every solve); empty when
        # the client has no resident expectation (first round / after a
        # SESSION_LOST re-snapshot)
        client_fpr = md.get("ktpu-session-fpr", "")
        from karpenter_tpu.faultinject import FAULT

        with self._lock:
            try:
                # chaos seam: force a registry eviction mid-session (the
                # injected error is the *signal*, not a failure — the
                # eviction itself is the fault being simulated)
                FAULT.point("rpc.session.evict", session=sid)
            except Exception:
                if self._sessions.pop(sid, None) is not None:
                    SESSION_EVICTIONS.inc(reason="fault")
            session = self._sessions.get(sid)
            lost = session is None or session.sched is not sched
            if not lost and client_fpr and session.fingerprint != client_fpr:
                # same registry slot but a different state chain (the
                # registry restarted or the slot was recycled): the
                # resident state the client is deltaing against is gone
                self._sessions.pop(sid, None)
                SESSION_EVICTIONS.inc(reason="stale_chain")
                lost = True
            if not lost:
                self._sessions.move_to_end(sid)
                return session
        # lost — the registry lock is RELEASED here: adoption replays
        # whole solve rounds on the device and must not hold it
        if client_fpr:
            session = self._adopt_session(sid, client_fpr, sched)
            if session is None:
                # typed loss: the client maps this to ONE silent snapshot
                # re-solve. NOT_FOUND is deliberately non-transient (the
                # retry loop must not storm) and distinct from
                # FAILED_PRECONDITION (which drives re-Configure).
                context.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"SESSION_LOST: resident session {sid!r} evicted or "
                    "restarted; re-snapshot",
                )
            return self._install(sid, session)
        return self._install(sid, ResidentSession(sched))

    def _install(self, sid, session):
        from karpenter_tpu.utils.metrics import SESSION_EVICTIONS

        with self._lock:
            self._sessions[sid] = session
            self._sessions.move_to_end(sid)
            cap = _session_cap()
            while len(self._sessions) > cap:
                # bounded registry: evict the LEAST-RECENTLY-USED session
                # (its next round surfaces as SESSION_LOST / fleet
                # handoff and re-snapshots)
                self._sessions.popitem(last=False)
                SESSION_EVICTIONS.inc(reason="capacity")
        return session

    def _adopt_session(self, sid, client_fpr, sched):
        """Session mobility: rebuild the lost session from the fleet's
        capsule archive by replaying its transcript chain. Returns the
        adopted ResidentSession only when the rebuilt fingerprint equals
        the one the client presented; None falls back to SESSION_LOST."""
        if self._fleet is None:
            return None
        from karpenter_tpu.fleet import mobility
        from karpenter_tpu.utils.metrics import FLEET_HANDOFFS

        from karpenter_tpu.obs.slo import SLO

        doc = self._fleet.capsule_for(sid, client_fpr)
        if doc is None:
            FLEET_HANDOFFS.inc(outcome="no_capsule")
            SLO.observe_availability(False, kind="handoff")
            return None
        # the replay drives real device solves — serialize like any round
        with self._solve_lock:
            session, outcome = mobility.adopt(sched, doc, client_fpr)
        FLEET_HANDOFFS.inc(outcome=outcome)
        # an adoption that lands is the availability story working — the
        # client never saw the dead replica; anything else burns budget
        SLO.observe_availability(outcome == "adopted", kind="handoff")
        return session

    @staticmethod
    def _echo_session_fpr(context, session, ledger_seq0: Optional[int] = None) -> None:
        """Trailing metadata: the fingerprint of the resident state this
        solve left behind (the client's proof-of-continuity token), plus
        the solve's round-ledger record (``ktpu-round-ledger``, compact
        JSON) so remote rounds land in the CLIENT's flight recorder too.
        ``set_trailing_metadata`` replaces rather than merges, so both
        keys ride one call."""
        md = []
        if session is not None:
            md.append(("ktpu-session-fpr", session.fingerprint))
        if ledger_seq0 is not None:
            from karpenter_tpu.obs import ledger as obs_ledger

            rounds = obs_ledger.LEDGER.since(ledger_seq0)
            # the LAST local record since the solve started is this
            # round's (relaxation sub-rounds record separately; remote
            # ingestions are filtered out)
            local = [r for r in rounds if r.get("source") == "local"]
            if local:
                md.append(
                    ("ktpu-round-ledger", obs_ledger.wire_record(local[-1]))
                )
        if not md:
            return
        try:
            context.set_trailing_metadata(tuple(md))
        except Exception:
            pass  # context already terminated (deadline); nothing to echo

    @staticmethod
    def _server_span(name: str, context):
        """Root a server-side span under the client's trace context when
        it crossed the wire (ktpu-trace-id / ktpu-span-id metadata), so a
        remote Solve's spans stitch into the caller's trace."""
        from karpenter_tpu.tracing.tracer import TRACER

        md = dict(context.invocation_metadata() or ())
        return TRACER.server_span(
            name, md.get("ktpu-trace-id"), md.get("ktpu-span-id")
        )

    # -- rpc handlers ------------------------------------------------------

    @staticmethod
    def _config_epoch(request: pb.ConfigureRequest, mesh_devices: int) -> str:
        """Cluster-shape epoch: everything a Configure feeds the scheduler
        constructor. Two Configures with the same epoch build the same
        scheduler, so the live one (and every resident session bound to
        it) can survive the reconfigure."""
        import hashlib

        tj = request.templates_json
        h = hashlib.blake2s(digest_size=8)
        h.update(tj if isinstance(tj, bytes) else tj.encode())
        knobs = "|".join(
            (
                str(request.max_claims if request.HasField("max_claims") else None),
                str(request.pod_pad if request.HasField("pod_pad") else None),
                request.reserved_mode or "fallback",
                str(bool(request.reserved_capacity_enabled)),
                request.min_values_policy or "Strict",
                str(mesh_devices),
            )
        )
        h.update(knobs.encode())
        return h.hexdigest()

    def Configure(self, request: pb.ConfigureRequest, context) -> pb.ConfigureResponse:
        from karpenter_tpu.controllers.provisioning.scheduler import TPUScheduler
        from karpenter_tpu.utils.metrics import SESSION_EVICTIONS

        mesh_devices = int(os.environ.get("KTPU_MESH_DEVICES", "0"))
        epoch = self._config_epoch(request, mesh_devices)
        with self._lock:
            if self._scheduler is not None and epoch == self._epoch:
                # same cluster shape: keep the live scheduler AND its
                # resident sessions — an unrelated Configure (a second
                # client arriving, a control-plane restart with identical
                # templates) must not force SESSION_LOST fleet-wide
                return pb.ConfigureResponse(config_version=self._version)
        templates = decode_templates(request.templates_json)
        mesh = None
        if mesh_devices:
            # the solver process owns the accelerators; its mesh size is a
            # deployment property (env), not a per-client setting
            from karpenter_tpu.parallel import make_mesh

            mesh = make_mesh(mesh_devices)
        sched = TPUScheduler(
            templates,
            max_claims=request.max_claims if request.HasField("max_claims") else None,
            pod_pad=request.pod_pad if request.HasField("pod_pad") else None,
            reserved_mode=request.reserved_mode or "fallback",
            reserved_capacity_enabled=request.reserved_capacity_enabled,
            min_values_policy=request.min_values_policy or "Strict",
            mesh=mesh,
        )
        with self._lock:
            self._version += 1
            self._scheduler = sched
            self._epoch = epoch
            version = self._version
            # resident sessions are bound to a scheduler generation; only
            # a genuine shape change invalidates them now
            n = len(self._sessions)
            self._sessions.clear()
            if n:
                SESSION_EVICTIONS.inc(n, reason="epoch")
        return pb.ConfigureResponse(config_version=version)

    def Solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        with self._server_span("rpc.server.Solve", context), self._obs_scope(
            context
        ):
            return self._solve(request, context)

    def SolveStream(self, request: pb.SolveRequest, context):
        """Streaming Solve: the scheduler's pipelined decode emits each
        chunk group's per-pod tables as soon as it lands, so serialization
        + DCN transfer of the bulk tables overlap the server's decode of
        later chunks; the final frame carries the claim-level remainder.
        A reset frame invalidates prior chunks whenever a relaxation round
        (or a host-oracle fallback) restarts the tables."""
        with self._server_span("rpc.server.SolveStream", context), self._obs_scope(
            context
        ):
            yield from self._solve_stream(request, context)

    def _checked_scheduler(self, request, context):
        with self._lock:
            sched, version = self._scheduler, self._version
        if sched is None or request.config_version != version:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"config_version {request.config_version} != live {version}; re-Configure",
            )
        if self._fleet is not None:
            # drain the guardrail bus before solving, so a peer replica's
            # quarantine trip / session capsule lands within one round
            self._fleet.pump()
        return sched

    @contextlib.contextmanager
    def _admitted(self, context):
        """Admission gate around the device solve. Without an
        AdmissionQueue this is exactly the old solve lock. With one, the
        caller blocks in per-tenant fair order for the solve slot; a
        round shed by overload yields "shed" WITHOUT the lock — it must
        run the host ladder instead of touching the device."""
        if self._admission is None:
            with self._solve_lock:
                yield "run"
            return
        md = dict(context.invocation_metadata() or ())
        tenant = md.get("ktpu-tenant") or md.get("ktpu-session-id") or "anon"
        verdict = self._admission.acquire(tenant)
        if verdict == "shed":
            from karpenter_tpu.obs.slo import SLO
            from karpenter_tpu.utils.metrics import FLEET_SHED

            FLEET_SHED.inc(reason="queue_full")
            SLO.observe_availability(False, kind="shed")
            yield "shed"
            return
        try:
            with self._solve_lock:
                yield "run"
        finally:
            self._admission.release()

    def _host_shed(self, sched, args, kwargs):
        """A shed round's solve: the existing host-solve ladder (the same
        engine every DRA/volume fallback already trusts), built from the
        decoded request — correct, device-free, and slower, which is the
        deliberate trade against stalling the whole queue."""
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            HostScheduler,
            normalize_volume_reqs,
        )
        from karpenter_tpu.utils.metrics import SOLVER_FALLBACK, SOLVER_HOST_FALLBACKS

        SOLVER_HOST_FALLBACKS.inc(reason="fleet_shed")
        SOLVER_FALLBACK.inc(reason="fleet_shed")
        pods, existing, budgets = args
        pods = list(pods)
        host = HostScheduler(
            sched.templates,
            existing_nodes=[n.clone() for n in (existing or [])],
            budgets=budgets,
            topology=kwargs["topology_factory"](pods),
            volume_reqs=normalize_volume_reqs(kwargs["volume_reqs"]),
            reserved_mode=kwargs["reserved_mode"] or sched.reserved_mode,
            reserved_capacity_enabled=sched.reserved_capacity_enabled,
            min_values_policy=sched.min_values_policy,
            reserved_in_use=kwargs["reserved_in_use"],
            dra_problem=kwargs["dra_problem"],
            pod_volumes=kwargs["pod_volumes"],
            deadline=kwargs["deadline"],
        )
        return host.solve(pods)

    def _solve_stream(self, request: pb.SolveRequest, context):
        import queue

        sched = self._checked_scheduler(request, context)
        frames: queue.Queue = queue.Queue()
        streamed = [False]  # chunks emitted since the last reset
        round_no = [0]  # bumps with every EMITTED reset frame
        _DONE = object()

        def sink(event) -> None:
            kind, delta = event
            if kind == "reset":
                if streamed[0]:
                    round_no[0] += 1
                    frames.put(FRAME_RESET + _round_bytes(round_no[0]))
                streamed[0] = False
            else:
                from karpenter_tpu.rpc.codec import encode_chunk_columnar

                streamed[0] = True
                frames.put(
                    FRAME_CHUNK_COL
                    + _round_bytes(round_no[0])
                    + encode_chunk_columnar(delta)
                )

        # the solve runs in a worker so the handler thread can yield chunk
        # frames while the decode is still producing later ones
        args, kwargs = self._solve_args(request, sched)
        from karpenter_tpu.obs import ledger as obs_ledger

        # before _session_for: an adoption's replay rounds record too,
        # and the telemetry publish below should carry them to the fleet
        ledger_seq0 = obs_ledger.LEDGER.seq()
        session = self._session_for(context, sched)
        sid = dict(context.invocation_metadata() or ()).get("ktpu-session-id")
        engine = session if session is not None else sched

        def run() -> None:
            try:
                with self._admitted(context) as verdict:
                    if verdict == "shed":
                        result = self._host_shed(sched, args, kwargs)
                    else:
                        result = engine.solve(*args, chunk_sink=sink, **kwargs)
                if self._fleet is not None and session is not None:
                    # announce the advanced chain so a peer can adopt it
                    # if this replica dies before the next round
                    self._fleet.publish_session(sid, session)
                resp = self._result_pb(sched, result)
                if streamed[0]:
                    # the streamed chunks already carried the per-pod
                    # tables — strip them so the drain frame stays small
                    for m in resp.claims:
                        m.ClearField("pod_uids")
                    resp.ClearField("assignments")
                    resp.ClearField("existing_assignments")
                    resp.ClearField("unschedulable")
                    frames.put(FRAME_FINAL_SLIM + resp.SerializeToString())
                else:
                    frames.put(FRAME_FINAL_FULL + resp.SerializeToString())
            except BaseException as e:  # noqa: BLE001 — re-raised in handler
                frames.put(e)
            frames.put(_DONE)

        # the worker must inherit the handler thread's contextvars so its
        # solve spans root under the server span (and thus stitch into the
        # client's trace via the ktpu-trace-id metadata)
        import contextvars

        ctx = contextvars.copy_context()
        worker = threading.Thread(target=ctx.run, args=(run,), daemon=True)
        worker.start()
        while True:
            item = frames.get()
            if item is _DONE:
                self._echo_session_fpr(context, session, ledger_seq0)
                self._publish_round(ledger_seq0)
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def _solve_args(self, request: pb.SolveRequest, sched) -> tuple:
        """Decode a SolveRequest into TPUScheduler.solve positional args —
        one decoding shared by the unary and streaming handlers."""
        pods = [convert.pod_from_pb(m) for m in request.pods]
        existing = [
            convert.existing_from_pb(m, i) for i, m in enumerate(request.existing_nodes)
        ]
        budgets = {
            pool: dict(rm.resources) for pool, rm in request.budgets.items()
        } or None
        bound = [
            (convert.pod_from_pb(b.pod), dict(b.node_labels)) for b in request.bound_pods
        ]
        volume_reqs = {
            va.pod_uid: [convert.reqs_from_pb(rs.requirements) for rs in va.alternatives]
            for va in request.volume_reqs
        } or None
        pod_volumes = {
            pv.pod_uid: convert.volumes_from_pb(pv) for pv in request.pod_volumes
        } or None

        def topology_factory(current_pods):
            from karpenter_tpu.controllers.provisioning.topology import (
                Topology,
                build_universe_domains,
            )
            from karpenter_tpu.tracing.tracer import TRACER

            with TRACER.span("topology.build", pods=len(current_pods)):
                # lazy universe: topology-free pod sets skip domain
                # construction entirely (Topology.build fast path)
                return Topology.build(
                    current_pods,
                    lambda: build_universe_domains(
                        sched.templates, existing, template_base=sched.universe_base()
                    ),
                    bound,
                )

        dra_problem = None
        if request.dra_problem_json:
            # snapshot in, metadata out: the server's host engine runs the
            # allocation DFS against the shipped state (rpc/dra_codec.py)
            from karpenter_tpu.rpc.dra_codec import decode_dra_problem

            dra_problem = decode_dra_problem(request.dra_problem_json, sched.templates)

        deadline = None
        if request.HasField("timeout_seconds"):
            deadline = time.monotonic() + request.timeout_seconds
        return (pods, existing, budgets), dict(
            topology_factory=topology_factory,
            volume_reqs=volume_reqs,
            reserved_mode=request.reserved_mode or None,
            reserved_in_use=dict(request.reserved_in_use) or None,
            pod_volumes=pod_volumes,
            dra_problem=dra_problem,
            deadline=deadline,
        )

    def _solve(self, request: pb.SolveRequest, context) -> pb.SolveResponse:
        sched = self._checked_scheduler(request, context)
        args, kwargs = self._solve_args(request, sched)
        from karpenter_tpu.obs import ledger as obs_ledger

        # before _session_for: an adoption's replay rounds record too,
        # and the telemetry publish below should carry them to the fleet
        ledger_seq0 = obs_ledger.LEDGER.seq()
        session = self._session_for(context, sched)
        sid = dict(context.invocation_metadata() or ()).get("ktpu-session-id")
        engine = session if session is not None else sched
        with self._admitted(context) as verdict:
            if verdict == "shed":
                result = self._host_shed(sched, args, kwargs)
            else:
                result = engine.solve(*args, **kwargs)
        if self._fleet is not None and session is not None:
            # announce the advanced chain so a peer can adopt it if this
            # replica dies before the next round
            self._fleet.publish_session(sid, session)
        self._echo_session_fpr(context, session, ledger_seq0)
        self._publish_round(ledger_seq0)
        return self._result_pb(sched, result)

    @staticmethod
    def _result_pb(sched, result) -> pb.SolveResponse:
        resp = convert.result_to_pb(result, sched.templates)
        if result.dra is not None:
            from karpenter_tpu.rpc.dra_codec import encode_dra_metadata

            resp.dra_metadata_json = encode_dra_metadata(
                result.dra.allocator.claim_allocation_metadata
            )
        return resp

    def WhatIf(self, request: pb.WhatIfRequest, context) -> pb.WhatIfResponse:
        """Batched consolidation what-ifs over the wire: S exclusion
        scenarios in ONE device dispatch (TPUScheduler.whatif_batch).
        Declines exactly when the in-process prefilter would (multi-alt
        volumes, per-scenario group-structure divergence) — callers fall
        back to sequential Solve RPCs. CSI attach limits ride the batch.
        Stays unary (no SolveStream analog): the reply is O(S) verdict
        booleans from one vmapped dispatch — there are no chunk results
        to stream, unlike Solve's per-pod tables."""
        with self._server_span("rpc.server.WhatIf", context):
            return self._whatif(request, context)

    def _whatif(self, request: pb.WhatIfRequest, context) -> pb.WhatIfResponse:
        with self._lock:
            sched, version = self._scheduler, self._version
        if sched is None or request.config_version != version:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"config_version {request.config_version} != live {version}; re-Configure",
            )
        pods = [convert.pod_from_pb(m) for m in request.pods]
        existing = [
            convert.existing_from_pb(m, i) for i, m in enumerate(request.existing_nodes)
        ]
        budgets = {
            pool: dict(rm.resources) for pool, rm in request.budgets.items()
        } or None
        bound = [
            (convert.pod_from_pb(b.pod), dict(b.node_labels), b.node_name)
            for b in request.bound_pods
        ]
        volume_reqs = {
            va.pod_uid: [convert.reqs_from_pb(rs.requirements) for rs in va.alternatives]
            for va in request.volume_reqs
        } or None
        scenarios = [
            (set(s.excluded_nodes), set(s.active_pod_uids), set(s.counted_pod_uids))
            for s in request.scenarios
        ]

        def topology_factory(current_pods, excluded):
            from karpenter_tpu.controllers.provisioning.topology import (
                Topology,
                build_universe_domains,
            )

            # the scenario's excluded nodes leave the domain UNIVERSE too
            # (local parity: _build_topology -> _existing_sim_nodes(excluded));
            # a domain only an excluded node carries would otherwise pin
            # the spread global min at a permanently-zero domain
            surviving = [n for n in existing if n.name not in excluded]
            keep = [(p, labels) for p, labels, name in bound if name not in excluded]
            return Topology.build(
                current_pods,
                lambda: build_universe_domains(
                    sched.templates, surviving, template_base=sched.universe_base()
                ),
                keep,
            )

        with self._solve_lock:
            out = sched.whatif_batch(
                pods,
                existing,
                budgets,
                scenarios,
                topology_factory,
                volume_reqs=volume_reqs,
                reserved_in_use=dict(request.reserved_in_use) or None,
                pod_volumes={
                    pv.pod_uid: convert.volumes_from_pb(pv)
                    for pv in request.pod_volumes
                }
                or None,
            )
        resp = pb.WhatIfResponse()
        if out is None:
            resp.declined = True
        else:
            for ok, n_new in out:
                v = resp.verdicts.add()
                v.feasible = bool(ok)
                v.new_claims = int(n_new)
        return resp

    def Health(self, request: pb.HealthRequest, context) -> pb.HealthResponse:
        import jax

        with self._lock:
            version = self._version
        return pb.HealthResponse(
            ready=self._scheduler is not None,
            platform=jax.devices()[0].platform,
            config_version=version,
        )


def _handlers(service: SolverService) -> grpc.GenericRpcHandler:
    """Hand-wired method handlers (no grpc_tools codegen in this image —
    protoc emits messages only; the service table is built directly)."""
    rpcs = {
        "Configure": grpc.unary_unary_rpc_method_handler(
            service.Configure,
            request_deserializer=pb.ConfigureRequest.FromString,
            response_serializer=pb.ConfigureResponse.SerializeToString,
        ),
        "Solve": grpc.unary_unary_rpc_method_handler(
            service.Solve,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=pb.SolveResponse.SerializeToString,
        ),
        # hand-framed server stream: each item is already bytes (tag +
        # SolveResponse payload), so the serializer is the identity
        "SolveStream": grpc.unary_stream_rpc_method_handler(
            service.SolveStream,
            request_deserializer=pb.SolveRequest.FromString,
            response_serializer=lambda b: b,
        ),
        "WhatIf": grpc.unary_unary_rpc_method_handler(
            service.WhatIf,
            request_deserializer=pb.WhatIfRequest.FromString,
            response_serializer=pb.WhatIfResponse.SerializeToString,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.Health,
            request_deserializer=pb.HealthRequest.FromString,
            response_serializer=pb.HealthResponse.SerializeToString,
        ),
    }
    return grpc.method_handlers_generic_handler(SERVICE_NAME, rpcs)


def serve(
    address: str = "127.0.0.1:0",
    max_workers: int = 4,
    service: Optional[SolverService] = None,
) -> tuple[grpc.Server, str]:
    """Start a solver server; returns (server, bound address). Solves are
    serialized through SolverService._solve_lock, so the worker pool only
    needs to cover Configure/Health overlap. ``service`` lets fleet
    callers (tests, bench --fleet) inject a SolverService wired to a
    shared bus / admission queue."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            # north-star problems serialize ~10s of MB of pods
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ],
    )
    server.add_generic_rpc_handlers((_handlers(service or SolverService()),))
    port = server.add_insecure_port(address)
    # host:port split that survives bracketed IPv6 literals ("[::1]:0")
    if address.startswith("["):
        host = address[: address.index("]") + 1]
    else:
        host = address.rsplit(":", 1)[0]
    server.start()
    return server, f"{host}:{port}"


def main() -> None:
    import argparse

    parser = argparse.ArgumentParser(description="karpenter-tpu solver service")
    parser.add_argument("--port", type=int, default=18632)
    parser.add_argument("--host", default="0.0.0.0")
    args = parser.parse_args()
    server, addr = serve(f"{args.host}:{args.port}")
    print(f"solver listening on {addr}", flush=True)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
