"""gRPC control/solver split (SURVEY.md §2.9).

- solver.proto / solver_pb2.py — the wire contract (typed Solve hot path,
  JSON-config Configure cold path)
- service.py — the solver-side server hosting a TPUScheduler
- client.py  — RemoteScheduler, the Provisioner-facing drop-in
- codec.py   — canonical template/catalog JSON for Configure
"""

from karpenter_tpu.rpc import solver_pb2  # noqa: F401
from karpenter_tpu.rpc.client import RemoteScheduler  # noqa: F401
from karpenter_tpu.rpc.service import SolverService, serve  # noqa: F401
