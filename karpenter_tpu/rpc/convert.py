"""Typed proto <-> model conversion for the solve hot path.

Faithfulness matters more than brevity here: pod kind-dedup
(host_scheduler.pod_content_sig) hashes spec content, so every field the
signature covers must round-trip exactly — a lossy convert would split or
merge pod kinds across the wire and change packing.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.controllers.provisioning.host_scheduler import (
    ExistingSimNode,
    SchedulingResult,
    SimClaim,
)
from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.models.pod import (
    HostPort,
    NodeAffinity,
    NodeSelectorTerm,
    Pod,
    PodAffinityTerm,
    PodSpec,
    PreferredSchedulingTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.models.taints import Taint, Toleration
from karpenter_tpu.rpc import solver_pb2 as pb
from karpenter_tpu.rpc.codec import (
    requirement_from_dict,
    requirement_to_dict,
)
from karpenter_tpu.scheduling import Requirement, Requirements
from karpenter_tpu.scheduling.volumes import VolumeUsage

# -- requirements ------------------------------------------------------------


def req_to_pb(r: Requirement) -> pb.Requirement:
    d = requirement_to_dict(r)
    out = pb.Requirement(key=d["key"], complement=d.get("complement", False))
    out.values.extend(d.get("values", ()))
    if "gte" in d:
        out.gte = d["gte"]
    if "lte" in d:
        out.lte = d["lte"]
    if "minValues" in d:
        out.min_values = d["minValues"]
    return out


def req_from_pb(m: pb.Requirement) -> Requirement:
    d: dict = {"key": m.key, "complement": m.complement, "values": list(m.values)}
    if m.HasField("gte"):
        d["gte"] = m.gte
    if m.HasField("lte"):
        d["lte"] = m.lte
    if m.HasField("min_values"):
        d["minValues"] = m.min_values
    return requirement_from_dict(d)


def reqs_to_pb(reqs: Requirements) -> list[pb.Requirement]:
    return [req_to_pb(r) for r in sorted(reqs.values(), key=lambda r: r.key)]


def reqs_from_pb(items) -> Requirements:
    return Requirements(*(req_from_pb(m) for m in items))


# -- pods --------------------------------------------------------------------


def _terms_to_pb(terms: list[PodAffinityTerm], out) -> None:
    for t in terms:
        m = out.add()
        m.topology_key = t.topology_key
        m.label_selector.update(t.label_selector)
        m.namespaces.extend(t.namespaces)


def _terms_from_pb(items) -> list[PodAffinityTerm]:
    return [
        PodAffinityTerm(
            topology_key=m.topology_key,
            label_selector=dict(m.label_selector),
            namespaces=list(m.namespaces),
        )
        for m in items
    ]


def pod_to_pb(pod: Pod) -> pb.Pod:
    m = pb.Pod(
        name=pod.metadata.name,
        namespace=pod.metadata.namespace,
        uid=pod.metadata.uid,
        creation_timestamp=pod.metadata.creation_timestamp,
        priority=pod.spec.priority,
        node_name=pod.spec.node_name,
        termination_grace_period_seconds=pod.spec.termination_grace_period_seconds,
    )
    m.labels.update(pod.metadata.labels)
    m.annotations.update(pod.metadata.annotations)
    m.requests.update(pod.spec.requests)
    m.limits.update(pod.spec.limits)
    m.node_selector.update(pod.spec.node_selector)
    m.resource_claims.extend(pod.spec.resource_claims)
    na = pod.spec.node_affinity
    if na is not None:
        for term in na.required:
            t = m.node_affinity.required.add()
            for e in term.match_expressions:
                x = t.match_expressions.add()
                x.key, x.operator = e["key"], e["operator"]
                x.values.extend(e.get("values", ()))
        for pref in na.preferred:
            t = m.node_affinity.preferred.add()
            t.weight = pref.weight
            for e in pref.match_expressions:
                x = t.match_expressions.add()
                x.key, x.operator = e["key"], e["operator"]
                x.values.extend(e.get("values", ()))
    _terms_to_pb(pod.spec.pod_affinity, m.pod_affinity)
    _terms_to_pb(pod.spec.pod_anti_affinity, m.pod_anti_affinity)
    _terms_to_pb(pod.spec.preferred_pod_affinity, m.preferred_pod_affinity)
    _terms_to_pb(pod.spec.preferred_pod_anti_affinity, m.preferred_pod_anti_affinity)
    for tol in pod.spec.tolerations:
        t = m.tolerations.add()
        t.key, t.operator, t.value, t.effect = tol.key, tol.operator, tol.value, tol.effect
        if tol.toleration_seconds is not None:
            t.toleration_seconds = tol.toleration_seconds
    for tsc in pod.spec.topology_spread_constraints:
        t = m.topology_spread_constraints.add()
        t.max_skew = tsc.max_skew
        t.topology_key = tsc.topology_key
        t.when_unsatisfiable = tsc.when_unsatisfiable
        t.label_selector.update(tsc.label_selector)
        if tsc.min_domains is not None:
            t.min_domains = tsc.min_domains
        t.node_affinity_policy = tsc.node_affinity_policy
        t.node_taints_policy = tsc.node_taints_policy
    for hp in pod.spec.host_ports:
        h = m.host_ports.add()
        h.port, h.protocol, h.host_ip = hp.port, hp.protocol, hp.host_ip
    m.pvc_names.extend(pod.spec.pvc_names)
    return m


def _expr_from_pb(x) -> dict:
    d = {"key": x.key, "operator": x.operator}
    if x.values:
        d["values"] = list(x.values)
    return d


def pod_from_pb(m: pb.Pod) -> Pod:
    spec = PodSpec(
        requests=dict(m.requests),
        limits=dict(m.limits),
        node_name=m.node_name,
        resource_claims=list(m.resource_claims),
        termination_grace_period_seconds=m.termination_grace_period_seconds,
        node_selector=dict(m.node_selector),
        pod_affinity=_terms_from_pb(m.pod_affinity),
        pod_anti_affinity=_terms_from_pb(m.pod_anti_affinity),
        preferred_pod_affinity=_terms_from_pb(m.preferred_pod_affinity),
        preferred_pod_anti_affinity=_terms_from_pb(m.preferred_pod_anti_affinity),
        tolerations=[
            Toleration(
                key=t.key,
                operator=t.operator,
                value=t.value,
                effect=t.effect,
                toleration_seconds=(
                    t.toleration_seconds if t.HasField("toleration_seconds") else None
                ),
            )
            for t in m.tolerations
        ],
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=t.max_skew,
                topology_key=t.topology_key,
                when_unsatisfiable=t.when_unsatisfiable,
                label_selector=dict(t.label_selector),
                min_domains=t.min_domains if t.HasField("min_domains") else None,
                node_affinity_policy=t.node_affinity_policy,
                node_taints_policy=t.node_taints_policy,
            )
            for t in m.topology_spread_constraints
        ],
        host_ports=[
            HostPort(port=h.port, protocol=h.protocol, host_ip=h.host_ip)
            for h in m.host_ports
        ],
        priority=m.priority,
        pvc_names=list(m.pvc_names),
    )
    if m.HasField("node_affinity"):
        spec.node_affinity = NodeAffinity(
            required=[
                NodeSelectorTerm(
                    match_expressions=[_expr_from_pb(x) for x in t.match_expressions]
                )
                for t in m.node_affinity.required
            ],
            preferred=[
                PreferredSchedulingTerm(
                    weight=t.weight,
                    match_expressions=[_expr_from_pb(x) for x in t.match_expressions],
                )
                for t in m.node_affinity.preferred
            ],
        )
    pod = Pod(
        metadata=ObjectMeta(
            name=m.name,
            namespace=m.namespace,
            uid=m.uid,
            labels=dict(m.labels),
            annotations=dict(m.annotations),
            creation_timestamp=m.creation_timestamp,
        ),
        spec=spec,
    )
    pod.status.conditions["PodScheduled"] = "Unschedulable"
    return pod


# -- volumes / existing nodes ------------------------------------------------


def volumes_to_pb(pod_uid: str, vols: dict) -> pb.PodVolumes:
    m = pb.PodVolumes(pod_uid=pod_uid)
    for driver in sorted(vols):
        d = m.volumes.add()
        d.driver = driver
        d.pvc_ids.extend(sorted(vols[driver]))
    return m


def volumes_from_pb(m: pb.PodVolumes) -> dict:
    return {d.driver: set(d.pvc_ids) for d in m.volumes}


def existing_to_pb(n: ExistingSimNode) -> pb.ExistingNode:
    m = pb.ExistingNode(name=n.name)
    m.requirements.extend(reqs_to_pb(n.requirements))
    m.available.update(n.available)
    m.used.update(n.used)
    for ip, port, proto in n.host_ports:
        h = m.host_ports.add()
        h.host_ip, h.port, h.protocol = ip, port, proto
    for t in n.taints:
        x = m.taints.add()
        x.key, x.value, x.effect = t.key, t.value, t.effect
    if n.volume_usage is not None:
        m.volume_usage.limits.update(n.volume_usage.limits)
        for uid in sorted(n.volume_usage.pod_volumes):
            m.volume_usage.pod_volumes.append(
                volumes_to_pb(uid, n.volume_usage.pod_volumes[uid])
            )
    return m


def existing_from_pb(m: pb.ExistingNode, index: int) -> ExistingSimNode:
    usage = None
    if m.HasField("volume_usage"):
        usage = VolumeUsage()
        for driver, count in m.volume_usage.limits.items():
            usage.add_limit(driver, count)
        for pv in m.volume_usage.pod_volumes:
            usage.add(pv.pod_uid, volumes_from_pb(pv))
    return ExistingSimNode(
        name=m.name,
        index=index,
        requirements=reqs_from_pb(m.requirements),
        available=dict(m.available),
        taints=[Taint(key=t.key, value=t.value, effect=t.effect) for t in m.taints],
        used=dict(m.used),
        host_ports=[(h.host_ip, h.port, h.protocol) for h in m.host_ports],
        volume_usage=usage,
    )


# -- result ------------------------------------------------------------------


def result_to_pb(result: SchedulingResult, templates: list) -> pb.SolveResponse:
    tmpl_idx = {id(t): i for i, t in enumerate(templates)}
    resp = pb.SolveResponse()
    for c in result.claims:
        m = resp.claims.add()
        m.template_index = tmpl_idx[id(c.template)]
        m.requirements.extend(reqs_to_pb(c.requirements))
        m.used.update(c.used)
        m.instance_type_names.extend(it.name for it in c.instance_types)
        m.pod_uids.extend(p.uid for p in c.pods)
        m.slot = c.slot
        m.hostname = c.hostname
        for ip, port, proto in c.host_ports:
            h = m.host_ports.add()
            h.host_ip, h.port, h.protocol = ip, port, proto
        m.reserved_ids.extend(sorted(c.reserved_ids))
        m.min_values_relaxed = c.min_values_relaxed
    for pod, reason in result.unschedulable:
        u = resp.unschedulable.add()
        u.pod_uid, u.reason = pod.uid, reason
    for uid, node_name in result.existing_assignments.items():
        a = resp.existing_assignments.add()
        a.pod_uid, a.node_name = uid, node_name
    resp.assignments.update(result.assignments)
    return resp


def result_from_pb(
    resp: pb.SolveResponse,
    templates: list,
    catalog: dict[str, object],
    pods_by_uid: dict[str, Pod],
    existing_nodes: Optional[list[ExistingSimNode]] = None,
) -> SchedulingResult:
    """Rebuild a SchedulingResult against the CLIENT's template/catalog
    objects (identity matters downstream: create_node_claims reads
    template fields, cheapest_launch walks instance types)."""
    claims = []
    for m in resp.claims:
        claims.append(
            SimClaim(
                template=templates[m.template_index],
                requirements=reqs_from_pb(m.requirements),
                used=dict(m.used),
                instance_types=[catalog[n] for n in m.instance_type_names],
                pods=[pods_by_uid[u] for u in m.pod_uids],
                slot=m.slot,
                hostname=m.hostname,
                host_ports=[(h.host_ip, h.port, h.protocol) for h in m.host_ports],
                reserved_ids=frozenset(m.reserved_ids),
                min_values_relaxed=m.min_values_relaxed,
            )
        )
    existing = [n.clone() for n in (existing_nodes or [])]
    by_name = {n.name: n for n in existing}
    existing_assignments = {}
    for a in resp.existing_assignments:
        existing_assignments[a.pod_uid] = a.node_name
        node = by_name.get(a.node_name)
        if node is not None and a.pod_uid in pods_by_uid:
            node.pods.append(pods_by_uid[a.pod_uid])
    return SchedulingResult(
        claims=claims,
        unschedulable=[
            (pods_by_uid[u.pod_uid], u.reason)
            for u in resp.unschedulable
            if u.pod_uid in pods_by_uid
        ],
        assignments=dict(resp.assignments),
        existing=existing,
        existing_assignments=existing_assignments,
    )


def result_from_stream(
    resp: pb.SolveResponse,
    claim_pod_uids: dict[int, list[str]],
    existing_pairs: list[tuple[str, str]],
    unsched_pairs: list[tuple[str, str]],
    templates: list,
    catalog: dict[str, object],
    pods_by_uid: dict[str, Pod],
    existing_nodes: Optional[list[ExistingSimNode]] = None,
) -> SchedulingResult:
    """Rebuild a SchedulingResult from a STREAMED Solve: the final (slim)
    SolveResponse carries claims WITHOUT pod_uids and none of the per-pod
    tables — those arrived earlier as ordered chunk frames, accumulated by
    the client into per-slot uid lists / assignment pairs. Pod order
    within each claim (parity-relevant: it is the decode stream order) is
    exactly the chunk emission order."""
    claims = []
    assignments: dict[str, int] = {}
    for m in resp.claims:
        uids = claim_pod_uids.get(m.slot, [])
        for u in uids:
            assignments[u] = m.slot
        claims.append(
            SimClaim(
                template=templates[m.template_index],
                requirements=reqs_from_pb(m.requirements),
                used=dict(m.used),
                instance_types=[catalog[n] for n in m.instance_type_names],
                pods=[pods_by_uid[u] for u in uids if u in pods_by_uid],
                slot=m.slot,
                hostname=m.hostname,
                host_ports=[(h.host_ip, h.port, h.protocol) for h in m.host_ports],
                reserved_ids=frozenset(m.reserved_ids),
                min_values_relaxed=m.min_values_relaxed,
            )
        )
    existing = [n.clone() for n in (existing_nodes or [])]
    by_name = {n.name: n for n in existing}
    existing_assignments: dict[str, str] = {}
    for uid, node_name in existing_pairs:
        existing_assignments[uid] = node_name
        node = by_name.get(node_name)
        if node is not None and uid in pods_by_uid:
            node.pods.append(pods_by_uid[uid])
    return SchedulingResult(
        claims=claims,
        unschedulable=[
            (pods_by_uid[u], reason) for u, reason in unsched_pairs if u in pods_by_uid
        ],
        assignments=assignments,
        existing=existing,
        existing_assignments=existing_assignments,
    )
