"""RemoteScheduler: the control plane's view of the solver service.

Drop-in for TPUScheduler at the Provisioner seam — same solve() surface,
same SchedulingResult out — but the work happens across the wire
(solver.proto). This is the reference's decorator pattern
(pkg/cloudprovider/metrics/cloudprovider.go) applied to the scheduler
boundary: the Provisioner neither knows nor cares whether the solver is
in-process or remote.

Split of labor:
- Remote: the full relaxation ladder, NO_ROOM recovery, device dispatch,
  host-oracle fallbacks for volume alternatives — everything
  TPUScheduler.solve does, running next to the TPU. DRA solves cross the
  wire too: the DRAProblem is a self-contained snapshot, the server's
  host engine runs the allocation DFS, and the winning round's per-claim
  metadata ships back (rpc/dra_codec.py).
- whatif_batch crosses the wire as well (the WhatIf RPC): scenarios'
  topology seeds rebuild server-side from shipped bound pods; the client
  returns None (sequential-simulate fallback) when bound pods are
  unavailable or the server declines/predates the RPC.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import grpc

from karpenter_tpu.controllers.provisioning.host_scheduler import (
    SchedulingResult,
    normalize_volume_reqs,
)
from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import ClaimTemplate
from karpenter_tpu.faultinject import FAULT
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.rpc import solver_pb2 as pb
from karpenter_tpu.rpc import convert
from karpenter_tpu.rpc.codec import encode_templates
from karpenter_tpu.rpc.retry import (
    Backoff,
    CircuitBreaker,
    CircuitOpenError,
    is_transient_code,
)
from karpenter_tpu.rpc.service import (
    FRAME_CHUNK,
    FRAME_CHUNK_COL,
    FRAME_FINAL_FULL,
    FRAME_RESET,
    SERVICE_NAME,
)

_RPC_OPTIONS = [
    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
    ("grpc.max_send_message_length", 256 * 1024 * 1024),
]

# Every call carries a gRPC deadline — an unreachable or hung solver must
# never block the control plane indefinitely (the whole point of the Solve
# timeout work). Solve gets the request's own budget plus slack for the
# server's cold XLA compile (~20-70s per shape class, bench cold_s).
CONFIGURE_TIMEOUT_SECONDS = 120.0
# FAILED_PRECONDITION (config superseded / solver restart) retries per
# call: the server holds one active configuration, so concurrent clients
# with different configs ping-pong — bound it so contention surfaces
RECONFIGURE_RETRIES = 3
HEALTH_TIMEOUT_SECONDS = 10.0
SOLVE_COMPILE_SLACK_SECONDS = 600.0
DEFAULT_SOLVE_BUDGET_SECONDS = 600.0
# Transport hardening (all env-tunable; tests shrink the backoff):
# transient codes (UNAVAILABLE/RESOURCE_EXHAUSTED/ABORTED) retry with
# exponential backoff + jitter; after STREAM_RETRIES mid-stream failures
# the call downgrades to unary Solve for its remaining attempts (the
# chunk stitcher restarts clean either way — accumulated frames from a
# broken attempt never leak into the retry).
TRANSPORT_RETRIES = int(os.environ.get("KTPU_RPC_RETRIES", "3"))
STREAM_RETRIES = int(os.environ.get("KTPU_RPC_STREAM_RETRIES", "2"))
RETRY_BASE_SECONDS = float(os.environ.get("KTPU_RPC_RETRY_BASE", "0.2"))
RETRY_CAP_SECONDS = float(os.environ.get("KTPU_RPC_RETRY_CAP", "10.0"))
BREAKER_THRESHOLD = int(os.environ.get("KTPU_RPC_BREAKER_THRESHOLD", "5"))
BREAKER_COOLDOWN_SECONDS = float(os.environ.get("KTPU_RPC_BREAKER_COOLDOWN", "15.0"))

# per-target circuit breakers: every RemoteScheduler against the same
# endpoint shares one breaker, so a down solver is tripped once, not once
# per scheduler-cache rebuild
_BREAKERS: dict[str, CircuitBreaker] = {}


def _breaker_for(endpoint: str) -> CircuitBreaker:
    breaker = _BREAKERS.get(endpoint)
    if breaker is None:

        def on_transition(to: str) -> None:
            from karpenter_tpu.utils.metrics import CIRCUIT_TRANSITIONS

            CIRCUIT_TRANSITIONS.inc(target=endpoint, to=to)

        breaker = CircuitBreaker(
            failure_threshold=BREAKER_THRESHOLD,
            cooldown_s=BREAKER_COOLDOWN_SECONDS,
            on_transition=on_transition,
        )
        _BREAKERS[endpoint] = breaker
    return breaker


def reset_breakers() -> None:
    """Drop all per-target breaker state (tests)."""
    _BREAKERS.clear()


class StreamStitcher:
    """The SolveStream chunk-stitching state machine, extracted so the
    out-of-order/stale-frame behavior is unit-testable without sockets.

    Frames carry a server-side ROUND (service.py framing): a reset frame
    advances the live round and discards accumulated tables; a chunk
    frame whose round differs from the live one is STALE — it belongs to
    a relaxation round (or a cut stream's abandoned attempt) that a reset
    already invalidated — and must be dropped, not stitched."""

    def __init__(self):
        self.claims: dict[int, list[str]] = {}
        self.exist: list[tuple[str, str]] = []
        self.unsched: list[tuple[str, str]] = []
        self.round = 0
        self.n_frames = self.n_chunks = self.n_resets = self.n_stale = 0
        self.final = None
        self.full = False

    def feed(self, frame: bytes) -> bool:
        """Consume one frame; True once the final frame landed."""
        self.n_frames += 1
        tag = frame[:1]
        if tag == FRAME_RESET:
            self.n_resets += 1
            self.round = int.from_bytes(frame[1:5], "big")
            self.claims.clear()
            self.exist.clear()
            self.unsched.clear()
        elif tag in (FRAME_CHUNK, FRAME_CHUNK_COL):
            round_no = int.from_bytes(frame[1:5], "big")
            if round_no != self.round:
                self.n_stale += 1
                from karpenter_tpu.utils.metrics import STREAM_STALE_FRAMES

                STREAM_STALE_FRAMES.inc()
                return False
            self.n_chunks += 1
            if tag == FRAME_CHUNK_COL:
                # zero-copy chunk tables: int32 column views + one string
                # blob instead of a per-chunk protobuf parse
                from karpenter_tpu.rpc.codec import decode_chunk_columnar

                part = decode_chunk_columnar(bytes(frame[5:]))
                for slot, uids in part["claims"]:
                    self.claims.setdefault(slot, []).extend(uids)
                self.exist.extend(part["existing"])
                self.unsched.extend(part["unsched"])
            else:
                part = pb.SolveResponse.FromString(bytes(frame[5:]))
                for m in part.claims:
                    self.claims.setdefault(m.slot, []).extend(m.pod_uids)
                for a in part.existing_assignments:
                    self.exist.append((a.pod_uid, a.node_name))
                for u in part.unschedulable:
                    self.unsched.append((u.pod_uid, u.reason))
        else:  # FINAL_SLIM / FINAL_FULL
            self.final = pb.SolveResponse.FromString(bytes(frame[1:]))
            self.full = tag == FRAME_FINAL_FULL
            return True
        return False

    def tables(self) -> dict:
        return {"claims": self.claims, "existing": self.exist, "unsched": self.unsched}

    def stats(self) -> dict:
        return {
            "frames": self.n_frames,
            "chunks": self.n_chunks,
            "resets": self.n_resets,
            "stale": self.n_stale,
            "full": self.full,
        }


class RemoteScheduler:
    """One instance per template/catalog set, like TPUScheduler; Configure
    happens eagerly at construction so the first Solve pays no config RTT."""

    # the Provisioner materializes bound_pods (topology count seeding) only
    # for schedulers that ship it across a wire — the in-process engine
    # reads the cluster through its topology_factory instead
    wants_bound_pods = True

    def __init__(
        self,
        endpoint: str,
        templates: list[ClaimTemplate],
        max_claims: Optional[int] = None,
        pod_pad: Optional[int] = None,
        reserved_mode: str = "fallback",
        reserved_capacity_enabled: bool = True,
        min_values_policy: str = "Strict",
        channel: Optional[grpc.Channel] = None,
    ):
        self.templates = templates
        self.reserved_mode = reserved_mode
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.min_values_policy = min_values_policy
        self._catalog = {}
        for t in templates:
            for it in t.instance_types:
                self._catalog.setdefault(it.name, it)
        # fleet routing front: a comma-separated endpoint list is a
        # replica set — the client talks to ONE replica at a time and
        # retargets (rpc/fleet, ISSUE 16) when its transport gives out,
        # carrying the session fingerprint so the next replica can adopt
        # the capsule transcript instead of forcing a cold re-solve
        self._endpoints = [e.strip() for e in (endpoint or "").split(",") if e.strip()]
        if not self._endpoints:
            self._endpoints = [endpoint]
        self._endpoint_idx = 0
        self._connect(self._endpoints[0], channel=channel)
        self.last_stream: dict = {}
        # resident-session affinity (ISSUE 7): one session id per client
        # scheduler instance, sent as metadata on every Solve so the
        # server reuses its on-device resident SolverState across rounds.
        # Stateless downgrade is structural: old servers ignore unknown
        # metadata, and KTPU_RESIDENT=0 suppresses it entirely.
        import uuid

        self._session_id = (
            uuid.uuid4().hex
            if os.environ.get("KTPU_RESIDENT", "1") not in ("0", "false")
            else None
        )
        # resident-state fingerprint (guard/, ISSUE 10): the server echoes
        # a hash of its session's applied-round chain in trailing metadata;
        # we send it back on the next Solve. A mismatch (server restart,
        # LRU eviction) surfaces as a typed SESSION_LOST instead of a
        # silently-wrong delta base. Empty until the first echo, so old
        # servers (no trailer) never trigger the loss path.
        self._session_fpr = ""
        # fleet trace identity (obs/tracectx, ISSUE 17): every solve round
        # mints one compact context here at the origin; it rides the wire
        # as ktpu-fleet-trace and stitches the round's journey across
        # retargets and handoffs into a single queryable tree
        self._trace_origin = f"client-{os.getpid()}"
        self._tenant = os.environ.get("KTPU_TENANT", "")
        req = pb.ConfigureRequest(
            templates_json=encode_templates(templates),
            reserved_mode=reserved_mode,
            reserved_capacity_enabled=reserved_capacity_enabled,
            min_values_policy=min_values_policy,
        )
        if max_claims is not None:
            req.max_claims = max_claims
        if pod_pad is not None:
            req.pod_pad = pod_pad
        self._configure_request = req
        self._reconfigure()
        self.last_timings: dict = {}

    def _connect(self, endpoint: str, channel: Optional[grpc.Channel] = None) -> None:
        """(Re)build the channel + stubs against one endpoint. Called at
        construction and on every fleet retarget — stubs are bound to a
        channel, so they rebuild together."""
        self._channel = channel or grpc.insecure_channel(endpoint, options=_RPC_OPTIONS)

        def timed_stub(method, req_cls, resp_cls):
            # every crossing (including retries) records into the duration
            # histogram — the decorator-seam observability parity
            # (cloudprovider/metrics/cloudprovider.go wraps every SPI call)
            stub = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )

            def call(request, **kwargs):
                from karpenter_tpu.tracing.tracer import TRACER
                from karpenter_tpu.utils.metrics import SOLVER_RPC_DURATION

                with TRACER.span(f"rpc.{method}"):
                    # trace-context propagation: the server seeds its
                    # handler-thread spans from these so a remote Solve's
                    # server-side spans stitch into the CLIENT's trace
                    ctx = TRACER.context()
                    if ctx is not None:
                        md = list(kwargs.pop("metadata", None) or ())
                        md += [("ktpu-trace-id", ctx[0]), ("ktpu-span-id", ctx[1])]
                        kwargs["metadata"] = md
                    with SOLVER_RPC_DURATION.time(method=method):
                        if kwargs.pop("with_call", False):
                            # (response, call) — the caller wants trailing
                            # metadata (the server's session fingerprint)
                            return stub.with_call(request, **kwargs)
                        return stub(request, **kwargs)

            return call

        self._configure = timed_stub("Configure", pb.ConfigureRequest, pb.ConfigureResponse)
        self._solve = timed_stub("Solve", pb.SolveRequest, pb.SolveResponse)
        self._whatif = timed_stub("WhatIf", pb.WhatIfRequest, pb.WhatIfResponse)
        self._health = timed_stub("Health", pb.HealthRequest, pb.HealthResponse)
        # streaming Solve: per-chunk partial tables arrive while the
        # server's pipelined decode still works on later chunks. Frames
        # are hand-framed bytes (tag [+ round] + SolveResponse payload)
        # so the deserializer is the identity. Preferred by default; one
        # UNIMPLEMENTED (older server) downgrades to unary for the
        # channel's lifetime. KTPU_RPC_STREAM=0 opts out.
        self._solve_stream = self._channel.unary_stream(
            f"/{SERVICE_NAME}/SolveStream",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=lambda b: b,
        )
        self._stream_ok = os.environ.get("KTPU_RPC_STREAM", "1") != "0"
        # transport hardening: per-target breaker + jittered backoff (the
        # RNG is fresh per scheduler; seed via rpc.retry.Backoff in tests)
        self._endpoint = endpoint or "in-process"
        self._breaker = _breaker_for(self._endpoint)
        self._backoff = Backoff(base_s=RETRY_BASE_SECONDS, cap_s=RETRY_CAP_SECONDS)

    def _retarget(self, reason: str) -> None:
        """Route to the next replica in the endpoint list. The session id
        AND fingerprint survive: the new replica either adopts the capsule
        transcript off the guardrail bus (fingerprint-verified) or answers
        SESSION_LOST and the ordinary one-shot re-snapshot runs there."""
        from karpenter_tpu.utils.metrics import FLEET_RETARGETS

        from karpenter_tpu.obs import tracectx
        from karpenter_tpu.obs.slo import SLO

        # a retarget is an availability event (a replica was unreachable)
        # and one more hop on the round's fleet trace
        SLO.observe_availability(False, kind="retarget")
        ctx = tracectx.current()
        if ctx is not None:
            ctx.hop += 1
        self._endpoint_idx = (self._endpoint_idx + 1) % len(self._endpoints)
        target = self._endpoints[self._endpoint_idx]
        try:
            self._channel.close()
        except Exception:
            pass
        self._connect(target)
        FLEET_RETARGETS.inc(reason=reason)
        self._reconfigure()

    def _reconfigure(self) -> None:
        self._config_version = self._configure(
            self._configure_request, timeout=CONFIGURE_TIMEOUT_SECONDS
        ).config_version

    def close(self) -> None:
        self._channel.close()

    def health(self) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=HEALTH_TIMEOUT_SECONDS)

    def _consume_stream(self, req, rpc_timeout: float):
        """Drive one SolveStream call to completion through a fresh
        StreamStitcher (a reset frame discards accumulated tables — a
        relaxation round or host fallback restarted the solve; a stale
        chunk from a superseded round is dropped) and return (final
        SolveResponse, accumulated tables or None when the final frame
        was FULL). The stitcher is LOCAL to the call: a mid-stream
        failure abandons it wholesale, so a transport retry can never
        stitch frames from a broken attempt. Tracing metadata and the
        RPC duration histogram mirror the unary stub."""
        from karpenter_tpu.tracing.tracer import TRACER
        from karpenter_tpu.utils.metrics import SOLVER_RPC_DURATION

        stitcher = StreamStitcher()
        with TRACER.span("rpc.SolveStream"):
            kwargs: dict = {"timeout": rpc_timeout}
            md = list(self._session_md())
            ctx = TRACER.context()
            if ctx is not None:
                md += [
                    ("ktpu-trace-id", ctx[0]),
                    ("ktpu-span-id", ctx[1]),
                ]
            if md:
                kwargs["metadata"] = md
            with SOLVER_RPC_DURATION.time(method="SolveStream"):
                call = self._solve_stream(req, **kwargs)
                # explicit iteration so the time this client spends
                # BLOCKED on the transport (waiting for the next frame,
                # vs host-side stitching between frames) is attributed —
                # it is the remote round's wire segment
                wire_blocked_s = 0.0
                frames = iter(call)
                while True:
                    t_wire = time.perf_counter()
                    try:
                        frame = next(frames)
                    except StopIteration:
                        break
                    wire_blocked_s += time.perf_counter() - t_wire
                    # the mid-stream cut point: an injected UNAVAILABLE
                    # here simulates the transport dying at chunk <index>
                    FAULT.point("rpc.stream.chunk", index=stitcher.n_chunks)
                    if stitcher.feed(frame):
                        break
                if stitcher.final is not None:
                    # the final frame is the handler's last yield, so the
                    # RPC terminates immediately after — this blocks only
                    # for that turnaround. Session fingerprint AND the
                    # round-ledger record both ride the trailer.
                    self._absorb_trailing(call.trailing_metadata())
        if stitcher.final is None:
            raise RuntimeError("SolveStream ended without a final frame")
        from karpenter_tpu.obs import waterfall as _wfl

        _wfl.add_current("rpc.wire", wire_blocked_s)
        self.last_stream = stitcher.stats()
        self.last_stream["wire_blocked_s"] = round(wire_blocked_s, 6)
        if stitcher.full:
            return stitcher.final, None
        return stitcher.final, stitcher.tables()

    def _session_md(self) -> list:
        md = []
        if self._session_id is not None:
            md.append(("ktpu-session-id", self._session_id))
            if self._session_fpr:
                md.append(("ktpu-session-fpr", self._session_fpr))
        if self._tenant:
            md.append(("ktpu-tenant", self._tenant))
        from karpenter_tpu.obs import tracectx

        ctx = tracectx.current()
        if ctx is not None:
            md.append((tracectx.METADATA_KEY, ctx.to_wire()))
        return md

    def _absorb_trailing(self, trailing) -> None:
        """Absorb trailing metadata: the server's resident-state
        fingerprint (absent key — old server, stateless solve — leaves
        the stored value untouched) and the solve's round-ledger record,
        which lands in the client-side flight recorder with
        source="remote" so an incident timeline covers remote rounds
        too."""
        for key, value in trailing or ():
            if key == "ktpu-session-fpr" and self._session_id is not None:
                self._session_fpr = value
            elif key == "ktpu-round-ledger":
                from karpenter_tpu.obs import ledger as obs_ledger

                obs_ledger.ingest_remote(value)

    def _unary_solve(self, req, rpc_timeout: float):
        md = self._session_md()
        resp, call = self._solve(
            req, timeout=rpc_timeout, metadata=(md or None), with_call=True
        )
        self._absorb_trailing(call.trailing_metadata())
        return resp

    def _transport_solve(self, req, rpc_timeout: float):
        """One hardened Solve crossing: stream-first with mid-stream
        recovery (reconnect and re-solve from scratch; after
        STREAM_RETRIES stream failures the call downgrades to unary for
        its remaining attempts), transient-code retry with jittered
        backoff, and per-target circuit-breaker accounting. Non-transient
        codes (FAILED_PRECONDITION included — the caller's re-Configure
        loop owns that) raise through untouched."""
        from karpenter_tpu.utils.metrics import STREAM_RECOVERIES

        stream_failures = 0
        for attempt in range(TRANSPORT_RETRIES + 1):
            if not self._breaker.allow():
                raise CircuitOpenError(
                    f"solver {self._endpoint} circuit open"
                    f" (cooling down after repeated transport failures)"
                )
            use_stream = self._stream_ok and stream_failures < STREAM_RETRIES
            try:
                FAULT.point(
                    "rpc.solve.send",
                    method="SolveStream" if use_stream else "Solve",
                    attempt=attempt,
                )
                if use_stream:
                    try:
                        out = self._consume_stream(req, rpc_timeout)
                    except grpc.RpcError as err:
                        if err.code() != grpc.StatusCode.UNIMPLEMENTED:
                            raise
                        # older server without the SolveStream handler:
                        # permanent downgrade to the unary path
                        self._stream_ok = False
                        out = self._unary_solve(req, rpc_timeout), None
                else:
                    out = self._unary_solve(req, rpc_timeout), None
                self._breaker.record_success()
                if stream_failures:
                    STREAM_RECOVERIES.inc(
                        outcome="resumed" if use_stream else "downgraded_unary"
                    )
                return out
            except grpc.RpcError as err:
                if not is_transient_code(err):
                    raise
                self._breaker.record_failure()
                if use_stream:
                    stream_failures += 1
                if attempt >= TRANSPORT_RETRIES:
                    if stream_failures:
                        STREAM_RECOVERIES.inc(outcome="exhausted")
                    raise
                time.sleep(self._backoff.delay(attempt))

    # -- the TPUScheduler surface -----------------------------------------

    @staticmethod
    def _encode_common(req, pods, existing_nodes, budgets, volume_reqs, reserved_in_use):
        """The request fields Solve and WhatIf share — one encoding to
        keep the two wire paths from drifting."""
        for p in pods:
            req.pods.append(convert.pod_to_pb(p))
        for n in existing_nodes or []:
            req.existing_nodes.append(convert.existing_to_pb(n))
        for pool, res_map in (budgets or {}).items():
            req.budgets[pool].resources.update(res_map)
        for uid, alts in normalize_volume_reqs(volume_reqs).items():
            va = req.volume_reqs.add()
            va.pod_uid = uid
            for alt in alts:
                rs = va.alternatives.add()
                rs.requirements.extend(convert.reqs_to_pb(alt))
        for rid, n in (reserved_in_use or {}).items():
            req.reserved_in_use[rid] = n

    def solve(self, pods: Sequence[Pod], *args, **kwargs) -> SchedulingResult:
        """One scheduling round. Mints the round's fleet trace context —
        the same trace_id survives transport retries, retargets, and a
        session handoff (hop count records each crossing) — then runs the
        hardened transport round under it."""
        from karpenter_tpu.obs import tracectx

        ctx = tracectx.mint(
            origin=self._trace_origin,
            tenant=self._tenant or (self._session_id or "")[:12],
        )
        with tracectx.activate(ctx):
            return self._solve_round(pods, *args, **kwargs)

    def _solve_round(
        self,
        pods: Sequence[Pod],
        existing_nodes=None,
        budgets=None,
        topology=None,
        topology_factory=None,
        volume_reqs=None,
        reserved_mode=None,
        reserved_in_use=None,
        dra_problem=None,
        pod_volumes=None,
        deadline=None,
        now=None,
        bound_pods=None,
    ) -> SchedulingResult:
        t0 = time.perf_counter()
        # fresh per solve: a unary-downgraded call must not inherit the
        # previous stream solve's frame stats / wire attribution
        self.last_stream = {}
        req = pb.SolveRequest(config_version=self._config_version)
        if dra_problem is not None and any(p.spec.resource_claims for p in pods):
            # the DRAProblem is a self-contained snapshot (slices, classes,
            # claims, allocation seeds) — it crosses the wire and the
            # SERVER's host engine runs the allocation DFS
            # (rpc/dra_codec.py; allocator.go:231-296)
            from karpenter_tpu.rpc.dra_codec import encode_dra_problem

            req.dra_problem_json = encode_dra_problem(dra_problem)
        pods = list(pods)
        self._encode_common(req, pods, existing_nodes, budgets, volume_reqs, reserved_in_use)
        for entry in bound_pods or []:
            b = req.bound_pods.add()
            b.pod.CopyFrom(convert.pod_to_pb(entry[0]))
            b.node_labels.update(entry[1])
            if len(entry) > 2:
                b.node_name = entry[2]
        for uid, vols in (pod_volumes or {}).items():
            req.pod_volumes.append(convert.volumes_to_pb(uid, vols))
        if reserved_mode is not None:
            req.reserved_mode = reserved_mode
        if deadline is not None:
            # wall deadlines don't cross machines: ship the REMAINING
            # budget; the server re-anchors it on its own monotonic clock
            now_fn = now if now is not None else time.monotonic
            req.timeout_seconds = max(deadline - now_fn(), 0.0)
        rpc_timeout = (
            req.timeout_seconds if deadline is not None else DEFAULT_SOLVE_BUDGET_SECONDS
        ) + SOLVE_COMPILE_SLACK_SECONDS
        t_encode = time.perf_counter()
        stream_acc = None
        session_lost_retried = False
        attempt = 0
        retargets = 0
        while True:
            try:
                resp, stream_acc = self._transport_solve(req, rpc_timeout)
                break
            except CircuitOpenError:
                if retargets >= len(self._endpoints) - 1:
                    raise
                # this replica is cooling down; try the next one NOW —
                # the fleet front exists so one dead replica costs a
                # retarget, not a cooldown-long stall
                self._retarget("circuit_open")
                req.config_version = self._config_version
                retargets += 1
            except grpc.RpcError as err:
                if (
                    is_transient_code(err)
                    and retargets < len(self._endpoints) - 1
                ):
                    # transport retries against THIS replica are spent
                    # (it was killed / unreachable): route the round to
                    # the next replica, session fingerprint intact
                    self._retarget("transport")
                    req.config_version = self._config_version
                    retargets += 1
                    continue
                if (
                    err.code() == grpc.StatusCode.NOT_FOUND
                    and "SESSION_LOST" in (err.details() or "")
                    and not session_lost_retried
                ):
                    # the server evicted or restarted our resident session
                    # (fingerprint mismatch / registry miss). The request
                    # is a full snapshot already, so recovery is ONE clean
                    # re-solve: forget the stale fingerprint and resend.
                    # Counted, not raised — the caller never sees it.
                    from karpenter_tpu.utils.metrics import RESIDENT_ROUNDS

                    session_lost_retried = True
                    self._session_fpr = ""
                    RESIDENT_ROUNDS.inc(mode="invalidated")
                    continue
                if (
                    err.code() != grpc.StatusCode.FAILED_PRECONDITION
                    or attempt >= RECONFIGURE_RETRIES
                ):
                    raise
                # the solver restarted (or another client's Configure
                # superseded ours): re-Configure against the live server
                # and retry with the caller's REMAINING budget. The loop is
                # bounded so two clients ping-ponging Configures surface an
                # RpcError instead of livelocking (the server holds ONE
                # active configuration; see service.Configure).
                self._reconfigure()
                req.config_version = self._config_version
                if deadline is not None:
                    remaining = max(deadline - now_fn(), 0.0)
                    req.timeout_seconds = remaining
                    rpc_timeout = remaining + SOLVE_COMPILE_SLACK_SECONDS
                attempt += 1
        t_rpc = time.perf_counter()
        pods_by_uid = {p.uid: p for p in pods}
        if stream_acc is not None:
            # streamed path: the per-pod tables arrived as ordered chunk
            # frames; the final frame carried only the claim-level rest
            result = convert.result_from_stream(
                resp,
                stream_acc["claims"],
                stream_acc["existing"],
                stream_acc["unsched"],
                self.templates,
                self._catalog,
                pods_by_uid,
                existing_nodes,
            )
        else:
            result = convert.result_from_pb(
                resp,
                self.templates,
                self._catalog,
                pods_by_uid,
                existing_nodes,
            )
        if resp.dra_metadata_json:
            from karpenter_tpu.rpc.dra_codec import RemoteDRARound, decode_dra_metadata

            result.dra = RemoteDRARound(decode_dra_metadata(resp.dra_metadata_json))
        elif req.dra_problem_json:
            # a DRA-aware server always returns at least "{}" here; empty
            # bytes mean the server predates field 11 and SILENTLY solved
            # without any allocator — fall back to the local host engine
            # rather than placing claim pods with no device constraints
            from karpenter_tpu.utils.metrics import SOLVER_FALLBACK, SOLVER_HOST_FALLBACKS

            SOLVER_HOST_FALLBACKS.inc(reason="dra_server_predates")
            SOLVER_FALLBACK.inc(reason="dra_server_predates")
            from karpenter_tpu.controllers.provisioning.host_scheduler import (
                HostScheduler,
            )

            host = HostScheduler(
                self.templates,
                existing_nodes=[n.clone() for n in (existing_nodes or [])],
                budgets=budgets,
                topology=(
                    topology_factory(list(pods))
                    if topology_factory is not None
                    else topology
                ),
                volume_reqs=normalize_volume_reqs(volume_reqs),
                reserved_mode=(
                    reserved_mode if reserved_mode is not None else self.reserved_mode
                ),
                reserved_capacity_enabled=self.reserved_capacity_enabled,
                min_values_policy=self.min_values_policy,
                reserved_in_use=reserved_in_use,
                dra_problem=dra_problem,
                pod_volumes=pod_volumes,
                deadline=deadline,
                now=now,
            )
            return host.solve(list(pods))
        t_end = time.perf_counter()
        self.last_timings = {
            "encode_s": t_encode - t0,
            "device_s": t_rpc - t_encode,  # wire + remote solve
            "decode_s": t_end - t_rpc,
        }
        wire_s = (getattr(self, "last_stream", None) or {}).get("wire_blocked_s")
        if wire_s is not None:
            # the transport-blocked share of device_s: frame waits measured
            # inside _consume_stream (the remote round's wire attribution)
            self.last_timings["rpc_wire_s"] = wire_s
        return result

    def whatif_batch(
        self,
        pods,
        existing_nodes,
        budgets,
        scenarios,
        topology_factory=None,
        volume_reqs=None,
        reserved_in_use=None,
        bound_pods=None,
        pod_volumes=None,
    ):
        """Batched what-ifs over the wire: the scenarios' topology seeds
        rebuild SERVER-side from the shipped bound pods (excluding each
        scenario's nodes by name), so no callback crosses. Returns None —
        sequential-simulate fallback — when bound pods weren't provided
        or the server declines (same cases as the in-process prefilter)."""
        if bound_pods is None:
            return None
        req = pb.WhatIfRequest(config_version=self._config_version)
        self._encode_common(req, pods, existing_nodes, budgets, volume_reqs, reserved_in_use)
        from karpenter_tpu.models import labels as l

        for entry in bound_pods:
            bp, labels = entry[0], entry[1]
            name = entry[2] if len(entry) > 2 else labels.get(l.LABEL_HOSTNAME, "")
            if not name:
                # can't exclude this pod's node by name server-side —
                # verdicts would be unsound; decline to sequential
                return None
            b = req.bound_pods.add()
            b.pod.CopyFrom(convert.pod_to_pb(bp))
            b.node_labels.update(labels)
            b.node_name = name
        for excluded, active, counted in scenarios:
            s = req.scenarios.add()
            s.excluded_nodes.extend(sorted(excluded))
            s.active_pod_uids.extend(sorted(active))
            s.counted_pod_uids.extend(sorted(counted))
        for uid, vols in (pod_volumes or {}).items():
            req.pod_volumes.append(convert.volumes_to_pb(uid, vols))
        for attempt in range(RECONFIGURE_RETRIES + 1):
            try:
                resp = self._whatif(
                    req,
                    timeout=DEFAULT_SOLVE_BUDGET_SECONDS + SOLVE_COMPILE_SLACK_SECONDS,
                )
                break
            except grpc.RpcError as err:
                if err.code() == grpc.StatusCode.UNIMPLEMENTED:
                    # older solver without the WhatIf handler: sequential
                    # fallback, exactly the pre-RPC behavior
                    return None
                if is_transient_code(err):
                    # what-ifs are an optimization — a flaky wire degrades
                    # to the sequential-simulate path instead of failing
                    # the consolidation pass (the breaker still learns)
                    self._breaker.record_failure()
                    from karpenter_tpu.utils.metrics import SOLVER_FALLBACK

                    SOLVER_FALLBACK.inc(reason="whatif_transport")
                    return None
                if (
                    err.code() != grpc.StatusCode.FAILED_PRECONDITION
                    or attempt == RECONFIGURE_RETRIES
                ):
                    raise
                self._reconfigure()
                req.config_version = self._config_version
        if resp.declined:
            return None
        return [(v.feasible, v.new_claims) for v in resp.verdicts]
