"""RemoteScheduler: the control plane's view of the solver service.

Drop-in for TPUScheduler at the Provisioner seam — same solve() surface,
same SchedulingResult out — but the work happens across the wire
(solver.proto). This is the reference's decorator pattern
(pkg/cloudprovider/metrics/cloudprovider.go) applied to the scheduler
boundary: the Provisioner neither knows nor cares whether the solver is
in-process or remote.

Split of labor:
- Remote: the full relaxation ladder, NO_ROOM recovery, device dispatch,
  host-oracle fallbacks for volume alternatives — everything
  TPUScheduler.solve does, running next to the TPU. DRA solves cross the
  wire too: the DRAProblem is a self-contained snapshot, the server's
  host engine runs the allocation DFS, and the winning round's per-claim
  metadata ships back (rpc/dra_codec.py).
- whatif_batch crosses the wire as well (the WhatIf RPC): scenarios'
  topology seeds rebuild server-side from shipped bound pods; the client
  returns None (sequential-simulate fallback) when bound pods are
  unavailable or the server declines/predates the RPC.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import grpc

from karpenter_tpu.controllers.provisioning.host_scheduler import (
    SchedulingResult,
    normalize_volume_reqs,
)
from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import ClaimTemplate
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.rpc import solver_pb2 as pb
from karpenter_tpu.rpc import convert
from karpenter_tpu.rpc.codec import encode_templates
from karpenter_tpu.rpc.service import SERVICE_NAME

_RPC_OPTIONS = [
    ("grpc.max_receive_message_length", 256 * 1024 * 1024),
    ("grpc.max_send_message_length", 256 * 1024 * 1024),
]

# Every call carries a gRPC deadline — an unreachable or hung solver must
# never block the control plane indefinitely (the whole point of the Solve
# timeout work). Solve gets the request's own budget plus slack for the
# server's cold XLA compile (~20-70s per shape class, bench cold_s).
CONFIGURE_TIMEOUT_SECONDS = 120.0
# FAILED_PRECONDITION (config superseded / solver restart) retries per
# call: the server holds one active configuration, so concurrent clients
# with different configs ping-pong — bound it so contention surfaces
RECONFIGURE_RETRIES = 3
HEALTH_TIMEOUT_SECONDS = 10.0
SOLVE_COMPILE_SLACK_SECONDS = 600.0
DEFAULT_SOLVE_BUDGET_SECONDS = 600.0


class RemoteScheduler:
    """One instance per template/catalog set, like TPUScheduler; Configure
    happens eagerly at construction so the first Solve pays no config RTT."""

    # the Provisioner materializes bound_pods (topology count seeding) only
    # for schedulers that ship it across a wire — the in-process engine
    # reads the cluster through its topology_factory instead
    wants_bound_pods = True

    def __init__(
        self,
        endpoint: str,
        templates: list[ClaimTemplate],
        max_claims: Optional[int] = None,
        pod_pad: Optional[int] = None,
        reserved_mode: str = "fallback",
        reserved_capacity_enabled: bool = True,
        min_values_policy: str = "Strict",
        channel: Optional[grpc.Channel] = None,
    ):
        self.templates = templates
        self.reserved_mode = reserved_mode
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.min_values_policy = min_values_policy
        self._catalog = {}
        for t in templates:
            for it in t.instance_types:
                self._catalog.setdefault(it.name, it)
        self._channel = channel or grpc.insecure_channel(endpoint, options=_RPC_OPTIONS)

        def timed_stub(method, req_cls, resp_cls):
            # every crossing (including retries) records into the duration
            # histogram — the decorator-seam observability parity
            # (cloudprovider/metrics/cloudprovider.go wraps every SPI call)
            stub = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )

            def call(request, **kwargs):
                from karpenter_tpu.tracing.tracer import TRACER
                from karpenter_tpu.utils.metrics import SOLVER_RPC_DURATION

                with TRACER.span(f"rpc.{method}"):
                    # trace-context propagation: the server seeds its
                    # handler-thread spans from these so a remote Solve's
                    # server-side spans stitch into the CLIENT's trace
                    ctx = TRACER.context()
                    if ctx is not None:
                        md = list(kwargs.pop("metadata", None) or ())
                        md += [("ktpu-trace-id", ctx[0]), ("ktpu-span-id", ctx[1])]
                        kwargs["metadata"] = md
                    with SOLVER_RPC_DURATION.time(method=method):
                        return stub(request, **kwargs)

            return call

        self._configure = timed_stub("Configure", pb.ConfigureRequest, pb.ConfigureResponse)
        self._solve = timed_stub("Solve", pb.SolveRequest, pb.SolveResponse)
        self._whatif = timed_stub("WhatIf", pb.WhatIfRequest, pb.WhatIfResponse)
        self._health = timed_stub("Health", pb.HealthRequest, pb.HealthResponse)
        # streaming Solve: per-chunk partial tables arrive while the
        # server's pipelined decode still works on later chunks. Frames
        # are hand-framed bytes (tag + SolveResponse payload) so the
        # deserializer is the identity. Preferred by default; one
        # UNIMPLEMENTED (older server) downgrades to unary for the
        # channel's lifetime. KTPU_RPC_STREAM=0 opts out.
        import os as _os

        self._solve_stream = self._channel.unary_stream(
            f"/{SERVICE_NAME}/SolveStream",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=lambda b: b,
        )
        self._stream_ok = _os.environ.get("KTPU_RPC_STREAM", "1") != "0"
        self.last_stream: dict = {}
        req = pb.ConfigureRequest(
            templates_json=encode_templates(templates),
            reserved_mode=reserved_mode,
            reserved_capacity_enabled=reserved_capacity_enabled,
            min_values_policy=min_values_policy,
        )
        if max_claims is not None:
            req.max_claims = max_claims
        if pod_pad is not None:
            req.pod_pad = pod_pad
        self._configure_request = req
        self._reconfigure()
        self.last_timings: dict = {}

    def _reconfigure(self) -> None:
        self._config_version = self._configure(
            self._configure_request, timeout=CONFIGURE_TIMEOUT_SECONDS
        ).config_version

    def close(self) -> None:
        self._channel.close()

    def health(self) -> pb.HealthResponse:
        return self._health(pb.HealthRequest(), timeout=HEALTH_TIMEOUT_SECONDS)

    def _consume_stream(self, req, rpc_timeout: float):
        """Drive one SolveStream call to completion: accumulate the
        ordered per-pod tables from chunk frames (a reset frame discards
        them — a relaxation round or host fallback restarted the solve)
        and return (final SolveResponse, accumulated tables or None when
        the final frame was FULL). Tracing metadata and the RPC duration
        histogram mirror the unary stub."""
        from karpenter_tpu.rpc.service import (
            FRAME_CHUNK,
            FRAME_FINAL_FULL,
            FRAME_RESET,
        )
        from karpenter_tpu.tracing.tracer import TRACER
        from karpenter_tpu.utils.metrics import SOLVER_RPC_DURATION

        claims: dict[int, list[str]] = {}
        exist: list[tuple[str, str]] = []
        unsched: list[tuple[str, str]] = []
        final = None
        full = False
        n_frames = n_chunks = n_resets = 0
        with TRACER.span("rpc.SolveStream"):
            kwargs: dict = {"timeout": rpc_timeout}
            ctx = TRACER.context()
            if ctx is not None:
                kwargs["metadata"] = [
                    ("ktpu-trace-id", ctx[0]),
                    ("ktpu-span-id", ctx[1]),
                ]
            with SOLVER_RPC_DURATION.time(method="SolveStream"):
                for frame in self._solve_stream(req, **kwargs):
                    n_frames += 1
                    tag, payload = frame[:1], bytes(frame[1:])
                    if tag == FRAME_RESET:
                        n_resets += 1
                        claims.clear()
                        exist.clear()
                        unsched.clear()
                    elif tag == FRAME_CHUNK:
                        n_chunks += 1
                        part = pb.SolveResponse.FromString(payload)
                        for m in part.claims:
                            claims.setdefault(m.slot, []).extend(m.pod_uids)
                        for a in part.existing_assignments:
                            exist.append((a.pod_uid, a.node_name))
                        for u in part.unschedulable:
                            unsched.append((u.pod_uid, u.reason))
                    else:  # FINAL_SLIM / FINAL_FULL
                        final = pb.SolveResponse.FromString(payload)
                        full = tag == FRAME_FINAL_FULL
        if final is None:
            raise RuntimeError("SolveStream ended without a final frame")
        self.last_stream = {
            "frames": n_frames,
            "chunks": n_chunks,
            "resets": n_resets,
            "full": full,
        }
        if full:
            return final, None
        return final, {"claims": claims, "existing": exist, "unsched": unsched}

    # -- the TPUScheduler surface -----------------------------------------

    @staticmethod
    def _encode_common(req, pods, existing_nodes, budgets, volume_reqs, reserved_in_use):
        """The request fields Solve and WhatIf share — one encoding to
        keep the two wire paths from drifting."""
        for p in pods:
            req.pods.append(convert.pod_to_pb(p))
        for n in existing_nodes or []:
            req.existing_nodes.append(convert.existing_to_pb(n))
        for pool, res_map in (budgets or {}).items():
            req.budgets[pool].resources.update(res_map)
        for uid, alts in normalize_volume_reqs(volume_reqs).items():
            va = req.volume_reqs.add()
            va.pod_uid = uid
            for alt in alts:
                rs = va.alternatives.add()
                rs.requirements.extend(convert.reqs_to_pb(alt))
        for rid, n in (reserved_in_use or {}).items():
            req.reserved_in_use[rid] = n

    def solve(
        self,
        pods: Sequence[Pod],
        existing_nodes=None,
        budgets=None,
        topology=None,
        topology_factory=None,
        volume_reqs=None,
        reserved_mode=None,
        reserved_in_use=None,
        dra_problem=None,
        pod_volumes=None,
        deadline=None,
        now=None,
        bound_pods=None,
    ) -> SchedulingResult:
        t0 = time.perf_counter()
        req = pb.SolveRequest(config_version=self._config_version)
        if dra_problem is not None and any(p.spec.resource_claims for p in pods):
            # the DRAProblem is a self-contained snapshot (slices, classes,
            # claims, allocation seeds) — it crosses the wire and the
            # SERVER's host engine runs the allocation DFS
            # (rpc/dra_codec.py; allocator.go:231-296)
            from karpenter_tpu.rpc.dra_codec import encode_dra_problem

            req.dra_problem_json = encode_dra_problem(dra_problem)
        pods = list(pods)
        self._encode_common(req, pods, existing_nodes, budgets, volume_reqs, reserved_in_use)
        for entry in bound_pods or []:
            b = req.bound_pods.add()
            b.pod.CopyFrom(convert.pod_to_pb(entry[0]))
            b.node_labels.update(entry[1])
            if len(entry) > 2:
                b.node_name = entry[2]
        for uid, vols in (pod_volumes or {}).items():
            req.pod_volumes.append(convert.volumes_to_pb(uid, vols))
        if reserved_mode is not None:
            req.reserved_mode = reserved_mode
        if deadline is not None:
            # wall deadlines don't cross machines: ship the REMAINING
            # budget; the server re-anchors it on its own monotonic clock
            now_fn = now if now is not None else time.monotonic
            req.timeout_seconds = max(deadline - now_fn(), 0.0)
        rpc_timeout = (
            req.timeout_seconds if deadline is not None else DEFAULT_SOLVE_BUDGET_SECONDS
        ) + SOLVE_COMPILE_SLACK_SECONDS
        t_encode = time.perf_counter()
        stream_acc = None
        for attempt in range(RECONFIGURE_RETRIES + 1):
            try:
                if self._stream_ok:
                    try:
                        resp, stream_acc = self._consume_stream(req, rpc_timeout)
                    except grpc.RpcError as err:
                        if err.code() != grpc.StatusCode.UNIMPLEMENTED:
                            raise
                        # older server without the SolveStream handler:
                        # permanent downgrade to the unary path
                        self._stream_ok = False
                        resp, stream_acc = self._solve(req, timeout=rpc_timeout), None
                else:
                    resp, stream_acc = self._solve(req, timeout=rpc_timeout), None
                break
            except grpc.RpcError as err:
                if (
                    err.code() != grpc.StatusCode.FAILED_PRECONDITION
                    or attempt == RECONFIGURE_RETRIES
                ):
                    raise
                # the solver restarted (or another client's Configure
                # superseded ours): re-Configure against the live server
                # and retry with the caller's REMAINING budget. The loop is
                # bounded so two clients ping-ponging Configures surface an
                # RpcError instead of livelocking (the server holds ONE
                # active configuration; see service.Configure).
                self._reconfigure()
                req.config_version = self._config_version
                if deadline is not None:
                    remaining = max(deadline - now_fn(), 0.0)
                    req.timeout_seconds = remaining
                    rpc_timeout = remaining + SOLVE_COMPILE_SLACK_SECONDS
        t_rpc = time.perf_counter()
        pods_by_uid = {p.uid: p for p in pods}
        if stream_acc is not None:
            # streamed path: the per-pod tables arrived as ordered chunk
            # frames; the final frame carried only the claim-level rest
            result = convert.result_from_stream(
                resp,
                stream_acc["claims"],
                stream_acc["existing"],
                stream_acc["unsched"],
                self.templates,
                self._catalog,
                pods_by_uid,
                existing_nodes,
            )
        else:
            result = convert.result_from_pb(
                resp,
                self.templates,
                self._catalog,
                pods_by_uid,
                existing_nodes,
            )
        if resp.dra_metadata_json:
            from karpenter_tpu.rpc.dra_codec import RemoteDRARound, decode_dra_metadata

            result.dra = RemoteDRARound(decode_dra_metadata(resp.dra_metadata_json))
        elif req.dra_problem_json:
            # a DRA-aware server always returns at least "{}" here; empty
            # bytes mean the server predates field 11 and SILENTLY solved
            # without any allocator — fall back to the local host engine
            # rather than placing claim pods with no device constraints
            from karpenter_tpu.utils.metrics import SOLVER_HOST_FALLBACKS

            SOLVER_HOST_FALLBACKS.inc(reason="dra_server_predates")
            from karpenter_tpu.controllers.provisioning.host_scheduler import (
                HostScheduler,
            )

            host = HostScheduler(
                self.templates,
                existing_nodes=[n.clone() for n in (existing_nodes or [])],
                budgets=budgets,
                topology=(
                    topology_factory(list(pods))
                    if topology_factory is not None
                    else topology
                ),
                volume_reqs=normalize_volume_reqs(volume_reqs),
                reserved_mode=(
                    reserved_mode if reserved_mode is not None else self.reserved_mode
                ),
                reserved_capacity_enabled=self.reserved_capacity_enabled,
                min_values_policy=self.min_values_policy,
                reserved_in_use=reserved_in_use,
                dra_problem=dra_problem,
                pod_volumes=pod_volumes,
                deadline=deadline,
                now=now,
            )
            return host.solve(list(pods))
        t_end = time.perf_counter()
        self.last_timings = {
            "encode_s": t_encode - t0,
            "device_s": t_rpc - t_encode,  # wire + remote solve
            "decode_s": t_end - t_rpc,
        }
        return result

    def whatif_batch(
        self,
        pods,
        existing_nodes,
        budgets,
        scenarios,
        topology_factory=None,
        volume_reqs=None,
        reserved_in_use=None,
        bound_pods=None,
        pod_volumes=None,
    ):
        """Batched what-ifs over the wire: the scenarios' topology seeds
        rebuild SERVER-side from the shipped bound pods (excluding each
        scenario's nodes by name), so no callback crosses. Returns None —
        sequential-simulate fallback — when bound pods weren't provided
        or the server declines (same cases as the in-process prefilter)."""
        if bound_pods is None:
            return None
        req = pb.WhatIfRequest(config_version=self._config_version)
        self._encode_common(req, pods, existing_nodes, budgets, volume_reqs, reserved_in_use)
        from karpenter_tpu.models import labels as l

        for entry in bound_pods:
            bp, labels = entry[0], entry[1]
            name = entry[2] if len(entry) > 2 else labels.get(l.LABEL_HOSTNAME, "")
            if not name:
                # can't exclude this pod's node by name server-side —
                # verdicts would be unsound; decline to sequential
                return None
            b = req.bound_pods.add()
            b.pod.CopyFrom(convert.pod_to_pb(bp))
            b.node_labels.update(labels)
            b.node_name = name
        for excluded, active, counted in scenarios:
            s = req.scenarios.add()
            s.excluded_nodes.extend(sorted(excluded))
            s.active_pod_uids.extend(sorted(active))
            s.counted_pod_uids.extend(sorted(counted))
        for uid, vols in (pod_volumes or {}).items():
            req.pod_volumes.append(convert.volumes_to_pb(uid, vols))
        for attempt in range(RECONFIGURE_RETRIES + 1):
            try:
                resp = self._whatif(
                    req,
                    timeout=DEFAULT_SOLVE_BUDGET_SECONDS + SOLVE_COMPILE_SLACK_SECONDS,
                )
                break
            except grpc.RpcError as err:
                if err.code() == grpc.StatusCode.UNIMPLEMENTED:
                    # older solver without the WhatIf handler: sequential
                    # fallback, exactly the pre-RPC behavior
                    return None
                if (
                    err.code() != grpc.StatusCode.FAILED_PRECONDITION
                    or attempt == RECONFIGURE_RETRIES
                ):
                    raise
                self._reconfigure()
                req.config_version = self._config_version
        if resp.declined:
            return None
        return [(v.feasible, v.new_claims) for v in resp.verdicts]
