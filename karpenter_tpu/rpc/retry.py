"""Retry/backoff and circuit-breaking for the control->solver wire.

Pure mechanisms — no sockets, no globals — so the math is unit-testable
(tests/test_retry.py) and the RemoteScheduler composes them:

- ``Backoff``: exponential with multiplicative jitter, capped. The
  jitter draws from an injectable ``random.Random`` so a seeded RNG
  yields a deterministic delay sequence (the chaos suite's
  reproducibility contract extends to retry timing).
- ``CircuitBreaker``: closed -> open after N consecutive failures; open
  fails fast (no hammering a down solver from the provisioning loop)
  until the cooldown elapses; half-open admits one probe; a probe
  success closes, a probe failure re-opens. The clock is an injectable
  ``now()`` so transitions are testable without sleeping.

``injected_rpc_error`` manufactures grpc.RpcError-compatible errors for
the fault injector ("unavailable" / "exhausted" kinds): the client's
transient-code classification treats them exactly like a real transport
failure, which is the point.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional

import grpc

# codes worth a client-side retry: the request never ran to completion
# (transport cut, server overload, racing cancellation). NOT here:
# DEADLINE_EXCEEDED (the budget is spent — retrying overdrafts it) and
# FAILED_PRECONDITION (the re-Configure loop owns that).
TRANSIENT_CODES = frozenset(
    {
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.RESOURCE_EXHAUSTED,
        grpc.StatusCode.ABORTED,
    }
)


def is_transient_code(err: Exception) -> bool:
    return isinstance(err, grpc.RpcError) and err.code() in TRANSIENT_CODES


class InjectedRpcError(grpc.RpcError):
    """A grpc.RpcError the fault injector can raise from client-side
    fault points; carries just the surface the client consults."""

    def __init__(self, code: grpc.StatusCode, message: str):
        super().__init__(message)
        self._code = code
        self._message = message

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._message


def injected_rpc_error(kind: str, message: str) -> InjectedRpcError:
    code = {
        "unavailable": grpc.StatusCode.UNAVAILABLE,
        "exhausted": grpc.StatusCode.RESOURCE_EXHAUSTED,
    }[kind]
    return InjectedRpcError(code, message)


class Backoff:
    """delay(attempt) = min(base * multiplier**attempt, cap) scaled into
    [1 - jitter_frac, 1] by the RNG — full-jitter-style spreading that
    never exceeds the deterministic ceiling, so cap math stays exact."""

    def __init__(
        self,
        base_s: float = 0.2,
        cap_s: float = 30.0,
        multiplier: float = 2.0,
        jitter_frac: float = 0.5,
        rng: Optional[random.Random] = None,
    ):
        if not 0.0 <= jitter_frac <= 1.0:
            raise ValueError(f"jitter_frac {jitter_frac} outside [0, 1]")
        self.base_s = base_s
        self.cap_s = cap_s
        self.multiplier = multiplier
        self.jitter_frac = jitter_frac
        self._rng = rng or random.Random()

    def ceiling(self, attempt: int) -> float:
        return min(self.base_s * self.multiplier**attempt, self.cap_s)

    def delay(self, attempt: int) -> float:
        raw = self.ceiling(attempt)
        if not self.jitter_frac:
            return raw
        return raw * (1.0 - self.jitter_frac * self._rng.random())


class CircuitBreaker:
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        failure_threshold: int = 5,
        cooldown_s: float = 30.0,
        now: Callable[[], float] = time.monotonic,
        on_transition: Optional[Callable[[str], None]] = None,
    ):
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._now = now
        self._on_transition = on_transition
        self.state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0

    def _transition(self, to: str) -> None:
        if self.state == to:
            return
        self.state = to
        if self._on_transition is not None:
            self._on_transition(to)

    def allow(self) -> bool:
        """May a call proceed right now? An open breaker past its
        cooldown moves to half-open and admits the probe."""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN:
            if self._now() - self._opened_at >= self.cooldown_s:
                self._transition(self.HALF_OPEN)
                return True
            return False
        return True  # half-open: the probe is in flight

    def record_success(self) -> None:
        self._failures = 0
        self._transition(self.CLOSED)

    def record_failure(self) -> None:
        if self.state == self.HALF_OPEN:
            # the probe failed: straight back to open, fresh cooldown
            self._opened_at = self._now()
            self._transition(self.OPEN)
            return
        self._failures += 1
        if self._failures >= self.failure_threshold:
            self._opened_at = self._now()
            self._transition(self.OPEN)


class CircuitOpenError(ConnectionError):
    """Raised instead of dialing when the target's breaker is open."""
