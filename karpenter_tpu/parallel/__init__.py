"""Device-mesh sharding of the solver.

The reference scales with goroutine fan-outs and kube-apiserver watches
(SURVEY.md §2.9); the TPU build scales by sharding the dense problem
tensors over a jax.sharding.Mesh and letting XLA insert ICI collectives:

  "it" axis   instance-type (tensor-parallel) sharding of the catalog —
              the [claims × instance-types] triple mask is computed on
              shards and any-reduced (psum) across devices
  "dp" axis   batch-of-problems data parallelism — consolidation what-ifs
              and bucketed scheduling batches are independent problems
              vmapped over the leading axis

DCN enters only for multi-slice scale-out; a single solve call never
crosses it.
"""

from karpenter_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    pad_axis_to,
    shard_instance_types,
    sharded_solve,
)
