"""Device-mesh sharding of the solver.

The reference scales with goroutine fan-outs and kube-apiserver watches
(SURVEY.md §2.9); the TPU build scales by sharding the dense problem
tensors over a jax.sharding.Mesh and letting XLA insert ICI collectives:

  "it" axis   instance-type (tensor-parallel) sharding of the catalog —
              the [claims × instance-types] triple mask is computed on
              shards and any-reduced (psum) across devices
  "dp" axis   claims/pods data parallelism — the hot [W, T] viability
              masks, bank [NCAP, T] columns and kscan [W, T, GR] grid
              shard their claims axis over dp rows (ops.solver.shard_hint
              annotations), and the pipelined fill's chunk groups solve
              SPECULATIVELY one-per-dp-row in a single batched dispatch,
              merged exact-or-replay against the frozen-bank contract
              (ops.solver.solve_fill_dp / merge_shard_fill)

The split honors the KTPU_MESH="<dp>x<it>" env override (validated
against jax.device_count()), else auto-factorizes. DCN enters only for
multi-slice scale-out; a single solve call never crosses it.
"""

from karpenter_tpu.parallel.mesh import (  # noqa: F401
    factorize_mesh,
    make_mesh,
    pad_axis_to,
    parse_mesh_override,
    shard_instance_types,
    sharded_solve,
)
