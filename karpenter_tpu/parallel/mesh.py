"""Mesh construction and sharded solve entry points."""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu.ops import solver as ops_solver
from karpenter_tpu.ops.encode import InstanceTypeTensors, ReqSetTensors


def factorize_mesh(n: int) -> tuple[int, int]:
    """The most square (dp, it) split of n with it >= dp, so the
    instance-type axis (the bigger tensor dimension) gets the larger
    share."""
    dp = 1
    for cand in range(int(math.isqrt(n)), 0, -1):
        if n % cand == 0:
            dp = cand
            break
    return dp, n // dp


def parse_mesh_override(spec: str) -> tuple[int, int]:
    """Parse a KTPU_MESH override of the form "<dp>x<it>" (e.g. "2x4").
    Raises ValueError with a message naming the knob on malformed input."""
    parts = spec.lower().split("x")
    try:
        if len(parts) != 2:
            raise ValueError(spec)
        dp, it = int(parts[0]), int(parts[1])
        if dp < 1 or it < 1:
            raise ValueError(spec)
    except ValueError:
        raise ValueError(
            f"KTPU_MESH={spec!r} is not a valid mesh spec; expected "
            '"<dp>x<it>" with positive integers, e.g. "2x4"'
        ) from None
    return dp, it


def make_mesh(n_devices: Optional[int] = None, axis_names: tuple[str, str] = ("dp", "it")) -> Mesh:
    """A 2D (dp × it) mesh over the available devices.

    The split comes from the KTPU_MESH env override ("<dp>x<it>", e.g.
    "2x4" — validated against jax.device_count()) when set, else from the
    most square auto-factorization of n_devices (factorize_mesh).
    """
    import os

    devices = jax.devices()
    override = os.environ.get("KTPU_MESH", "").strip()
    if override:
        dp, it = parse_mesh_override(override)
        n = dp * it
        if n_devices is not None and n_devices != n:
            raise ValueError(
                f"KTPU_MESH={override!r} asks for {dp}x{it}={n} devices but "
                f"the caller requested {n_devices}; drop one of the two"
            )
        if len(devices) < n:
            raise ValueError(
                f"KTPU_MESH={override!r} asks for {dp}x{it}={n} devices, "
                f"have {len(devices)} (jax.device_count()); use a split "
                f"whose product is <= the device count"
            )
    else:
        n = n_devices or len(devices)
        if len(devices) < n:
            raise ValueError(f"need {n} devices, have {len(devices)}")
        dp, it = factorize_mesh(n)
    return Mesh(np.array(devices[:n]).reshape(dp, it), axis_names)


def pad_axis_to(x: jnp.ndarray, axis: int, size: int, fill=0):
    if x.shape[axis] == size:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, size - x.shape[axis])
    return jnp.pad(x, pad, constant_values=fill)


def _pad_reqs(reqs: ReqSetTensors, size: int) -> ReqSetTensors:
    """Pad the batch axis; padded rows get the 'matches nothing' encoding so
    sharded padding types never become viable."""
    from karpenter_tpu.ops.encode import INT_MAX, INT_MIN

    return ReqSetTensors(
        mask=pad_axis_to(reqs.mask, 0, size, False),
        inf=pad_axis_to(reqs.inf, 0, size, False),
        excl=pad_axis_to(reqs.excl, 0, size, False),
        gte=pad_axis_to(reqs.gte, 0, size, INT_MIN),
        lte=pad_axis_to(reqs.lte, 0, size, INT_MAX),
        defined=pad_axis_to(reqs.defined, 0, size, False),
    )


def shard_instance_types(it: InstanceTypeTensors, mesh: Mesh) -> InstanceTypeTensors:
    """Shard the catalog over the mesh's "it" axis (pad T to a multiple).

    Padded types are invalid + match nothing + fit nothing, so results are
    identical to the unsharded solve.
    """
    n_it = mesh.shape["it"]
    T = it.alloc.shape[0]
    T_pad = ((T + n_it - 1) // n_it) * n_it
    padded = InstanceTypeTensors(
        reqs=_pad_reqs(it.reqs, T_pad),
        alloc=pad_axis_to(it.alloc, 0, T_pad, -np.inf),
        cap=pad_axis_to(it.cap, 0, T_pad, np.inf),  # inf: padding never passes budget filters
        group_valid=pad_axis_to(it.group_valid, 0, T_pad, False),
        zc_avail=pad_axis_to(it.zc_avail, 0, T_pad, False),
        price_zc=pad_axis_to(it.price_zc, 0, T_pad, np.inf),
        valid=pad_axis_to(it.valid, 0, T_pad, False),
        res_ofs=pad_axis_to(it.res_ofs, 0, T_pad, False),
    )
    shard = NamedSharding(mesh, P("it"))
    return InstanceTypeTensors(
        reqs=ReqSetTensors(*(jax.device_put(x, shard) for x in padded.reqs)),
        alloc=jax.device_put(padded.alloc, shard),
        cap=jax.device_put(padded.cap, shard),
        group_valid=jax.device_put(padded.group_valid, shard),
        zc_avail=jax.device_put(padded.zc_avail, shard),
        price_zc=jax.device_put(padded.price_zc, shard),
        valid=jax.device_put(padded.valid, shard),
        res_ofs=jax.device_put(padded.res_ofs, shard),
    )


def sharded_solve(
    pods,
    pod_tol,
    pod_it_allow,
    pod_exist_ok,
    pod_ports,
    pod_port_conf,
    pod_vols,
    exist,
    it_sharded: InstanceTypeTensors,
    templates,
    well_known,
    topo,
    pod_topo,
    *,
    zone_kid: int,
    ct_kid: int,
    n_claims: int,
    mv_active: bool = False,
    topo_kids: tuple = (),
    res_cap0=None,
    rid_kid: int = -1,
    res_vid: int = -1,
    res_active: bool = False,
    res_strict: bool = False,
    window: int = 0,
):
    """Run ops_solver.solve with the catalog sharded over the "it" mesh axis.

    The solve body is pure jnp, so GSPMD partitions the [claims × types]
    triple-mask computation across devices and inserts the any-reduce
    collectives over ICI. The per-type template and pod-allow masks are
    padded to the sharded catalog size; everything else is replicated.

    The active window (claims axis W) shards exactly like the full claims
    axis did: the hot [W, T] viability masks and bank [NCAP, T] columns
    follow the catalog's "it" sharding through GSPMD propagation, while
    the [W, K, V] requirement tensors stay replicated — `window` threads
    straight through to ops_solver.solve.
    """
    T_pad = it_sharded.alloc.shape[0]
    # every per-type tensor must grow with the padded catalog: the template
    # membership mask [G, T] and the minValues value slab [T, J, V] (padded
    # types contribute no distinct values, so floors count identically)
    tmpl = templates._replace(
        its=pad_axis_to(templates.its, 1, T_pad, False),
        mv_it_values=pad_axis_to(templates.mv_it_values, 0, T_pad, False),
    )
    allow = pad_axis_to(pod_it_allow, 1, T_pad, False)
    return ops_solver.solve(
        pods,
        pod_tol,
        allow,
        pod_exist_ok,
        pod_ports,
        pod_port_conf,
        pod_vols,
        exist,
        it_sharded,
        tmpl,
        well_known,
        topo,
        pod_topo,
        zone_kid=zone_kid,
        ct_kid=ct_kid,
        n_claims=n_claims,
        mv_active=mv_active,
        topo_kids=topo_kids,
        res_cap0=res_cap0,
        rid_kid=rid_kid,
        res_vid=res_vid,
        res_active=res_active,
        res_strict=res_strict,
        window=window,
    )
