"""Admission control: a bounded per-replica solve queue.

One solve runs on the device at a time (that serialization already
exists — the service's solve lock); admission control decides what the
OTHERS do while they wait. The queue holds at most ``capacity`` waiting
rounds with per-tenant fair ordering (round-robin across tenants, FIFO
within one); when a new round arrives over a full queue, the OLDEST
waiting round is shed — its caller gets the "shed" verdict and re-routes
onto the existing host-solve ladder instead of stalling, counted in
``ktpu_fleet_shed_total{reason="queue_full"}``. Shedding the oldest (not
the newcomer) bounds every round's queue time: a round either reaches
the device within ~capacity turns or degrades to a host solve, and a
single tenant flooding the queue cannot starve the others past its
round-robin share.
"""

from __future__ import annotations

import threading


class AdmissionQueue:
    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._cond = threading.Condition()
        self._running = False
        self._waiting: list = []  # arrival order; entries: {tenant, verdict}
        self._rr_last: str = ""
        self.shed_count = 0

    def acquire(self, tenant: str) -> str:
        """Block until this round may run ("run") or is shed ("shed").
        A "run" verdict holds the solve slot: the caller MUST release().
        A "shed" verdict holds nothing — go straight to the host ladder."""
        with self._cond:
            if not self._running and not self._waiting:
                self._running = True
                return "run"
            if len(self._waiting) >= self.capacity:
                oldest = self._waiting.pop(0)
                oldest["verdict"] = "shed"
                self.shed_count += 1
                self._cond.notify_all()
            entry = {"tenant": tenant, "verdict": None}
            self._waiting.append(entry)
            while entry["verdict"] is None:
                self._cond.wait()
            return entry["verdict"]

    def release(self) -> None:
        """Free the solve slot and hand it to the fairest waiter: the
        first round of the next tenant after the last-served one."""
        with self._cond:
            if not self._waiting:
                self._running = False
                return
            tenants = []
            for e in self._waiting:
                if e["tenant"] not in tenants:
                    tenants.append(e["tenant"])
            if self._rr_last in tenants:
                pick = tenants[(tenants.index(self._rr_last) + 1) % len(tenants)]
            else:
                pick = tenants[0]
            for i, e in enumerate(self._waiting):
                if e["tenant"] == pick:
                    entry = self._waiting.pop(i)
                    break
            entry["verdict"] = "run"
            self._rr_last = pick
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return len(self._waiting)
