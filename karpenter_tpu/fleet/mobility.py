"""Capsule-based session mobility.

A resident session is pure function of its transcript: the scheduler
config plus the cumulative per-round pod lists determine every gate
decision, every claim, and therefore every blake2s round sig. So a
session capsule — a guard-bundle doc whose ``rounds`` field is the FULL
cumulative chain transcript (``obs.ledger.session_chain_transcript``),
not the ledger's compressed two-round form — is sufficient to rebuild
the session anywhere: materialize the bundle, replay each round through
a fresh ``ResidentSession`` on the adopting replica's scheduler, and
compare the rebuilt fingerprint against the one the client presented.

Exactness argument: capsule transcript => replayed state chain =>
fingerprint equality. The replay runs the SAME delta gates the original
rounds ran against the same inputs, so a chain that stayed resident
reproduces bit-identical round sigs; any divergence (a gate falls
differently, a round is unschedulable, the capsule was built under a
different cluster shape) surfaces as a fingerprint mismatch and the
adopting replica refuses — the client then gets the ordinary
SESSION_LOST cold re-solve, never a silently different session.
"""

from __future__ import annotations

import base64
from typing import Optional, Tuple

from karpenter_tpu.guard import bundle as guard_bundle


def export_session(sid: str, session) -> Optional[dict]:
    """Session -> portable capsule doc (None when there is no resident
    state to export — snapshot-mode rounds have nothing to hand off)."""
    from karpenter_tpu.obs import ledger as obs_ledger

    chain = obs_ledger.session_chain_transcript(session)
    if not chain:
        return None
    r = session._r
    detail = {"fingerprint": session.fingerprint, "session_id": sid}
    from karpenter_tpu.obs import tracectx

    trace = tracectx.current_dict()
    if trace is not None:
        # the cutting round's fleet trace rides the capsule: an adopting
        # replica replays under the SAME trace_id (one hop further), so
        # the handoff stitches across both replicas in /debug/trace/<id>
        detail["trace"] = trace
    try:
        return guard_bundle.make_bundle(
            "fleet",
            "session mobility capsule",
            session.sched,
            dict(r["pod_by_uid"]),
            chain,
            existing_nodes=r["exist_pristine"],
            detail=detail,
        )
    except Exception:
        return None  # export is best-effort; the cold path still works


def adopt(sched, doc: dict, expect_fpr: str) -> Tuple[Optional[object], str]:
    """Rebuild a session from a capsule on this replica's scheduler.

    Returns ``(session, "adopted")`` on success, else ``(None, outcome)``
    with outcome one of shape_mismatch / replay_failed /
    fingerprint_mismatch (the ktpu_fleet_handoffs_total vocabulary).
    """
    from karpenter_tpu.controllers.provisioning.scheduler import ResidentSession
    from karpenter_tpu.rpc.codec import encode_templates

    shape = doc.get("scheduler") or {}
    if (
        shape.get("max_claims") != int(sched.max_claims)
        or base64.b64decode(doc.get("templates_b64", ""))
        != encode_templates(sched.templates)
    ):
        # the capsule was cut under a different cluster shape; replaying
        # it here could not reproduce the chain, don't try
        return None, "shape_mismatch"
    from karpenter_tpu.obs import tracectx

    ctx = tracectx.TraceContext.from_dict((doc.get("detail") or {}).get("trace"))
    try:
        with tracectx.activate(ctx.child() if ctx is not None else None):
            _, pods_by_uid, existing, rounds = guard_bundle.materialize(doc)
            session = ResidentSession.replay_chain(
                sched, pods_by_uid, existing, rounds
            )
    except Exception:
        return None, "replay_failed"
    if session is None:
        return None, "replay_failed"
    if session.fingerprint != expect_fpr:
        return None, "fingerprint_mismatch"
    return session, "adopted"
