"""Fleet-scale serving: N solver replicas behind a routing front.

The vertical stack (encode -> device solve -> decode, resident sessions,
guardrails, the round ledger) is all per-process. This package is the
horizontal layer over it:

* ``bus`` — a pluggable guardrail bus (in-process hub for tests, an
  append-only file backend for multi-process fleets) carrying quarantine
  trips, audit verdicts, session capsules, and compile-cache warmups.
* ``mobility`` — capsule-based session mobility: a lost resident session
  is rebuilt on a new replica by replaying the ledger's cumulative
  transcript; the rebuilt blake2s round-sig chain must equal the lost
  fingerprint before the replica trusts it.
* ``member`` — one replica's bus endpoint: wires the guard/obs listener
  hooks to the bus, pumps remote messages, archives peers' session
  capsules for adoption.
* ``admission`` — a bounded per-replica solve queue with per-tenant fair
  ordering; overload sheds the oldest waiting round onto the host-solve
  ladder instead of stalling (``ktpu_fleet_shed_total``).
"""

from karpenter_tpu.fleet.admission import AdmissionQueue
from karpenter_tpu.fleet.bus import FileBus, InProcessHub
from karpenter_tpu.fleet.member import FleetMember

__all__ = ["AdmissionQueue", "FileBus", "InProcessHub", "FleetMember"]
