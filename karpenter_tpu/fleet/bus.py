"""Pluggable guardrail bus backends.

A bus is an append-only per-topic log with offset-based consumption:

    publish(topic, msg)            # msg is a JSON-serializable dict
    fetch(topic, offset) -> (msgs, new_offset)

Consumers own their offsets (each FleetMember remembers where it is per
topic), so the bus itself is stateless about subscribers — a replica that
restarts simply re-reads from 0 and skips its own origin ids. Two
backends:

* ``InProcessHub`` — a dict of lists; the test double and the backend
  for co-located replicas in one process (bench --fleet).
* ``FileBus`` — one JSONL file per topic in a shared directory; each
  publish is a single O_APPEND write (atomic for line-sized payloads on
  local filesystems), each fetch resumes from a byte offset and only
  consumes complete lines, so a torn tail line is re-read next pump.
"""

from __future__ import annotations

import json
import os
import threading
from typing import List, Tuple

TOPICS = ("quarantine", "audit", "session", "compile")


class InProcessHub:
    """Shared-memory bus: every member holds a reference to the same hub."""

    def __init__(self):
        self._lock = threading.Lock()
        self._topics: dict = {}

    def publish(self, topic: str, msg: dict) -> None:
        with self._lock:
            self._topics.setdefault(topic, []).append(dict(msg))

    def fetch(self, topic: str, offset: int) -> Tuple[List[dict], int]:
        with self._lock:
            log = self._topics.get(topic, ())
            msgs = [dict(m) for m in log[offset:]]
            return msgs, len(log)


class FileBus:
    """Shared-directory bus for multi-process fleets (KTPU_FLEET_BUS=file,
    KTPU_FLEET_BUS_DIR=<dir>)."""

    def __init__(self, dirpath: str):
        self._dir = dirpath
        os.makedirs(dirpath, exist_ok=True)

    def _path(self, topic: str) -> str:
        # topics are a closed internal vocabulary, but never let a
        # malformed one escape the bus directory
        safe = "".join(c for c in topic if c.isalnum() or c in "-_")
        return os.path.join(self._dir, f"{safe}.jsonl")

    def publish(self, topic: str, msg: dict) -> None:
        line = json.dumps(msg, sort_keys=True) + "\n"
        fd = os.open(self._path(topic), os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)

    def fetch(self, topic: str, offset: int) -> Tuple[List[dict], int]:
        path = self._path(topic)
        try:
            with open(path, "rb") as fh:
                fh.seek(offset)
                chunk = fh.read()
        except FileNotFoundError:
            return [], offset
        if not chunk:
            return [], offset
        # only complete lines; a partial tail (a concurrent publish in
        # flight) stays unconsumed until it gains its newline
        end = chunk.rfind(b"\n")
        if end < 0:
            return [], offset
        msgs = []
        for raw in chunk[: end + 1].splitlines():
            if not raw.strip():
                continue
            try:
                msgs.append(json.loads(raw))
            except ValueError:
                continue  # skip a corrupt line rather than wedge the pump
        return msgs, offset + end + 1
