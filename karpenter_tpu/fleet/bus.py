"""Pluggable guardrail bus backends.

A bus is an append-only per-topic log with offset-based consumption:

    publish(topic, msg)            # msg is a JSON-serializable dict
    fetch(topic, offset) -> (msgs, new_offset)

Consumers own their offsets (each FleetMember remembers where it is per
topic), so the bus itself is stateless about subscribers — a replica that
restarts simply re-reads from 0 and skips its own origin ids. Two
backends:

* ``InProcessHub`` — a dict of lists; the test double and the backend
  for co-located replicas in one process (bench --fleet).
* ``FileBus`` — one JSONL file per topic in a shared directory; each
  publish is a single O_APPEND write (atomic for line-sized payloads on
  local filesystems), each fetch resumes from a byte offset and only
  consumes complete lines, so a torn tail line is re-read next pump.

``FileBus`` logs are size-capped (``KTPU_BUS_MAX_BYTES``, 0 = unbounded):
when a topic log would exceed the cap, its oldest complete lines are
dropped and the surviving tail rewritten behind a one-line header that
records the logical *base offset* — how many bytes of history were ever
compacted away.  Offsets handed to ``fetch`` are logical positions in the
infinite append stream, so a live subscriber's offset keeps meaning the
same bytes across any number of rotations; only a subscriber parked
before the base (slower than a whole rotation) loses messages, and it
resumes cleanly from the base rather than mid-line.
"""

from __future__ import annotations

import json
import os
import threading
from typing import List, Tuple

from ..utils import metrics

TOPICS = ("quarantine", "audit", "session", "compile", "telemetry")

_HEADER_MAGIC = b"#"


def _env_max_bytes() -> int:
    try:
        return max(0, int(os.environ.get("KTPU_BUS_MAX_BYTES", "0") or 0))
    except ValueError:
        return 0


class InProcessHub:
    """Shared-memory bus: every member holds a reference to the same hub."""

    def __init__(self):
        self._lock = threading.Lock()
        self._topics: dict = {}

    def publish(self, topic: str, msg: dict) -> None:
        with self._lock:
            self._topics.setdefault(topic, []).append(dict(msg))

    def fetch(self, topic: str, offset: int) -> Tuple[List[dict], int]:
        with self._lock:
            log = self._topics.get(topic, ())
            msgs = [dict(m) for m in log[offset:]]
            return msgs, len(log)


class FileBus:
    """Shared-directory bus for multi-process fleets (KTPU_FLEET_BUS=file,
    KTPU_FLEET_BUS_DIR=<dir>)."""

    def __init__(self, dirpath: str, max_bytes=None):
        self._dir = dirpath
        self._max_bytes = _env_max_bytes() if max_bytes is None else max(0, int(max_bytes))
        os.makedirs(dirpath, exist_ok=True)

    def _path(self, topic: str) -> str:
        # topics are a closed internal vocabulary, but never let a
        # malformed one escape the bus directory
        safe = "".join(c for c in topic if c.isalnum() or c in "-_")
        return os.path.join(self._dir, f"{safe}.jsonl")

    @staticmethod
    def _split_header(data: bytes) -> Tuple[int, int]:
        """(base_offset, header_len) of a topic file's raw bytes.

        Pre-compaction files have no header: base 0, header 0.  A reader
        that predates compaction treats the header line as corrupt JSON
        and skips it, so mixed-version fleets degrade to at-least-once
        rather than wedging.
        """
        if not data.startswith(_HEADER_MAGIC):
            return 0, 0
        nl = data.find(b"\n")
        if nl < 0:
            return 0, 0
        try:
            base = int(json.loads(data[1:nl].decode())["base"])
        except (ValueError, KeyError, TypeError):
            return 0, 0
        return max(0, base), nl + 1

    def publish(self, topic: str, msg: dict) -> None:
        line = (json.dumps(msg, sort_keys=True) + "\n").encode()
        path = self._path(topic)
        if self._max_bytes:
            self._maybe_compact(topic, path, incoming=len(line))
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, line)
        finally:
            os.close(fd)

    def _maybe_compact(self, topic: str, path: str, incoming: int) -> None:
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        if size + incoming <= self._max_bytes:
            return
        # single-winner compaction: concurrent publishers skip rather
        # than race the rewrite (their appends land after os.replace at
        # worst into the pre-compaction inode, same as a torn publish)
        lock = path + ".lock"
        try:
            lock_fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError:
            return
        try:
            with open(path, "rb") as fh:
                data = fh.read()
            base, hlen = self._split_header(data)
            body = data[hlen:]
            # keep the newest complete lines down to half the cap so
            # compactions amortize instead of firing on every publish
            keep_budget = max(incoming, self._max_bytes // 2)
            cut = 0
            while len(body) - cut > keep_budget:
                nl = body.find(b"\n", cut)
                if nl < 0:
                    break
                cut = nl + 1
            if cut == 0:
                return
            new_base = base + cut
            header = _HEADER_MAGIC + json.dumps({"base": new_base}).encode() + b"\n"
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(header + body[cut:])
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            metrics.FLEET_BUS_ROTATIONS.inc(topic=topic)
        finally:
            os.close(lock_fd)
            try:
                os.unlink(lock)
            except OSError:
                pass

    def fetch(self, topic: str, offset: int) -> Tuple[List[dict], int]:
        path = self._path(topic)
        try:
            with open(path, "rb") as fh:
                head = fh.read(4096)
                base, hlen = self._split_header(head)
                if offset < base:
                    # the prefix this subscriber never consumed was
                    # compacted away; resume at the oldest surviving line
                    offset = base
                fh.seek(hlen + (offset - base))
                chunk = fh.read()
        except FileNotFoundError:
            return [], offset
        if not chunk:
            return [], offset
        # only complete lines; a partial tail (a concurrent publish in
        # flight) stays unconsumed until it gains its newline
        end = chunk.rfind(b"\n")
        if end < 0:
            return [], offset
        msgs = []
        for raw in chunk[: end + 1].splitlines():
            if not raw.strip():
                continue
            try:
                msgs.append(json.loads(raw))
            except ValueError:
                continue  # skip a corrupt line rather than wedge the pump
        return msgs, offset + end + 1
