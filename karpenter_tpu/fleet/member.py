"""One replica's endpoint on the guardrail bus.

A ``FleetMember`` is the glue between a replica's in-process guardrails
and the fleet: it subscribes to the local listener hooks (quarantine
trips, audit verdicts, fresh jit compiles) and republishes them on the
bus; ``pump()`` drains the bus and applies peers' messages locally —
a remote quarantine trip trips the local breaker (source="remote", so it
is not re-published in a loop), peers' session capsules go into an
archive the RPC service adopts from on SESSION_LOST, and compile
announcements mark kernel keys warm (``ktpu_fleet_warm_announced_total``
— a replica sharing a persistent compile cache knows the key is already
paid for).

The member also archives its OWN published capsules: a single replica
whose registry evicted a session (chaos ``rpc.session.evict``, LRU
capacity) can re-adopt from its own archive without any peer.
"""

from __future__ import annotations

import os
import threading
import uuid
from collections import deque
from typing import Optional

from karpenter_tpu.fleet import bus as bus_mod
from karpenter_tpu.fleet import mobility
from karpenter_tpu.utils.metrics import (
    FLEET_BUS_MESSAGES,
    FLEET_WARM_ANNOUNCED,
)

_MAX_ARCHIVE = 64
_MAX_REMOTE_AUDITS = 256
_MAX_REMOTE_ROUNDS = 512


class FleetMember:
    def __init__(self, bus, replica_id: str = "", quarantine=None):
        from karpenter_tpu.guard import audit as guard_audit
        from karpenter_tpu.guard.quarantine import QUARANTINE
        from karpenter_tpu.obs import observatory

        self.bus = bus
        self.replica_id = replica_id or f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self._quarantine = QUARANTINE if quarantine is None else quarantine
        self._lock = threading.Lock()
        self._offsets = {t: 0 for t in bus_mod.TOPICS}
        #: sid -> (fingerprint, capsule doc) for adoption, newest wins
        self._archive: "dict" = {}
        self._archive_order: deque = deque()
        #: fingerprints already published per sid (skip unchanged rounds)
        self._published_fpr: dict = {}
        self.remote_audits: deque = deque(maxlen=_MAX_REMOTE_AUDITS)
        #: peers' compact round records (telemetry frames) for fleetobs
        self.remote_rounds: deque = deque(maxlen=_MAX_REMOTE_ROUNDS)
        self.warm_kernels: set = set()
        self._closed = False
        # the fleet observatory reads pumped telemetry frames off live
        # members (weak registration; a collected member drops out)
        from karpenter_tpu.obs import fleetobs

        fleetobs.register(self)
        self._quarantine.add_listener(self._on_trip)
        guard_audit.add_audit_listener(self._on_audit)
        observatory.add_compile_listener(self._on_compile)

    # -- local guardrails -> bus -------------------------------------------

    def _publish(self, topic: str, msg: dict) -> None:
        msg = dict(msg, origin=self.replica_id)
        if "trace" not in msg:
            from karpenter_tpu.obs import tracectx

            trace = tracectx.current_dict()
            if trace is not None:
                msg["trace"] = trace
        try:
            self.bus.publish(topic, msg)
        except Exception:
            return  # the bus must never take the solve path down with it
        FLEET_BUS_MESSAGES.inc(topic=topic, direction="published")

    def _on_trip(self, path: str, reason: str, ttl: float, source: str) -> None:
        if source != "local":
            return  # remote trips came FROM the bus; don't echo them back
        from karpenter_tpu.obs.slo import SLO

        SLO.observe_availability(False, kind="quarantine")
        self._publish(
            "quarantine", {"path": path, "reason": reason, "ttl_s": ttl}
        )

    def _on_audit(self, path: str, verdict: str, reason: str) -> None:
        self._publish(
            "audit", {"path": path, "verdict": verdict, "reason": reason}
        )

    def _on_compile(self, note: dict) -> None:
        self._publish("compile", note)

    def publish_session(self, sid: str, session) -> None:
        """Announce this session's current capsule (skipped when nothing
        is resident or the chain has not advanced since the last one)."""
        fpr = session.fingerprint
        if not fpr or self._published_fpr.get(sid) == fpr:
            return
        doc = mobility.export_session(sid, session)
        if doc is None:
            return
        self._published_fpr[sid] = fpr
        # own archive first: a local eviction can re-adopt without peers
        self._archive_put(sid, fpr, doc)
        self._publish("session", {"sid": sid, "fpr": fpr, "doc": doc})

    def publish_round(self, rec: dict) -> None:
        """Announce one solved round as a compact telemetry frame so peers
        (and fleetobs) see the fleet's timeline without sharing a ledger
        directory. ``rec`` is a round-ledger record; only its wire-safe
        keys ride the bus."""
        from karpenter_tpu.obs import ledger as obs_ledger

        frame = obs_ledger.telemetry_frame(rec)
        if frame is not None:
            self._publish("telemetry", frame)

    # -- bus -> local -------------------------------------------------------

    def pump(self) -> int:
        """Drain every topic and apply peers' messages. Returns how many
        foreign messages were applied (cheap no-op when the bus is idle —
        the service calls this once per solve round)."""
        applied = 0
        for topic in bus_mod.TOPICS:
            with self._lock:
                offset = self._offsets[topic]
            try:
                msgs, new_offset = self.bus.fetch(topic, offset)
            except Exception:
                continue
            with self._lock:
                self._offsets[topic] = new_offset
            for msg in msgs:
                if msg.get("origin") == self.replica_id:
                    continue
                FLEET_BUS_MESSAGES.inc(topic=topic, direction="received")
                self._apply(topic, msg)
                applied += 1
        return applied

    def _apply(self, topic: str, msg: dict) -> None:
        origin = msg.get("origin", "?")
        if topic == "quarantine":
            path = msg.get("path")
            if path:
                reason = msg.get("reason", "")
                self._quarantine.trip(
                    path,
                    reason=f"fleet:{origin}:{reason}" if reason else f"fleet:{origin}",
                    ttl_s=msg.get("ttl_s"),
                    source="remote",
                )
        elif topic == "audit":
            self.remote_audits.append(dict(msg))
        elif topic == "session":
            sid, fpr, doc = msg.get("sid"), msg.get("fpr"), msg.get("doc")
            if sid and fpr and isinstance(doc, dict):
                self._archive_put(sid, fpr, doc)
        elif topic == "compile":
            kernel = msg.get("kernel")
            if kernel:
                self.warm_kernels.add(kernel)
                FLEET_WARM_ANNOUNCED.inc(kernel=kernel)
        elif topic == "telemetry":
            from karpenter_tpu.obs.slo import SLO

            self.remote_rounds.append(dict(msg))
            # peers' rounds burn the same fleet-wide SLO budget ours do
            SLO.observe_record(msg)

    def _archive_put(self, sid: str, fpr: str, doc: dict) -> None:
        with self._lock:
            if sid not in self._archive:
                self._archive_order.append(sid)
            self._archive[sid] = (fpr, doc)
            while len(self._archive_order) > _MAX_ARCHIVE:
                old = self._archive_order.popleft()
                self._archive.pop(old, None)

    def capsule_for(self, sid: str, fpr: str) -> Optional[dict]:
        """The freshest capsule matching this exact fingerprint, after a
        pump (the peer may have announced it this very round)."""
        self.pump()
        with self._lock:
            got = self._archive.get(sid)
        if got is None or got[0] != fpr:
            return None
        return got[1]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        from karpenter_tpu.guard import audit as guard_audit
        from karpenter_tpu.obs import observatory

        self._quarantine.remove_listener(self._on_trip)
        guard_audit.remove_audit_listener(self._on_audit)
        observatory.remove_compile_listener(self._on_compile)
