"""Tiny 5-field cron matcher for disruption-budget windows.

The reference uses robfig/cron for Budget.Schedule (nodepool.go:119-158); we
implement the standard minute/hour/dom/month/dow subset (*, lists, ranges,
steps) which covers the documented budget examples.
"""

from __future__ import annotations

import time


def _parse_field(spec: str, lo_v: int, hi_v: int) -> set[int]:
    out: set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", ""):
            start, end = lo_v, hi_v
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = int(a), int(b)
        else:
            start = end = int(part)
        out.update(range(start, end + 1, step))
    return out


_ALIASES = {
    "@hourly": "0 * * * *",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@weekly": "0 0 * * 0",
    "@monthly": "0 0 1 * *",
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
}


def matches(schedule: str, t: float) -> bool:
    """True if UTC time t falls on a cron firing minute."""
    schedule = _ALIASES.get(schedule.strip(), schedule)
    fields = schedule.split()
    if len(fields) != 5:
        raise ValueError(f"invalid cron schedule {schedule!r}")
    minute, hour, dom, month, dow = fields
    tm = time.gmtime(t)
    if tm.tm_min not in _parse_field(minute, 0, 59):
        return False
    if tm.tm_hour not in _parse_field(hour, 0, 23):
        return False
    if tm.tm_mon not in _parse_field(month, 1, 12):
        return False
    # standard cron: dom OR dow when both restricted, AND when one is *
    # dow parses 0-7 with both 0 and 7 meaning Sunday
    dow_set = {d % 7 for d in _parse_field(dow, 0, 7)}
    dom_set = _parse_field(dom, 1, 31)
    cron_dow = (tm.tm_wday + 1) % 7  # python Mon=0 -> cron Sun=0
    dom_star, dow_star = dom.strip() == "*", dow.strip() == "*"
    if dom_star and dow_star:
        return True
    if dom_star:
        return cron_dow in dow_set
    if dow_star:
        return tm.tm_mday in dom_set
    return tm.tm_mday in dom_set or cron_dow in dow_set


def in_window(schedule: str, duration_seconds: float, now: float) -> bool:
    """True if `now` is within [firing, firing+duration] for some firing.

    Scans back minute-by-minute over the duration (bounded; budget windows
    are hours-scale in practice).
    """
    start_minute = int(now // 60) * 60
    steps = int(duration_seconds // 60) + 1
    for i in range(min(steps, 60 * 24 * 32)):
        t = start_minute - i * 60
        if matches(schedule, t):
            return now - t <= duration_seconds
    return False
