"""Accelerator availability probing shared by entry points."""

from __future__ import annotations

import os
import subprocess
import sys

# Cold TPU tunnels (the axon plugin) can take minutes to come up; the
# round-1 bench fell back to CPU because the 90s probe was too short.
DEFAULT_PROBE_TIMEOUT = float(os.environ.get("KTPU_ACCEL_PROBE_TIMEOUT", "300"))


def probe_accelerator(timeout: float = DEFAULT_PROBE_TIMEOUT) -> str:
    """Probe device init in a subprocess — a hung TPU tunnel must not
    stall the caller (jax backend init is uninterruptible in-process).

    Returns "ok" (a non-CPU device is usable), "absent" (jax came up
    CPU-only), "timeout" (device init hung — e.g. a dead TPU tunnel), or
    "error" (the probe crashed — broken jax install / plugin fault).
    """
    try:
        out = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax, sys; d = jax.devices(); "
                "sys.exit(0 if d and d[0].platform != 'cpu' else 3)",
            ],
            timeout=timeout,
            capture_output=True,
        )
        if out.returncode == 0:
            return "ok"
        return "absent" if out.returncode == 3 else "error"
    except subprocess.TimeoutExpired:
        return "timeout"


def accelerator_usable(timeout: float = DEFAULT_PROBE_TIMEOUT) -> bool:
    return probe_accelerator(timeout) == "ok"


def force_cpu() -> None:
    """Force the CPU platform. Must run before the first jax backend use.

    The axon TPU plugin overrides the JAX_PLATFORMS env var (the effective
    platform list comes up as "axon,cpu" regardless), so env-only forcing
    silently initializes the TPU tunnel anyway; the config update is the
    only reliable mechanism in this image.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")


_cache_enabled = False


def enable_persistent_compile_cache(path: "str | None" = None) -> str:
    """Point XLA's persistent compilation cache at a durable directory so a
    process restart (or any shape-class revisit across processes) skips the
    20-70s cold compile — without this, the first batch after a restart
    would blow most of the reference's 1m Solve window
    (provisioner.go:415). Idempotent; returns the cache dir.

    Shape discipline upstream keeps this cache small: every solve pads pods
    and claim slots to power-of-two buckets and the label vocab to
    power-of-two K/V pads (scheduler.py), so the distinct shape classes —
    and therefore cache entries — grow logarithmically with problem size.
    """
    global _cache_enabled
    import jax

    path = path or os.environ.get(
        "KTPU_COMPILE_CACHE",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "karpenter_tpu",
            "xla_cache",
        ),
    )
    if not _cache_enabled:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache every kernel, not just the slow ones — the solve path is a
        # handful of executables and the reads are cheap
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        # The cache module LATCHES its enabled/initialized decision on the
        # first compile. Any jit dispatch before this point (jnp.asarray in
        # an encode helper is enough) initializes it with NO cache dir, and
        # every later config update is silently ignored — the historical
        # "zero entries persisted on CPU" tier-1 skip was exactly this
        # ordering hazard, not a platform limitation. Resetting after the
        # config updates re-initializes against the configured dir.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # pragma: no cover — private-API drift
            pass
        _cache_enabled = True
    return path


def force_cpu_if_unavailable(timeout: float = DEFAULT_PROBE_TIMEOUT) -> str | None:
    """CPU-fallback stanza: probes for an accelerator and forces the CPU
    platform when none is usable. Returns the probe failure mode
    ("absent" or "timeout") when the fallback was applied, None otherwise.
    """
    status = probe_accelerator(timeout)
    if status == "ok":
        return None
    force_cpu()
    return status
