"""Accelerator availability probing shared by entry points."""

from __future__ import annotations

import subprocess
import sys


def accelerator_usable(timeout: float = 90.0) -> bool:
    """Probe device init in a subprocess — a hung TPU tunnel must not
    stall the caller (jax backend init is uninterruptible in-process)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
        )
        return out.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def force_cpu_if_unavailable(timeout: float = 90.0) -> bool:
    """CPU-fallback stanza: returns True when the fallback was applied."""
    if accelerator_usable(timeout):
        return False
    import jax

    jax.config.update("jax_platforms", "cpu")
    return True
