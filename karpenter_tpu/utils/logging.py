"""Structured JSON logging + log-noise governor.

Counterparts of reference pkg/operator/logging (zap JSON logger with
level control and a NopLogger for simulations) and
pkg/utils/pretty.ChangeMonitor (suppress repeat log lines until the
payload changes or a TTL lapses).
"""

from __future__ import annotations

import json
import sys
import time
from typing import Optional

_LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}


class Logger:
    """Minimal zap-style JSON line logger. `with_values` returns a child
    carrying bound key/values; `nop()` silences simulation code paths
    (disruption/helpers.go:114 NopLogger)."""

    def __init__(self, level: str = "info", stream=None, _bound: Optional[dict] = None, _nop: bool = False):
        self.level = _LEVELS.get(level, 20)
        self.stream = stream if stream is not None else sys.stderr
        self._bound = dict(_bound or {})
        self._nop = _nop

    @staticmethod
    def nop() -> "Logger":
        return Logger(_nop=True)

    def with_values(self, **kv) -> "Logger":
        child = Logger(stream=self.stream, _nop=self._nop)
        child.level = self.level
        child._bound = {**self._bound, **kv}
        return child

    def _emit(self, level: str, msg: str, kv: dict) -> None:
        if self._nop or _LEVELS[level] < self.level:
            return
        record = {
            "level": level,
            "time": time.time(),
            "message": msg,
            **self._bound,
            **kv,
        }
        self.stream.write(json.dumps(record, default=str) + "\n")

    def debug(self, msg: str, **kv) -> None:
        self._emit("debug", msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._emit("info", msg, kv)

    def warn(self, msg: str, **kv) -> None:
        self._emit("warn", msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._emit("error", msg, kv)


class ChangeMonitor:
    """Log-dedup governor (pretty.ChangeMonitor): has_changed(key, value)
    is True only when the value differs from the last sighting or the
    entry aged past the TTL — callers skip logging otherwise."""

    def __init__(self, ttl_seconds: float = 24 * 3600.0, clock=None):
        self.ttl = ttl_seconds
        self._clock = clock
        self._seen: dict[str, tuple[str, float]] = {}

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else time.time()

    def has_changed(self, key: str, value) -> bool:
        rendered = json.dumps(value, sort_keys=True, default=str)
        now = self._now()
        prev = self._seen.get(key)
        if prev is not None and prev[0] == rendered and now - prev[1] < self.ttl:
            return False
        self._seen[key] = (rendered, now)
        return True


# process-wide default logger; operators may swap it (operator/logging)
DEFAULT = Logger(level="warn")


def get_logger() -> Logger:
    return DEFAULT


def set_level(level: str) -> None:
    DEFAULT.level = _LEVELS.get(level, 20)
