"""Operator runtime surfaces: leader election, health probes, profiling.

Counterpart of reference pkg/operator/operator.go:126-243:
- lease-based leader election with release-on-cancel (operator.go:171-181)
- health/readyz endpoints gated on state convergence (operator.go:225-243)
- profiling handlers behind --enable-profiling (operator.go:205-219) — the
  Python analog of net/http/pprof: live thread dumps and on-demand
  cProfile windows (plus the JAX profiler for device traces, utils/
  profiling hooks).

Everything runs against the injected clock so fake-clock tests can expire
leases deterministically.
"""

from __future__ import annotations

import io
import json
import threading
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from karpenter_tpu.models.objects import ObjectMeta
from karpenter_tpu.utils.clock import Clock

LEASES = "leases"  # coordination.k8s.io/v1 Lease analog

# client-go leaderelection defaults the reference inherits
LEASE_DURATION_SECONDS = 15.0
RENEW_DEADLINE_SECONDS = 10.0
RETRY_PERIOD_SECONDS = 2.0


@dataclass
class Lease:
    metadata: ObjectMeta
    holder: str = ""
    renew_time: float = 0.0
    lease_duration_seconds: float = LEASE_DURATION_SECONDS


class LeaderElector:
    """Lease-based single-active-replica election (operator.go:171-181).

    Not scale-out: the solver is stateless behind the leader (SURVEY §2.9),
    so HA is one active control plane + warm standbys racing for the lease.
    """

    def __init__(
        self,
        store,
        identity: str,
        clock: Optional[Clock] = None,
        lease_name: str = "karpenter-leader-election",
        lease_duration: float = LEASE_DURATION_SECONDS,
    ):
        self.store = store
        self.identity = identity
        self.clock = clock or store.clock
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        self._leading = False

    @property
    def is_leader(self) -> bool:
        return self._leading

    def try_acquire_or_renew(self) -> bool:
        """One election round; call every RETRY_PERIOD_SECONDS. Returns
        leadership after the round."""
        now = self.clock.now()
        lease = self.store.get(LEASES, self.lease_name)
        if lease is None:
            self.store.create(
                LEASES,
                Lease(
                    metadata=ObjectMeta(name=self.lease_name),
                    holder=self.identity,
                    renew_time=now,
                    lease_duration_seconds=self.lease_duration,
                ),
            )
            self._leading = True
            return True
        if lease.holder == self.identity:
            lease.renew_time = now
            self.store.update(LEASES, lease)
            self._leading = True
            return True
        if not lease.holder or now - lease.renew_time > lease.lease_duration_seconds:
            # released (empty holder) or expired: take over
            lease.holder = self.identity
            lease.renew_time = now
            self.store.update(LEASES, lease)
            self._leading = True
            return True
        self._leading = False
        return False

    def release(self) -> None:
        """Release-on-cancel (operator.go:176): a clean shutdown hands the
        lease over immediately instead of stalling failover a full TTL."""
        lease = self.store.get(LEASES, self.lease_name)
        if lease is not None and lease.holder == self.identity:
            lease.holder = ""
            lease.renew_time = 0.0
            self.store.update(LEASES, lease)
        self._leading = False


@dataclass
class HealthConfig:
    ready_checks: dict[str, Callable[[], bool]] = field(default_factory=dict)
    enable_profiling: bool = False  # operator.go:205 --enable-profiling


class _Handler(BaseHTTPRequestHandler):
    config: HealthConfig  # injected by serve_health

    def log_message(self, *args):  # quiet
        pass

    def _send(self, code: int, body: str, ctype: str = "text/plain") -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            self._send(200, "ok")
        elif path == "/readyz":
            # readiness = every registered check green (cache sync + CRD
            # presence in the reference, operator.go:225-243)
            failed = {
                name: False for name, fn in self.config.ready_checks.items() if not fn()
            }
            if failed:
                self._send(503, json.dumps({"failed": sorted(failed)}))
            else:
                self._send(200, "ok")
        elif path == "/metrics":
            from karpenter_tpu.utils.metrics import REGISTRY

            self._send(200, REGISTRY.expose(), ctype="text/plain; version=0.0.4")
        elif path == "/debug/pprof/threads":
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            import traceback

            out = io.StringIO()
            for tid, frame in sys_current_frames().items():
                out.write(f"--- thread {tid} ---\n")
                traceback.print_stack(frame, file=out)
            self._send(200, out.getvalue())
        elif path == "/debug/traces":
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            # the ring of recently completed decision-provenance traces
            # (tracing/tracer.py): nested spans for the provisioning and
            # disruption pipelines, plus attached SchedulingDecision
            # records — the span analog of the pprof handlers below
            from karpenter_tpu.tracing.tracer import TRACER

            self._send(
                200,
                json.dumps({"enabled": TRACER.enabled, "traces": TRACER.traces()}),
                ctype="application/json",
            )
        elif path == "/debug/envelope":
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            # live host-resource series: the running envelope sampler's
            # snapshot when one is active (bench / scenario runs), else a
            # one-shot RSS/CPU reading — the in-process analog of scraping
            # the controller pod's cgroup stats (thresholds.go:28-43)
            from karpenter_tpu.envelope.sampler import (
                global_sampler,
                read_cpu_seconds,
                read_rss_bytes,
            )

            sampler = global_sampler()
            if sampler is not None:
                body = sampler.snapshot()
            else:
                body = {
                    "rss_mb": round(read_rss_bytes() / 2**20, 1),
                    "cpu_s": round(read_cpu_seconds(), 3),
                    "stages": {},
                    "series": [],
                }
            self._send(200, json.dumps(body), ctype="application/json")
        elif path == "/debug/rounds":
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            # the round ledger's in-memory ring (obs/ledger.py) — one
            # compact record per solve round — plus the compile
            # observatory's per-kernel attribution
            from urllib.parse import parse_qs, urlparse

            from karpenter_tpu.obs import ledger as obs_ledger
            from karpenter_tpu.obs import observatory

            qs = parse_qs(urlparse(self.path).query)
            n = None
            if qs.get("n"):
                try:
                    n = max(int(qs["n"][0]), 1)
                except ValueError:
                    pass
            self._send(
                200,
                json.dumps(
                    {
                        "rounds": obs_ledger.LEDGER.records(n),
                        "observatory": observatory.snapshot(),
                    }
                ),
                ctype="application/json",
            )
        elif path == "/debug/fleet":
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            # the fleet observatory (obs/fleetobs.py): the cross-replica
            # timeline rollup — per-replica round counts, stitched trace
            # count, duplicate-round check, SLO burn rates
            from karpenter_tpu.obs import fleetobs

            self._send(
                200, json.dumps(fleetobs.debug_fleet()), ctype="application/json"
            )
        elif path.startswith("/debug/trace/"):
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            # one fleet trace id's whole journey, stitched across every
            # replica the observatory can see; ?format=perfetto exports
            # the same rounds as a Chrome-trace document
            from urllib.parse import parse_qs, urlparse

            from karpenter_tpu.obs import fleetobs, traceexport

            trace_id = path[len("/debug/trace/"):]
            stitched = fleetobs.debug_trace(trace_id)
            if stitched is None:
                self._send(404, f"unknown trace id {trace_id!r}")
                return
            qs = parse_qs(urlparse(self.path).query)
            if qs.get("format", [""])[0] == "perfetto":
                body = traceexport.chrome_trace(stitched["rounds"])
            else:
                body = stitched
            self._send(200, json.dumps(body), ctype="application/json")
        elif path == "/debug/quarantine":
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            # per-path circuit-breaker state (guard/quarantine.py): TTL
            # remaining, tripping reason, all-time trip count — the
            # inspectable form of the per-process breaker
            from karpenter_tpu.guard import QUARANTINE

            self._send(
                200, json.dumps(QUARANTINE.state()), ctype="application/json"
            )
        elif path == "/debug/profile":
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            # on-demand device profiling: a jax.profiler trace capture of
            # ?seconds= (clamped to 30s, one capture at a time) written
            # to disk; the response reports where the trace landed
            from urllib.parse import parse_qs, urlparse

            from karpenter_tpu.obs import observatory

            try:
                seconds = float(
                    parse_qs(urlparse(self.path).query).get("seconds", ["1"])[0]
                )
            except ValueError:
                seconds = 1.0
            try:
                body = observatory.capture_device_profile(seconds)
            except RuntimeError as err:
                self._send(409, str(err))
                return
            except Exception as err:  # noqa: BLE001 — capture is best-effort
                self._send(500, f"profile capture failed: {err}")
                return
            self._send(200, json.dumps(body), ctype="application/json")
        elif path == "/debug/pprof/profile":
            if not self.config.enable_profiling:
                self._send(404, "profiling disabled")
                return
            import time as _t
            from urllib.parse import parse_qs, urlparse

            seconds = float(
                parse_qs(urlparse(self.path).query).get("seconds", ["1"])[0]
            )
            # sampling profiler over ALL threads (a cProfile here would
            # only see this handler thread sleeping): collapse each
            # thread's stack to a ;-joined frame path every 10ms, report
            # sample counts — the wall-clock analog of pprof's CPU profile
            deadline = _t.monotonic() + min(seconds, 30.0)
            own = threading.get_ident()
            samples: dict[str, int] = {}
            n = 0
            while _t.monotonic() < deadline:
                for tid, frame in sys_current_frames().items():
                    if tid == own:
                        continue
                    parts = []
                    f = frame
                    while f is not None:
                        code = f.f_code
                        parts.append(
                            f"{code.co_filename.rsplit('/', 1)[-1]}:"
                            f"{f.f_lineno}:{code.co_name}"
                        )
                        f = f.f_back
                    key = ";".join(reversed(parts))
                    samples[key] = samples.get(key, 0) + 1
                n += 1
                _t.sleep(0.01)
            out = io.StringIO()
            out.write(f"# {n} sampling rounds, 10ms interval\n")
            for key, count in sorted(samples.items(), key=lambda kv: -kv[1])[:100]:
                out.write(f"{count} {key}\n")
            self._send(200, out.getvalue())
        else:
            self._send(404, "not found")


def sys_current_frames():
    import sys

    return sys._current_frames()


def serve_health(
    config: HealthConfig, host: str = "127.0.0.1", port: int = 0
) -> tuple[ThreadingHTTPServer, int]:
    """Start the health/metrics/profiling server on a daemon thread;
    returns (server, bound port)."""
    handler = type("BoundHandler", (_Handler,), {"config": config})
    server = ThreadingHTTPServer((host, port), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]
