"""Resource-list arithmetic.

Counterpart of the reference's resource helpers (reference:
pkg/utils/resources/resources.go — Merge/Subtract/Fits/Cmp over
corev1.ResourceList). We represent a resource list as a plain
``dict[str, float]`` with canonical units:

  cpu               cores (fractional)
  memory            bytes
  pods              count
  ephemeral-storage bytes
  <extended>        count (e.g. "nvidia.com/gpu", "hugepages-2Mi" in bytes)

Quantities may be given as Kubernetes quantity strings ("100m", "1Gi",
"2.5", "1e3") and are parsed to floats with `parse_quantity`.
"""

from __future__ import annotations

import math
import re

import numpy as np

# Canonical resource names (mirror corev1 resource names).
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
HUGEPAGES_PREFIX = "hugepages-"

_BIN_SUFFIX = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DEC_SUFFIX = {"n": 1e-9, "u": 1e-6, "m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18}

_QTY_RE = re.compile(r"^([+-]?[0-9.eE+-]+?)(Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE])?$")


def parse_quantity(q: "str | int | float") -> float:
    """Parse a Kubernetes quantity ('100m', '1Gi', 3, '2e3') into a float.

    Values are quantized to float32 so host-side resource arithmetic is
    bit-identical to the device solver's f32 tensors (same inputs, same
    accumulation order -> same sums, making exact <= comparisons safe on
    both sides).
    """
    if isinstance(q, (int, float)):
        return float(np.float32(q))
    s = q.strip()
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {q!r}")
    num, suffix = m.groups()
    value = float(num)
    if suffix:
        value *= _BIN_SUFFIX.get(suffix) or _DEC_SUFFIX[suffix]
    return float(np.float32(value))


def parse_resource_list(rl: "dict[str, str | int | float] | None") -> dict[str, float]:
    return {k: parse_quantity(v) for k, v in (rl or {}).items()}


def quantize(rl: "dict[str, float] | None") -> dict[str, float]:
    """Round every value to float32 (the framework-wide resource dtype)."""
    return {k: float(np.float32(v)) for k, v in (rl or {}).items()}


def merge(*lists: "dict[str, float] | None") -> dict[str, float]:
    """Sum resource lists key-wise (reference Merge semantics).

    Accumulates in float32 to stay bit-identical with the device solver.
    """
    out: dict[str, float] = {}
    for rl in lists:
        for k, v in (rl or {}).items():
            out[k] = float(np.float32(np.float32(out.get(k, 0.0)) + np.float32(v)))
    return out


def subtract(a: dict[str, float], b: dict[str, float]) -> dict[str, float]:
    """a - b key-wise; keys only in b appear negated (reference Subtract)."""
    out = dict(a)
    for k, v in b.items():
        out[k] = float(np.float32(np.float32(out.get(k, 0.0)) - np.float32(v)))
    return out


def fits(candidate: dict[str, float], total: dict[str, float]) -> bool:
    """True iff every requested resource in candidate is <= total[k].

    Exact comparison: both sides of the framework quantize to float32 and
    accumulate in the same order, so no epsilon is needed (and using one
    would diverge from the device solver's exact f32 compare).

    A resource requested but absent from total is treated as 0 available
    (so any positive request fails), matching the reference's Fits.
    """
    return all(v <= total.get(k, 0.0) for k, v in candidate.items())


def cmp(a: float, b: float, rel_tol: float = 1e-9) -> int:
    if math.isclose(a, b, rel_tol=rel_tol, abs_tol=1e-12):
        return 0
    return -1 if a < b else 1


def max_resources(*lists: dict[str, float]) -> dict[str, float]:
    out: dict[str, float] = {}
    for rl in lists:
        for k, v in rl.items():
            out[k] = max(out.get(k, 0.0), v)
    return out


def is_zero(rl: dict[str, float]) -> bool:
    return all(v <= 0 for v in rl.values())


def format_cpu(cores: float) -> str:
    if cores == int(cores):
        return str(int(cores))
    return f"{int(round(cores * 1000))}m"
