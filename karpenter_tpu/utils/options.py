"""Operator options and feature gates.

Counterpart of reference pkg/operator/options/options.go:68-216: flag+env
configuration with feature-gate CSV parsing. Values mirror the reference
defaults (options.go:112-140).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class FeatureGates:
    # defaults per options.go:134
    node_repair: bool = False
    reserved_capacity: bool = True
    spot_to_spot_consolidation: bool = False
    node_overlay: bool = False
    static_capacity: bool = True
    capacity_buffer: bool = False
    dynamic_resources: bool = False

    @staticmethod
    def parse(csv: str) -> "FeatureGates":
        """'NodeRepair=true,SpotToSpotConsolidation=false' -> gates."""
        gates = FeatureGates()
        mapping = {
            "NodeRepair": "node_repair",
            "ReservedCapacity": "reserved_capacity",
            "SpotToSpotConsolidation": "spot_to_spot_consolidation",
            "NodeOverlay": "node_overlay",
            "StaticCapacity": "static_capacity",
            "CapacityBuffer": "capacity_buffer",
            "DynamicResources": "dynamic_resources",
        }
        for part in csv.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, value = part.partition("=")
            attr = mapping.get(key.strip())
            if attr is not None:
                setattr(gates, attr, value.strip().lower() in ("true", "1", "yes"))
        return gates


@dataclass
class Options:
    batch_idle_seconds: float = 1.0  # options.go:129
    batch_max_seconds: float = 10.0  # options.go:130
    solve_timeout_seconds: float = 60.0  # provisioner.go:415
    disruption_poll_seconds: float = 10.0  # disruption/controller.go:71
    preference_policy: str = "Respect"  # Respect | Ignore (options.go:33-45)
    min_values_policy: str = "Strict"  # Strict | BestEffort
    # host:port of a remote solver service (rpc/service.py); empty = solve
    # in-process. The control/solver split of SURVEY.md §2.9.
    solver_endpoint: str = ""
    # devices for the solver's (dp x it) mesh; 0 = single device. The
    # catalog shards over "it" and GSPMD rides ICI (SURVEY §2.9).
    mesh_devices: int = 0
    # operator runtime (operator.go:126-243): 0 disables the probe server;
    # -1 binds an ephemeral port (tests read Operator.health_port back)
    health_probe_port: int = 0
    enable_profiling: bool = False  # operator.go:205
    leader_elect: bool = False  # single-process harness default; HA sets it
    feature_gates: FeatureGates = field(default_factory=FeatureGates)

    @staticmethod
    def from_env(prefix: str = "KARPENTER_") -> "Options":
        opts = Options()
        env = os.environ
        if prefix + "BATCH_IDLE_DURATION" in env:
            opts.batch_idle_seconds = float(env[prefix + "BATCH_IDLE_DURATION"])
        if prefix + "BATCH_MAX_DURATION" in env:
            opts.batch_max_seconds = float(env[prefix + "BATCH_MAX_DURATION"])
        if prefix + "PREFERENCE_POLICY" in env:
            opts.preference_policy = env[prefix + "PREFERENCE_POLICY"]
        if prefix + "MIN_VALUES_POLICY" in env:
            opts.min_values_policy = env[prefix + "MIN_VALUES_POLICY"]
        if prefix + "SOLVER_ENDPOINT" in env:
            opts.solver_endpoint = env[prefix + "SOLVER_ENDPOINT"]
        if prefix + "MESH_DEVICES" in env:
            opts.mesh_devices = int(env[prefix + "MESH_DEVICES"])
        if prefix + "HEALTH_PROBE_PORT" in env:
            opts.health_probe_port = int(env[prefix + "HEALTH_PROBE_PORT"])
        if prefix + "ENABLE_PROFILING" in env:
            opts.enable_profiling = env[prefix + "ENABLE_PROFILING"].lower() in (
                "true", "1", "yes",
            )
        if prefix + "LEADER_ELECT" in env:
            opts.leader_elect = env[prefix + "LEADER_ELECT"].lower() in (
                "true", "1", "yes",
            )
        if prefix + "FEATURE_GATES" in env:
            opts.feature_gates = FeatureGates.parse(env[prefix + "FEATURE_GATES"])
        return opts
