"""Deduplicated event recorder.

Counterpart of reference pkg/events/recorder.go:47-110: domain events are
deduplicated within a TTL window and rate-limited per dedupe key so event
storms (e.g. a pod failing to schedule every batch) don't flood the API.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.utils.clock import Clock

DEDUPE_TTL_SECONDS = 120.0  # recorder.go:56
MAX_EVENTS = 10_000


@dataclass
class Event:
    kind: str  # involved object kind
    name: str  # involved object name
    type: str  # Normal | Warning
    reason: str
    message: str
    timestamp: float = 0.0
    count: int = 1

    @property
    def dedupe_key(self) -> str:
        return f"{self.kind}/{self.name}/{self.reason}/{self.message}"


class Recorder:
    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or Clock()
        self.events: deque[Event] = deque(maxlen=MAX_EVENTS)
        self._last_seen: dict[str, tuple[float, Event]] = {}

    def publish(self, event: Event) -> bool:
        """Record unless an identical event fired within the TTL; returns
        whether the event was actually recorded (vs deduped)."""
        now = self.clock.now()
        event.timestamp = now
        seen = self._last_seen.get(event.dedupe_key)
        if seen is not None and now - seen[0] < DEDUPE_TTL_SECONDS:
            seen[1].count += 1
            self._last_seen[event.dedupe_key] = (seen[0], seen[1])
            return False
        # prune expired dedupe entries so memory stays bounded by the TTL
        # window, not by the lifetime count of distinct events
        if len(self._last_seen) > 4096:
            self._last_seen = {
                k: v for k, v in self._last_seen.items() if now - v[0] < DEDUPE_TTL_SECONDS
            }
        self._last_seen[event.dedupe_key] = (now, event)
        self.events.append(event)
        return True

    def for_object(self, kind: str, name: str) -> list[Event]:
        return [e for e in self.events if e.kind == kind and e.name == name]


# domain event constructors (disruption/events, scheduling/events.go analogs)
def nominate(pod_name: str, target: str) -> Event:
    return Event("Pod", pod_name, "Normal", "Nominated", f"Pod should schedule on {target}")


def failed_scheduling(pod_name: str, reason: str) -> Event:
    return Event("Pod", pod_name, "Warning", "FailedScheduling", reason)


def disrupting_node(node_name: str, reason: str) -> Event:
    return Event("Node", node_name, "Normal", "Disrupting", reason)


def unconsolidatable(node_name: str, reason: str) -> Event:
    return Event("Node", node_name, "Normal", "Unconsolidatable", reason)
