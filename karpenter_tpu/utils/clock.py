"""Clock abstraction: real time in production, fake time in tests.

Counterpart of the reference's clocktesting.FakeClock usage
(pkg/test/environment.go:48,195) — deterministic time travel for
consolidateAfter, budgets, TTLs and liveness timeouts.
"""

from __future__ import annotations

import time as _time


class Clock:
    def now(self) -> float:
        return _time.time()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)


class FakeClock(Clock):
    def __init__(self, start: float = 1_700_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self._now += seconds

    def step(self, seconds: float) -> None:
        self._now += seconds

    def set(self, t: float) -> None:
        self._now = t
