"""Prometheus-style metrics registry.

Counterpart of reference pkg/metrics/metrics.go:32-115 and the scheduler /
disruption metric families. In-process counters/gauges/histograms with
label sets and a text exposition dump; the solver additionally reports
device-side timings captured host-side (the Measure defer-observer
pattern, pkg/metrics/constants.go:65).
"""

from __future__ import annotations

import math
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Iterable, Optional


class _Family:
    def __init__(self, name: str, help_text: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.label_names = label_names

    def _key(self, labels: dict[str, str]) -> tuple:
        return tuple(labels.get(n, "") for n in self.label_names)


class Counter(_Family):
    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, tuple(label_names))
        self.values: dict[tuple, float] = defaultdict(float)

    def inc(self, amount: float = 1.0, **labels) -> None:
        self.values[self._key(labels)] += amount

    def get(self, **labels) -> float:
        return self.values.get(self._key(labels), 0.0)

    def sum(self, **labels) -> float:
        """Total over every series matching the given label subset (get()
        is exact-key: an omitted label means \"\", not a wildcard)."""
        idx = [(self.label_names.index(n), v) for n, v in labels.items()]
        return sum(
            v for k, v in self.values.items()
            if all(k[i] == want for i, want in idx)
        )


class Gauge(_Family):
    def __init__(self, name, help_text, label_names=()):
        super().__init__(name, help_text, tuple(label_names))
        self.values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        self.values[self._key(labels)] = value

    def get(self, **labels) -> float:
        return self.values.get(self._key(labels), 0.0)

    def delete(self, **labels) -> None:
        self.values.pop(self._key(labels), None)


DEFAULT_BUCKETS = tuple(0.001 * (2.0**i) for i in range(20))  # 1ms .. ~524s


class Histogram(_Family):
    def __init__(self, name, help_text, label_names=(), buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text, tuple(label_names))
        self.buckets = tuple(sorted(buckets))
        self.counts: dict[tuple, list[int]] = {}
        self.sums: dict[tuple, float] = defaultdict(float)
        self.totals: dict[tuple, int] = defaultdict(int)

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        if key not in self.counts:
            self.counts[key] = [0] * (len(self.buckets) + 1)
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if value <= b:
                idx = i
                break
        self.counts[key][idx] += 1
        self.sums[key] += value
        self.totals[key] += 1

    @contextmanager
    def time(self, **labels):
        """The Measure defer-observer (constants.go:65)."""
        start = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - start, **labels)

    def percentile(self, q: float, **labels) -> float:
        key = self._key(labels)
        total = self.totals.get(key, 0)
        if not total:
            return math.nan
        target = q * total
        seen = 0
        for i, count in enumerate(self.counts[key]):
            seen += count
            if seen >= target:
                return self.buckets[i] if i < len(self.buckets) else math.inf
        return math.inf


def _escape_label(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the exposition is unparsable."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


class Registry:
    def __init__(self) -> None:
        self._families: dict[str, _Family] = {}

    def counter(self, name, help_text="", label_names=()) -> Counter:
        return self.get_or_register(Counter, name, help_text, label_names)

    def gauge(self, name, help_text="", label_names=()) -> Gauge:
        return self.get_or_register(Gauge, name, help_text, label_names)

    def histogram(self, name, help_text="", label_names=(), buckets=DEFAULT_BUCKETS) -> Histogram:
        return self.get_or_register(Histogram, name, help_text, label_names, buckets=buckets)

    def get_or_register(self, cls, name, help_text="", label_names=(), **kwargs):
        """Idempotent family registration: a re-register with the same
        shape returns the EXISTING family (so a second Manager
        construction in one process shares series instead of silently
        shadowing or double-counting), while a type or label-set mismatch
        fails loudly instead of corrupting the exposition."""
        fam = self._families.get(name)
        if fam is None:
            fam = cls(name, help_text, tuple(label_names), **kwargs)
            self._families[name] = fam
            return fam
        if not isinstance(fam, cls):
            raise TypeError(f"metric {name} already registered as {type(fam).__name__}")
        if tuple(label_names) != fam.label_names:
            raise ValueError(
                f"metric {name} re-registered with labels {tuple(label_names)} "
                f"!= existing {fam.label_names}"
            )
        return fam

    # pre-rename alias (call sites predating get_or_register)
    _get_or_create = get_or_register

    def families(self) -> list[_Family]:
        return list(self._families.values())

    def expose(self) -> str:
        """Prometheus text exposition (scrape endpoint analog): escaped
        label values/help, cumulative le-bucket lines for histograms."""
        lines = []
        for fam in self._families.values():
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            kind = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}[type(fam)]
            lines.append(f"# TYPE {fam.name} {kind}")
            if isinstance(fam, (Counter, Gauge)):
                for key, value in fam.values.items():
                    labels = ",".join(
                        f'{n}="{_escape_label(v)}"'
                        for n, v in zip(fam.label_names, key)
                        if v
                    )
                    suffix = f"{{{labels}}}" if labels else ""
                    lines.append(f"{fam.name}{suffix} {value}")
            else:
                for key, total in fam.totals.items():
                    pairs = [
                        f'{n}="{_escape_label(v)}"'
                        for n, v in zip(fam.label_names, key)
                        if v
                    ]
                    base = f"{{{','.join(pairs)}}}" if pairs else ""
                    # cumulative buckets (le is just another label pair)
                    cum = 0
                    for i, bound in enumerate(fam.buckets):
                        cum += fam.counts[key][i]
                        le = ",".join(pairs + [f'le="{format(bound, ".10g")}"'])
                        lines.append(f"{fam.name}_bucket{{{le}}} {cum}")
                    le = ",".join(pairs + ['le="+Inf"'])
                    lines.append(f"{fam.name}_bucket{{{le}}} {total}")
                    lines.append(f"{fam.name}_count{base} {total}")
                    lines.append(f"{fam.name}_sum{base} {fam.sums[key]}")
        return "\n".join(lines) + "\n"


# The global registry + core metric families (pkg/metrics/metrics.go:32-115)
REGISTRY = Registry()

NODECLAIMS_CREATED = REGISTRY.counter(
    "karpenter_nodeclaims_created_total",
    "NodeClaims created",
    ("reason", "nodepool", "min_values_relaxed"),
)
NODECLAIMS_TERMINATED = REGISTRY.counter(
    "karpenter_nodeclaims_terminated_total", "NodeClaims terminated", ("reason", "nodepool")
)
NODECLAIMS_DISRUPTED = REGISTRY.counter(
    "karpenter_nodeclaims_disrupted_total", "NodeClaims disrupted", ("reason", "nodepool")
)
NODES_CREATED = REGISTRY.counter("karpenter_nodes_created_total", "Nodes created", ("nodepool",))
NODES_TERMINATED = REGISTRY.counter(
    "karpenter_nodes_terminated_total", "Nodes terminated", ("nodepool",)
)
PODS_DISRUPTION_INITIATED = REGISTRY.counter(
    "karpenter_pods_disruption_initiated_total", "Pod evictions initiated", ("nodepool",)
)
SCHEDULING_DURATION = REGISTRY.histogram(
    "karpenter_scheduler_scheduling_duration_seconds", "Solve wall time"
)
SCHEDULING_UNSCHEDULABLE = REGISTRY.gauge(
    "karpenter_scheduler_unschedulable_pods_count", "Pods the last solve could not place"
)
SOLVER_HOST_FALLBACKS = REGISTRY.counter(
    "karpenter_solver_host_fallback_total",
    "Solves routed to the host oracle instead of the device kernel",
    ("reason",),
)
SOLVER_RPC_DURATION = REGISTRY.histogram(
    "karpenter_solver_rpc_duration_seconds",
    "Control-plane -> solver-service RPC wall time",
    ("method",),
)
CONSOLIDATION_TIMEOUTS = REGISTRY.counter(
    "karpenter_consolidation_timeouts_total",
    "Consolidation passes that hit their method deadline",
    ("method",),
)
DISRUPTION_EVAL_DURATION = REGISTRY.histogram(
    "karpenter_disruption_evaluation_duration_seconds", "Disruption pass wall time", ("method",)
)
DISRUPTION_ELIGIBLE_NODES = REGISTRY.gauge(
    "karpenter_disruption_eligible_nodes", "Disruptable candidates", ("method",)
)
NODEPOOL_USAGE = REGISTRY.gauge(
    "karpenter_nodepool_usage", "Per-pool resource usage", ("nodepool", "resource_type")
)
NODEPOOL_LIMIT = REGISTRY.gauge(
    "karpenter_nodepool_limit", "Per-pool resource limits", ("nodepool", "resource_type")
)
# scheduler queue families (provisioning/scheduling/metrics.go:39-100)
SCHEDULER_QUEUE_DEPTH = REGISTRY.gauge(
    "karpenter_scheduler_queue_depth", "Pods waiting in the scheduling queue"
)
SCHEDULER_UNFINISHED_WORK = REGISTRY.gauge(
    "karpenter_scheduler_unfinished_work_seconds",
    "Age of the oldest pod still waiting to be scheduled",
)
SCHEDULER_IGNORED_PODS = REGISTRY.gauge(
    "karpenter_scheduler_ignored_pods_count", "Pods excluded from scheduling"
)
PENDING_PODS_BY_ZONE = REGISTRY.gauge(
    "karpenter_scheduler_pending_pods_by_effective_zone_count",
    "Pending pods grouped by their effective zone restriction",
    ("zone",),
)
# pod state families (controllers/metrics/pod/controller.go:61-170)
POD_STATE = REGISTRY.gauge(
    "karpenter_pods_state",
    "Current pod state",
    ("name", "namespace", "node", "nodepool", "phase", "scheduled"),
)
POD_STARTUP_DURATION = REGISTRY.histogram(
    "karpenter_pods_startup_duration_seconds", "Pod creation until running"
)
POD_BOUND_DURATION = REGISTRY.histogram(
    "karpenter_pods_bound_duration_seconds", "Pod creation until bound to a node"
)
# node state families (controllers/metrics/node/controller.go:70-140)
NODE_ALLOCATABLE = REGISTRY.gauge(
    "karpenter_nodes_allocatable",
    "Node allocatable by resource",
    ("node_name", "nodepool", "resource_type"),
)
NODE_TOTAL_POD_REQUESTS = REGISTRY.gauge(
    "karpenter_nodes_total_pod_requests",
    "Summed pod requests per node",
    ("node_name", "nodepool", "resource_type"),
)
NODE_UTILIZATION = REGISTRY.gauge(
    "karpenter_nodes_utilization_percent",
    "Requested over allocatable per node",
    ("node_name", "nodepool", "resource_type"),
)
# status-condition auto-metrics (operatorpkg status controller analog,
# reference controllers.go:140-158)
STATUS_CONDITION_COUNT = REGISTRY.gauge(
    "operator_status_condition_count",
    "Objects per condition type/status",
    ("kind", "type", "status"),
)
STATUS_CONDITION_TRANSITIONS = REGISTRY.counter(
    "operator_status_condition_transitions_total",
    "Condition transitions",
    ("type", "status"),
)
# host resource envelope (envelope/sampler.py ticks these; the analog of
# the controller pod's container_memory_working_set_bytes /
# container_cpu_usage_seconds_total the reference e2e thresholds scrape,
# test/suites/performance/thresholds.go:28-43)
HOST_RSS_BYTES = REGISTRY.gauge(
    "ktpu_host_rss_bytes", "Live resident set size of the control-plane process"
)
HOST_CPU_SECONDS = REGISTRY.gauge(
    "ktpu_cpu_seconds_total",
    "Cumulative user+system CPU seconds of the control-plane process",
)
# cloudprovider SPI decorator families (cloudprovider/metrics/cloudprovider.go)
CLOUDPROVIDER_DURATION = REGISTRY.histogram(
    "karpenter_cloudprovider_duration_seconds",
    "SPI method wall time",
    ("controller", "method", "provider"),
)
CLOUDPROVIDER_ERRORS = REGISTRY.counter(
    "karpenter_cloudprovider_errors_total",
    "SPI method errors",
    ("controller", "method", "provider", "error"),
)
# ---- reference-parity gap closers (ktpu_ convention; each help text
# names its reference analog so dashboards can map families 1:1) --------
_COUNT_BUCKETS = tuple(float(2**i) for i in range(18))  # 1 .. 131072
BATCH_WINDOW_SECONDS = REGISTRY.histogram(
    "ktpu_scheduler_batch_window_seconds",
    "Batcher debounce wait before a provisioning solve"
    " (reference karpenter_provisioner_batch_time_seconds)",
)
QUEUE_DEPTH_PODS = REGISTRY.histogram(
    "ktpu_scheduler_queue_depth_pods",
    "Pods per provisioning solve batch"
    " (reference karpenter_provisioner_scheduling_queue_depth)",
    buckets=_COUNT_BUCKETS,
)
UNSCHEDULABLE_PODS = REGISTRY.gauge(
    "ktpu_unschedulable_pods",
    "Pods the last solve could not place, by canonical failure reason"
    " (reference karpenter_scheduler_unschedulable_pods_count + error events)",
    ("reason",),
)
VOLUNTARY_DISRUPTION_DECISIONS = REGISTRY.counter(
    "ktpu_voluntary_disruption_decisions_total",
    "Disruption command outcomes after validation/scoring"
    " (reference karpenter_voluntary_disruption_decisions_total)",
    ("decision", "reason"),
)
VOLUNTARY_DISRUPTION_ELIGIBLE = REGISTRY.gauge(
    "ktpu_voluntary_disruption_eligible_nodes",
    "Disruptable candidates per disruption reason"
    " (reference karpenter_voluntary_disruption_eligible_nodes)",
    ("reason",),
)
NODECLAIM_TRANSITION_DURATION = REGISTRY.histogram(
    "ktpu_nodeclaims_transition_duration_seconds",
    "NodeClaim creation to lifecycle condition flipping true"
    " (reference karpenter_nodeclaims_*_duration family)",
    ("condition_type",),
)
NODECLAIM_TERMINATION_DURATION = REGISTRY.histogram(
    "ktpu_nodeclaims_termination_duration_seconds",
    "NodeClaim deletion to finalizer removal"
    " (reference karpenter_nodeclaims_termination_duration_seconds)",
)
# ---- fault injection & hardened failure paths (faultinject/, PR 4) ----
FAULT_INJECTIONS = REGISTRY.counter(
    "ktpu_fault_injections_total",
    "Faults injected at guarded points by the active FaultPlan",
    ("point", "mode"),
)
SOLVER_FALLBACK = REGISTRY.counter(
    "ktpu_solver_fallback_total",
    "Solves that degraded down the ladder (device -> host oracle);"
    " ktpu twin of karpenter_solver_host_fallback_total with the"
    " degradation reasons (device_dispatch, divergence, dra, ...)",
    ("reason",),
)
OFFERING_BLACKOUT = REGISTRY.gauge(
    "ktpu_offering_blackout",
    "Live unavailable-offering blackout entries by capacity type"
    " (reference aws unavailableofferings ICE-cache size)",
    ("capacity_type",),
)
STREAM_RECOVERIES = REGISTRY.counter(
    "ktpu_stream_recoveries_total",
    "Mid-SolveStream failures and how the client recovered"
    " (resumed = stream retried clean; downgraded = unary fallback)",
    ("outcome",),
)
STREAM_STALE_FRAMES = REGISTRY.counter(
    "ktpu_stream_stale_frames_total",
    "Chunk frames discarded because their round predates the last reset",
)
TRANSIENT_RETRIES = REGISTRY.counter(
    "ktpu_transient_retries_total",
    "Bounded retries of transient cloud/API errors, by controller",
    ("controller",),
)
CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "ktpu_circuit_transitions_total",
    "Solver-endpoint circuit-breaker state transitions",
    ("target", "to"),
)
# ---- active-window device scan + incremental encode (PR 5) ----
SCAN_WINDOW_SPILLS = REGISTRY.counter(
    "ktpu_scan_window_spills_total",
    "Claim opens refused because the solver's active window was full"
    " (the host grows the window and re-solves)",
)
ENCODE_CACHE_HITS = REGISTRY.counter(
    "ktpu_encode_cache_hits_total",
    "Pod-kind encode rows served from the incremental encode cache"
    " instead of re-encoding (KTPU_ENCODE_CACHE)",
)
# ---- resident incremental solver (PR 7) ----
RESIDENT_ROUNDS = REGISTRY.counter(
    "ktpu_resident_rounds_total",
    "Resident-session solve rounds by outcome: delta (arrivals/retractions"
    " applied against the on-device resident SolverState), full (cold"
    " re-solve — no resident state, unsupported constraint family, or a"
    " delta the session cannot prove bit-identical), invalidated (the"
    " cluster-shape epoch changed: catalog/templates/pads/vocab/existing"
    " nodes)",
    ("mode",),
)
RESIDENT_DELTA_PODS = REGISTRY.histogram(
    "ktpu_resident_delta_pods",
    "Pods in each resident delta round (arrivals encoded plus departures"
    " retracted) — steady-state churn should keep this small relative to"
    " the resident set",
    buckets=_COUNT_BUCKETS,
)
KSCAN_GRID_UPDATES = REGISTRY.counter(
    "ktpu_kscan_grid_updates_total",
    "Kind-scan capacity-grid updates per segment boundary: incremental"
    " (previous segment's boundary-adjusted [W, T, GR] grid reused —"
    " same request vector) vs full (the full-width divide-and-verify"
    " recompute)",
    ("mode",),
)
# ---- gang-aware multi-host slice scheduling (gang/, PR 6) ----
GANG_PLACEMENTS = REGISTRY.counter(
    "ktpu_gang_placements_total",
    "Gang scheduling outcomes per solve: placed (every member bound to one"
    " slice-shaped claim group), spilled (all-or-nothing refusal — every"
    " member failed together), timeout (straggler wait expired), invalid"
    " (malformed gang annotations), partial (invariant violation tripwire;"
    " must stay zero)",
    ("outcome",),
)
GANG_SPILLS = REGISTRY.counter(
    "ktpu_gang_spills_total",
    "Gangs that failed placement atomically (no slice shape could hold"
    " every member); the gang stays pending and retries",
)
GANG_WAIT_DURATION = REGISTRY.histogram(
    "ktpu_gang_wait_duration_seconds",
    "How long a partial gang waited for stragglers before every member"
    " arrived (observed when the gang completes; KTPU_GANG_WAIT_SECONDS"
    " bounds the wait between timeout reports)",
)
# ---- dp-sharded mesh solve (PR 8) ----
SHARD_MERGE_ROUNDS = REGISTRY.counter(
    "ktpu_shard_merge_rounds_total",
    "dp-shard chunk-group merge outcomes by solver family (fill |"
    " existing | topo_fill | kscan | perpod): committed (the on-device"
    " verdict proved the speculative per-shard solve independent of the"
    " committed claims — deadness held, zero leftovers/spills, no window"
    " or claim-axis overflow, no topology record/apply overlap, and"
    " disjoint existing-node debit touch sets — and it grafted exactly)"
    " vs replayed (a verdict bit was unset and the group re-dispatched"
    " sequentially; bit-parity holds either way)",
    ("outcome", "family"),
)
SHARD_FAMILY_ELIGIBLE = REGISTRY.counter(
    "ktpu_shard_family_eligible_total",
    "Chunk groups routed per solver family (fill | existing | topo_fill |"
    " kscan | perpod | gang): path=dp when the group entered a speculative"
    " merge round (committed or replayed — either way it rode the fan-out),"
    " path=sequential when eligibility gating kept it on the ordered scan;"
    " reason names the first failed conjunct on sequential increments"
    " (no_pipeline | no_dp_mesh | shard_dp_off | kscan_optout |"
    " perpod_optout | quarantined | existing_optout | single_group |"
    " single_chunk | gang_atomic; \"\" on dp) so the coverage matrix is"
    " self-describing; the dp/sequential ratio is the measured speculation"
    " coverage",
    ("family", "path", "reason"),
)
SHARD_VERDICT_BYTES = REGISTRY.counter(
    "ktpu_shard_verdict_bytes_total",
    "Bytes fetched from device for packed per-round commit-verdict words"
    " (one small transfer per speculative merge round — the round's single"
    " host synchronization point)",
)
SHARD_REPLICATED_BYTES = REGISTRY.gauge(
    "ktpu_shard_replicated_bytes",
    "Estimated bytes of per-kind encode tensors still replicated to every"
    " mesh device in the last meshed solve (the catalog, [.., T] masks and"
    " window/bank columns shard over (dp × it) and are excluded)",
)
# ---- guardrails (guard/, PR 10) ----
GUARD_AUDITS = REGISTRY.counter(
    "ktpu_guard_audits_total",
    "Shadow audits of exactness-critical fast paths: with probability"
    " KTPU_GUARD_AUDIT_RATE a resident delta round / committed dp-shard"
    " merge group / incremental kscan grid reuse / encode-cache hit is"
    " re-derived via its exact twin and compared bit-exact; verdict is"
    " pass or divergence (a divergence writes a repro bundle to"
    " KTPU_GUARD_DIR and quarantines the path)",
    ("path", "verdict"),
)
GUARD_QUARANTINED = REGISTRY.gauge(
    "ktpu_guard_quarantined",
    "1 while a fast path is quarantined after a shadow-audit divergence"
    " (resident -> snapshot solves, speculative -> sequential replay,"
    " grid -> full recompute, encode_cache -> bypass); clears on TTL"
    " expiry (KTPU_GUARD_TTL_S) or restart",
    ("path",),
)
# ---- placement objectives (objectives/, ISSUE 19) ----
OBJECTIVE_ROUNDS = REGISTRY.counter(
    "ktpu_objective_rounds_total",
    "K-variant objective fill merge rounds by active placement policy and"
    " outcome: committed (a feasible rank variant won on score and its"
    " state landed) vs replayed (no variant packed the chunk group"
    " cleanly, so the group re-ran through the sequential dispatch under"
    " the policy's canonical rank)",
    ("policy", "outcome"),
)
OBJECTIVE_VARIANT_WINS = REGISTRY.counter(
    "ktpu_objective_variant_wins_total",
    "Committed objective rounds split by which rank variant won the"
    " score: canonical (variant 0, the policy's greedy template order) vs"
    " perturbed (a one-move promotion beat it — the measured value of"
    " riding extra variants on the dp axis)",
    ("policy", "variant"),
)
PRICING_MISSING = REGISTRY.counter(
    "ktpu_pricing_missing_total",
    "Disruption candidates whose instance type had no offering price for"
    " their (zone, capacity-type): such candidates are EXCLUDED from"
    " cost-ranked consolidation ordering instead of silently pricing at"
    " 0.0 (which made a missing price look like the cheapest node)",
)
WATCHDOG_STALLS = REGISTRY.counter(
    "ktpu_watchdog_stalls_total",
    "Solve sections the watchdog declared stalled (no completion within"
    " KTPU_WATCHDOG_S — the collective-rendezvous deadlock class for the"
    " device dispatch, runaway host work for encode/decode); each stall"
    " dumps all-thread stacks and fails the solve into the host-fallback"
    " ladder instead of hanging, under its own fallback reason"
    " (watchdog_dispatch / watchdog_encode / watchdog_decode)",
    ("section",),
)
# ---- observability: round ledger + compile observatory (obs/, PR 12) ----
GUARD_QUARANTINE_TTL = REGISTRY.gauge(
    "ktpu_guard_quarantine_ttl_seconds",
    "Seconds remaining on a fast path's quarantine TTL (0 when the path"
    " is not quarantined); the fleet-wide inspectable form of the"
    " per-process breaker, alongside /debug/quarantine",
    ("path",),
)
LEDGER_ROUNDS = REGISTRY.counter(
    "ktpu_ledger_rounds_total",
    "Solve rounds recorded by the round ledger (obs/ledger.py), by"
    " source: local (this process solved it) vs remote (the record rode"
    " SolveStream trailing metadata back from the solver service)",
    ("source",),
)
JIT_COMPILES = REGISTRY.counter(
    "ktpu_jit_compiles_total",
    "XLA compiles attributed to named solver kernels by the compile"
    " observatory (obs/observatory.py); 'anonymous' is a compile that"
    " fired outside any named kernel's dynamic extent",
    ("kernel",),
)
JIT_COMPILE_SECONDS = REGISTRY.histogram(
    "ktpu_jit_compile_seconds",
    "Backend (XLA) compile durations observed via jax.monitoring —"
    " every bucket hit after warmup is a retrace paying cold-start"
    " latency on the hot path",
)
JIT_RETRACE_STORMS = REGISTRY.counter(
    "ktpu_jit_retrace_storms_total",
    "Named kernels that recompiled more than KTPU_RETRACE_WARN times"
    " (post-warmup retrace storm: a mesh flip, PadBucketCache churn, or"
    " an unstable static argument is thrashing jit's cache key);"
    " incremented once per kernel per storm detection",
    ("kernel",),
)
# ---- critical-path waterfall + dp utilization (obs/waterfall.py, PR 15) ----
ROUND_SEGMENT_SECONDS = REGISTRY.histogram(
    "ktpu_round_segment_seconds",
    "Per-round critical-path waterfall segment self-times"
    " (obs/waterfall.py): topology, encode, per-mode dispatch enqueue,"
    " dp-merge device waits / verdict syncs / grafts / replays, wire,"
    " decode — plus the reconciled 'other' remainder, which tests pin"
    " at <=5% of the round wall",
    ("segment",),
)
SHARD_DP_UTILIZATION = REGISTRY.gauge(
    "ktpu_shard_dp_utilization",
    "Fraction of speculative dp rows in the last meshed solve by state:"
    " committed (the row's chunk group grafted — useful work), replayed"
    " (a verdict bit refused the row and its group re-ran sequentially),"
    " idle (dispatch padding — fewer ready groups than dp rows); the"
    " three fractions sum to 1 whenever any merge round ran",
    ("state",),
)
# ---- fleet-scale serving (fleet/, PR 16) ----
SESSION_EVICTIONS = REGISTRY.counter(
    "ktpu_rpc_session_evictions_total",
    "Resident sessions dropped from the solver service registry, by"
    " reason: capacity (LRU past KTPU_SESSION_CAP), fault (injected"
    " rpc.session.evict chaos eviction), epoch (a Configure changed the"
    " cluster shape — templates/max_claims/pads/mesh — so every bound"
    " session is invalid), stale_chain (registry slot recycled under a"
    " different state chain than the client's fingerprint)",
    ("reason",),
)
FLEET_SHED = REGISTRY.counter(
    "ktpu_fleet_shed_total",
    "Solve rounds shed by fleet admission control, by reason: queue_full"
    " (the bounded per-replica solve queue hit KTPU_FLEET_QUEUE and the"
    " oldest waiting round was re-routed onto the host-solve ladder"
    " instead of stalling the client)",
    ("reason",),
)
FLEET_HANDOFFS = REGISTRY.counter(
    "ktpu_fleet_handoffs_total",
    "Session-mobility outcomes when a replica receives a fingerprint for"
    " resident state it does not hold: adopted (the capsule transcript"
    " replayed to a bit-equal fingerprint chain — the round proceeds as a"
    " delta with no client-visible loss), fingerprint_mismatch (the"
    " rebuilt chain disagreed; fall back to SESSION_LOST), replay_failed"
    " (transcript replay errored), no_capsule (the bus had no capsule"
    " for that session/fingerprint), shape_mismatch (capsule was built"
    " against a different template/config shape)",
    ("outcome",),
)
FLEET_BUS_MESSAGES = REGISTRY.counter(
    "ktpu_fleet_bus_messages_total",
    "Guardrail-bus traffic by topic (quarantine | audit | session |"
    " compile) and direction (published | received); received counts"
    " exclude a member's own messages",
    ("topic", "direction"),
)
FLEET_RETARGETS = REGISTRY.counter(
    "ktpu_fleet_retargets_total",
    "Client endpoint retargets inside the fleet routing front, by"
    " reason: transport (transient-code retries exhausted against the"
    " current replica), circuit_open (the per-endpoint breaker is"
    " cooling down); the session fingerprint survives the retarget so"
    " the new replica can adopt the capsule transcript",
    ("reason",),
)
FLEET_WARM_ANNOUNCED = REGISTRY.counter(
    "ktpu_fleet_warm_announced_total",
    "Freshly compiled kernel keys announced by fleet peers over the"
    " compile-warmer bus topic, per named kernel — a replica seeing an"
    " announcement knows the shared persistent compile cache now holds"
    " that key before it ever pays the compile itself",
    ("kernel",),
)
FLEET_BUS_ROTATIONS = REGISTRY.counter(
    "ktpu_fleet_bus_rotations_total",
    "FileBus topic-log compactions: the append log exceeded"
    " KTPU_BUS_MAX_BYTES and its oldest complete lines were dropped,"
    " with the surviving tail rewritten behind a base-offset header so"
    " live subscribers' fetch offsets keep meaning the same bytes",
    ("topic",),
)
SLO_EVENTS = REGISTRY.counter(
    "ktpu_slo_events_total",
    "Service-level objective events by objective (latency |"
    " availability) and outcome (good | bad); latency events come from"
    " round waterfall walls vs KTPU_SLO_LATENCY_S, availability events"
    " from solve outcomes plus fleet shed / retarget / handoff /"
    " quarantine signals on the guardrail bus",
    ("objective", "outcome"),
)
SLO_BURN_RATE = REGISTRY.gauge(
    "ktpu_slo_burn_rate",
    "Multi-window SLO burn rate per objective: the window's bad-event"
    " fraction divided by the error budget (1 - KTPU_SLO_TARGET); 1.0"
    " burns the budget exactly at the objective's edge, >1 burns it"
    " faster — the classic page-on-both-windows signal",
    ("objective", "window"),
)
SLO_BUDGET_REMAINING = REGISTRY.gauge(
    "ktpu_slo_error_budget_remaining",
    "Fraction of the long-window error budget still unspent per"
    " objective (1.0 = no bad events, 0.0 = budget exhausted; clamped"
    " at zero once overspent)",
    ("objective",),
)
