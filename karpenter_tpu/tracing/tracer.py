"""Decision-provenance span tracer.

The Dapper/OpenTelemetry lineage (PAPERS.md) applied to the provisioning
and disruption hot loops: nested spans with per-span attributes answer
"where inside the north-star solve did the time go" the same way
the reference's pprof handlers answer CPU questions — but along the
pipeline's own stage boundaries (batcher wait -> topology build ->
encode -> device dispatch -> wire transfer -> decode -> claim
creation/bind) instead of stack samples. Pipelined solves additionally
emit a `solve.pipeline` span with per-group `solve.pipeline.chunk[i]`
children: each carries wire_s / decode_s / in_flight attributes and the
parent carries `overlap_frac` — the share of wire+decode time hidden
behind in-flight device compute (overlap attribution; chunk spans
stitch across the gRPC split like every other span).

Design constraints, in order:

- ~zero cost when disabled (the default): ``TRACER.span(...)`` is one
  attribute check returning a shared no-op context manager; no ids, no
  clock reads, no allocation.
- < 1 % of a north-star solve when enabled: spans are coarse (per stage
  / per dispatch run, never per pod) and a span start+end is two
  ``perf_counter`` reads, one small allocation, and one short lock hold.
- bounded memory: a ring of the last ``max_traces`` completed traces,
  and a per-trace span cap so a runaway loop can't pin unbounded spans.

Trace assembly: a span started with no current span becomes a trace
root; children inherit the trace id through a ``contextvars.ContextVar``
(so threads and nested calls both work). A trace is flushed to the ring
when its last live span ends (a plain refcount — no explicit "root"
bookkeeping, which also makes server-side fragments work, below).

Cross-process stitching: the gRPC client injects ``ktpu-trace-id`` /
``ktpu-span-id`` request metadata; the solver service seeds its handler
thread's context from them (``server_span``), so a remote Solve's
server-side spans carry the CLIENT's trace id. In-process (tests, the
bench harness) both sides share one tracer and the trace flushes as a
single stitched record; across real processes each side exports its
fragment with the shared trace id and stitching is a group-by-trace-id
over the JSONL files.

Export: ``/debug/traces`` (utils/runtime.py, behind --enable-profiling)
serves the ring as JSON; setting ``KTPU_TRACE_DIR`` opts into JSONL
export (one completed trace per line, per-process file) and implicitly
enables the tracer.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

MAX_TRACES = 256
MAX_SPANS_PER_TRACE = 4096


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start: float  # perf_counter seconds (duration math)
    end: float = 0.0
    wall_start: float = 0.0  # epoch seconds (export/correlation only)
    attrs: dict = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return max(self.end - self.start, 0.0)

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def as_dict(self) -> dict:
        out = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "name": self.name,
            "start": self.start,
            "duration_s": self.duration_s,
            "wall_start": self.wall_start,
        }
        if self.parent_id:
            out["parent_id"] = self.parent_id
        if self.attrs:
            out["attrs"] = self.attrs
        return out


class _NoopSpan:
    """Shared disabled-path span: supports the full Span surface."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass

    def set(self, **attrs) -> None:
        pass


_NOOP = _NoopSpan()


class _SpanCtx:
    __slots__ = ("_tracer", "span", "_token")

    def __init__(self, tracer: "Tracer", span: Span, token) -> None:
        self._tracer = tracer
        self.span = span
        self._token = token

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.span.attrs.setdefault("error", exc_type.__name__)
        self._tracer._end(self.span, self._token)


class Tracer:
    def __init__(
        self,
        max_traces: int = MAX_TRACES,
        clock=time.perf_counter,
        wall=time.time,
    ):
        # injectable clocks (tests drive span ordering deterministically
        # instead of assuming wall-clock monotonic interleaving): `clock`
        # feeds duration math (perf_counter), `wall` feeds the epoch
        # correlation stamps
        self._clock = clock
        self._wall = wall
        # KTPU_TRACE_DIR is the opt-in for JSONL export AND implicitly
        # enables tracing (an exporter with nothing to export is useless)
        self.enabled = bool(os.environ.get("KTPU_TRACE_DIR"))
        self._ctx: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
            "ktpu_current_span", default=None
        )
        self._lock = threading.Lock()
        self._traces: deque[dict] = deque(maxlen=max_traces)
        self._open: dict[str, list[Span]] = {}  # trace id -> finished spans
        self._refs: dict[str, int] = {}  # trace id -> live span count
        self._decisions: dict[str, list[dict]] = {}
        # process-unique id prefix + a counter: ids must be unique across
        # the control plane and the solver service for stitching to work
        self._prefix = os.urandom(4).hex()
        self._seq = itertools.count(1)

    # -- lifecycle ---------------------------------------------------------

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop all recorded state (tests; never called in production)."""
        with self._lock:
            self._traces.clear()
            self._open.clear()
            self._refs.clear()
            self._decisions.clear()

    def _new_id(self) -> str:
        return f"{self._prefix}{next(self._seq):08x}"

    # -- span creation -----------------------------------------------------

    def span(self, name: str, **attrs):
        """Start a nested span; use as ``with TRACER.span("encode"):``.
        A span started with no current span roots a new trace."""
        if not self.enabled:
            return _NOOP
        parent = self._ctx.get()
        if parent is None:
            trace_id = self._new_id()
            parent_id = None
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        sp = Span(
            trace_id=trace_id,
            span_id=self._new_id(),
            parent_id=parent_id,
            name=name,
            start=self._clock(),
            wall_start=self._wall(),
            attrs=attrs,
        )
        with self._lock:
            self._refs[trace_id] = self._refs.get(trace_id, 0) + 1
        token = self._ctx.set(sp)
        return _SpanCtx(self, sp, token)

    def server_span(self, name: str, trace_id: Optional[str], parent_span_id: Optional[str], **attrs):
        """Root a server-side fragment under a REMOTE parent (the trace
        context that arrived in request metadata). Falls back to a plain
        span when no context crossed the wire."""
        if not self.enabled:
            return _NOOP
        if not trace_id:
            return self.span(name, **attrs)
        sp = Span(
            trace_id=trace_id,
            span_id=self._new_id(),
            parent_id=parent_span_id or None,
            name=name,
            start=self._clock(),
            wall_start=self._wall(),
            attrs=attrs,
        )
        with self._lock:
            self._refs[trace_id] = self._refs.get(trace_id, 0) + 1
        token = self._ctx.set(sp)
        return _SpanCtx(self, sp, token)

    def record_span(self, name: str, duration_s: float, **attrs) -> None:
        """Record an already-elapsed child span ending now (e.g. the
        batcher's debounce window, measured on the injected — possibly
        fake — clock, so it can't be bracketed with perf_counter)."""
        if not self.enabled:
            return
        parent = self._ctx.get()
        if parent is None:
            return
        end = self._clock()
        sp = Span(
            trace_id=parent.trace_id,
            span_id=self._new_id(),
            parent_id=parent.span_id,
            name=name,
            start=end - max(duration_s, 0.0),
            end=end,
            wall_start=self._wall() - max(duration_s, 0.0),
            attrs=attrs,
        )
        with self._lock:
            spans = self._open.setdefault(sp.trace_id, [])
            if len(spans) < MAX_SPANS_PER_TRACE:
                spans.append(sp)

    # -- context propagation ----------------------------------------------

    def context(self) -> Optional[tuple[str, str]]:
        """(trace_id, span_id) of the current span, for wire metadata."""
        cur = self._ctx.get()
        if cur is None:
            return None
        return cur.trace_id, cur.span_id

    def current(self) -> Optional[Span]:
        return self._ctx.get()

    # -- decisions ---------------------------------------------------------

    def add_decision(self, decision: dict) -> None:
        """Attach a SchedulingDecision record to the current trace."""
        if not self.enabled:
            return
        cur = self._ctx.get()
        if cur is None:
            return
        with self._lock:
            ds = self._decisions.setdefault(cur.trace_id, [])
            if len(ds) < MAX_SPANS_PER_TRACE:
                ds.append(decision)

    # -- completion / readout ----------------------------------------------

    def _end(self, sp: Span, token) -> None:
        sp.end = self._clock()
        self._ctx.reset(token)
        trace = None
        with self._lock:
            spans = self._open.setdefault(sp.trace_id, [])
            if len(spans) < MAX_SPANS_PER_TRACE:
                spans.append(sp)
            n = self._refs.get(sp.trace_id, 1) - 1
            if n > 0:
                self._refs[sp.trace_id] = n
            else:
                # last live span: the trace is (locally) complete
                self._refs.pop(sp.trace_id, None)
                finished = self._open.pop(sp.trace_id, [])
                decisions = self._decisions.pop(sp.trace_id, [])
                trace = {
                    "trace_id": sp.trace_id,
                    "root": sp.name,
                    "duration_s": sp.duration_s,
                    "spans": [s.as_dict() for s in finished],
                }
                if decisions:
                    trace["decisions"] = decisions
                self._traces.append(trace)
        if trace is not None:
            self._export(trace)

    def traces(self) -> list[dict]:
        """The ring of recently completed traces, oldest first."""
        with self._lock:
            return list(self._traces)

    def trace(self, trace_id: str) -> Optional[dict]:
        """One trace by id, fragments merged (a remote fragment that
        flushed separately shares the trace id)."""
        spans: list[dict] = []
        decisions: list[dict] = []
        root = None
        duration = 0.0
        with self._lock:
            for t in self._traces:
                if t["trace_id"] != trace_id:
                    continue
                spans.extend(t["spans"])
                decisions.extend(t.get("decisions", ()))
                root = root or t["root"]
                duration = max(duration, t["duration_s"])
        if not spans:
            return None
        out = {"trace_id": trace_id, "root": root, "duration_s": duration, "spans": spans}
        if decisions:
            out["decisions"] = decisions
        return out

    def _export(self, trace: dict) -> None:
        trace_dir = os.environ.get("KTPU_TRACE_DIR")
        if not trace_dir:
            return
        try:
            os.makedirs(trace_dir, exist_ok=True)
            path = os.path.join(trace_dir, f"ktpu-traces-{os.getpid()}.jsonl")
            with open(path, "a") as f:
                f.write(json.dumps(trace) + "\n")
        except OSError:
            pass  # export must never take down the control plane


# the process-global tracer every instrumentation site imports
TRACER = Tracer()
