"""The scheduling explainer: "why did pod X not land anywhere".

Counterpart of the reference's unschedulable-pod error events — the
scheduler there surfaces the failed requirement in the FailedScheduling
event message (scheduler.go:587-612 / events.go PodFailedToScheduleEvent)
— extended with relaxation-ladder provenance: which preference rungs the
shared ladder (preferences.py) shed before giving up.

The per-nodepool rejection walk runs POST-HOC over the solve's final
unschedulable set, never inside the hot loop: it replays the cheap
template-level gates (taints, requirement compatibility, the
instance-type triple filter) for the handful of failing pods, so both
engines — host oracle and device kernel — get identical explanations
for free, and the all-scheduled happy path pays nothing.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.models import labels as l
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.scheduling.taints import tolerates_all
from karpenter_tpu.utils import resources as res

# canonical reason slugs for the ktpu_unschedulable_pods gauge labels
# (free-text reasons would explode the label cardinality)
_SLUGS = (
    ("scheduling timeout exceeded", "solve_timeout"),
    ("claim-slot capacity", "no_room"),
    ("no compatible in-flight claim or template", "incompatible"),
    ("gang does not fit", "gang_spill"),
    ("gang waiting", "gang_waiting"),
    ("invalid gang", "gang_invalid"),
    ("resourceclaim", "dra"),
    ("resource claim", "dra"),
)
MAX_EXPLAINED_PODS = 512  # bound the post-hoc walk on pathological solves
MAX_REJECTIONS_IN_MESSAGE = 5


def reason_slug(reason: str) -> str:
    low = reason.lower()
    for needle, slug in _SLUGS:
        if needle in low:
            return slug
    return "other"


@dataclass
class SchedulingDecision:
    """One pod's provenance record, attached to the live trace and
    summarized into the deduped event stream."""

    pod_name: str
    pod_uid: str
    reason: str  # the engine's unschedulable reason, verbatim
    slug: str  # canonical label for the gauge
    relaxed: list[str] = field(default_factory=list)  # ladder rungs shed
    rejections: list[dict] = field(default_factory=list)  # per nodepool

    def as_dict(self) -> dict:
        return {
            "pod": self.pod_name,
            "uid": self.pod_uid,
            "outcome": "unschedulable",
            "reason": self.reason,
            "slug": self.slug,
            "relaxed": list(self.relaxed),
            "rejections": list(self.rejections),
        }

    def message(self) -> str:
        """The FailedScheduling event body: failing requirement first,
        then the relaxation steps attempted, then per-pool rejections."""
        parts = [f"Failed to schedule pod: {self.reason}"]
        if self.relaxed:
            parts.append("relaxed preferences: " + ", ".join(self.relaxed))
        shown = self.rejections[:MAX_REJECTIONS_IN_MESSAGE]
        for r in shown:
            parts.append(f"nodepool {r['nodepool']} rejected ({r['class']}): {r['detail']}")
        hidden = len(self.rejections) - len(shown)
        if hidden > 0:
            parts.append(f"(+{hidden} more nodepools rejected)")
        return "; ".join(parts)


def decision_for(
    pod: Pod, reason: str, templates, relaxed: list[str]
) -> SchedulingDecision:
    """Replay the template-level gates for one unschedulable pod and name
    what failed where. Classes, in the order the solve checks them:

    - ``taint``: an untolerated template taint (scheduler.go:695 path)
    - ``requirement``: pod requirements incompatible with the template
      (the failing key + value sets, requirements.go:181-197 wording)
    - ``instance-types``: compatible but zero instance types survive the
      requests-fit x offering-available triple filter (nodeclaim.go:541)
    - ``packing``: template-viable — the rejection happened deeper in the
      solve (topology narrowing, host ports, volume limits, claim slots)
    """
    pod_reqs = Requirements.from_pod(pod)
    rejections: list[dict] = []
    for tmpl in templates:
        err = tolerates_all(tmpl.taints, pod.spec.tolerations)
        if err is not None:
            rejections.append(
                {"nodepool": tmpl.nodepool_name, "class": "taint", "detail": err}
            )
            continue
        err = tmpl.requirements.compatible(pod_reqs, l.WELL_KNOWN_LABELS)
        if err is not None:
            rejections.append(
                {"nodepool": tmpl.nodepool_name, "class": "requirement", "detail": err}
            )
            continue
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            filter_instance_types,
        )

        combined = tmpl.requirements.copy()
        combined.add(*pod_reqs.values())
        total = res.merge(tmpl.daemon_requests, pod.total_requests())
        remaining = filter_instance_types(tmpl.instance_types, combined, total)
        if not remaining:
            rejections.append(
                {
                    "nodepool": tmpl.nodepool_name,
                    "class": "instance-types",
                    "detail": (
                        f"0/{len(tmpl.instance_types)} instance types satisfy "
                        "requests, offerings and minValues"
                    ),
                }
            )
            continue
        rejections.append(
            {
                "nodepool": tmpl.nodepool_name,
                "class": "packing",
                "detail": (
                    f"{len(remaining)} instance types viable; rejected deeper in "
                    "the solve (topology/host ports/volumes/claim slots)"
                ),
            }
        )
    return SchedulingDecision(
        pod_name=pod.name,
        pod_uid=pod.uid,
        reason=reason,
        slug=reason_slug(reason),
        relaxed=list(relaxed),
        rejections=rejections,
    )
