"""Decision-provenance tracing: span tracer + scheduling explainer."""

from karpenter_tpu.tracing.tracer import MAX_TRACES, Span, Tracer, TRACER
from karpenter_tpu.tracing.explainer import (
    MAX_EXPLAINED_PODS,
    SchedulingDecision,
    decision_for,
    reason_slug,
)

__all__ = [
    "MAX_EXPLAINED_PODS",
    "MAX_TRACES",
    "SchedulingDecision",
    "Span",
    "TRACER",
    "Tracer",
    "decision_for",
    "reason_slug",
]
