"""Test fixtures + expectations DSL.

The analog of the reference's pkg/test (object factories, envtest
environment) and pkg/test/expectations/expectations.go: drive a full
schedule→launch→bind cycle and assert on the resulting cluster, skew
distributions, metrics and resource budgets — against the real kwok
provider + manager loop, so tests measure the same pipeline the parity
suites pin.

Reference map:
- Env / env()                 <- test.NewEnvironment (environment.go:141)
- expect_provisioned          <- ExpectProvisioned (expectations.go:324-410)
- expect_not_provisioned      <- ExpectNotScheduled
- make_nodes_initialized      <- ExpectMakeNodesInitialized (:749)
- expect_skew                 <- ExpectSkew (:929)
- expect_metric / _at_least   <- metric assertions (:887-909)
- measure_resources           <- test/suites/performance/thresholds.go:28-43
"""

from __future__ import annotations

from contextlib import contextmanager


class FakeCandidate:
    """The minimal candidate surface simulate_batch consumes."""

    def __init__(self, name, pods):
        self.name = name
        self.reschedulable_pods = pods


class Env:
    """A self-contained test environment: fake clock, in-memory store,
    kwok cloud, manager — the envtest-equivalent harness."""

    def __init__(self, catalog_size: int = 32, catalog=None, options=None):
        from karpenter_tpu.cloudprovider.fake import instance_types
        from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
        from karpenter_tpu.controllers.manager import Manager
        from karpenter_tpu.state.store import ObjectStore
        from karpenter_tpu.utils.clock import FakeClock

        self.clock = FakeClock()
        self.store = ObjectStore(self.clock)
        self.cloud = KwokCloudProvider(
            self.store, catalog=catalog or instance_types(catalog_size)
        )
        self.mgr = Manager(self.store, self.cloud, self.clock, options=options)

    # -- factories (pkg/test/{pods,nodepool}.go) ---------------------------

    def nodepool(self, name: str = "default", **overrides):
        from karpenter_tpu.models.nodepool import Budget, NodePool
        from karpenter_tpu.state.store import ObjectStore

        pool = NodePool()
        pool.metadata.name = name
        pool.spec.disruption.budgets = [Budget(nodes="100%")]
        for key, value in overrides.items():
            setattr(pool.spec, key, value)
        self.store.create(ObjectStore.NODEPOOLS, pool)
        return pool

    def pods(self, n: int = 1, prefix: str = "p", **make_pod_kwargs):
        from karpenter_tpu.models.pod import make_pod

        return [make_pod(f"{prefix}-{i}", **make_pod_kwargs) for i in range(n)]

    # -- cycle drivers ------------------------------------------------------

    def run(self, rounds: int = 1) -> None:
        """Reconcile + kubelet heartbeat + bind, `rounds` times."""
        from karpenter_tpu.controllers.manager import KubeSchedulerSim

        for _ in range(rounds):
            self.mgr.run_until_idle()
            self.cloud.simulate_kubelet_ready()
            self.mgr.run_until_idle()
            KubeSchedulerSim(self.store, self.mgr.cluster).bind_pending()
            self.mgr.run_until_idle()


def env(**kwargs) -> Env:
    return Env(**kwargs)


# -- expectations ------------------------------------------------------------


def expect_provisioned(e: Env, *pods):
    """Create the pods, drive a full cycle, assert every pod bound to a
    Ready node; returns the nodes the pods landed on
    (ExpectProvisioned, expectations.go:324-410)."""
    from karpenter_tpu.state.store import ObjectStore

    for p in pods:
        e.store.create(ObjectStore.PODS, p)
    e.run(rounds=2)
    nodes = []
    for p in pods:
        live = e.store.get(ObjectStore.PODS, p.name)
        assert live is not None and live.spec.node_name, (
            f"pod {p.name} not scheduled"
        )
        node = e.store.get(ObjectStore.NODES, live.spec.node_name)
        assert node is not None, f"pod {p.name} bound to a vanished node"
        nodes.append(node)
    return nodes


def expect_not_provisioned(e: Env, *pods):
    """Create the pods, drive a cycle, assert they remain unbound."""
    from karpenter_tpu.state.store import ObjectStore

    for p in pods:
        e.store.create(ObjectStore.PODS, p)
    e.run(rounds=2)
    for p in pods:
        live = e.store.get(ObjectStore.PODS, p.name)
        assert live is not None and not live.spec.node_name, (
            f"pod {p.name} unexpectedly scheduled to {live.spec.node_name}"
        )


def make_nodes_initialized(e: Env) -> int:
    """Fake the kubelet: all kwok nodes Ready (ExpectMakeNodesInitialized)."""
    flipped = e.cloud.simulate_kubelet_ready()
    e.mgr.run_until_idle()
    return flipped


def expect_skew(e: Env, topology_key: str, label_selector: dict) -> dict:
    """domain -> count of bound selector-matched pods over nodes' domains;
    assert on it with max(...)-min(...) (ExpectSkew, expectations.go:929)."""
    from karpenter_tpu.state.store import ObjectStore

    counts: dict[str, int] = {}
    # every reachable domain participates, even at zero
    for node in e.store.nodes():
        domain = node.metadata.labels.get(topology_key)
        if domain is not None:
            counts.setdefault(domain, 0)
    for pod in e.store.pods():
        if not pod.spec.node_name or pod.is_terminal():
            continue
        if any(pod.metadata.labels.get(k) != v for k, v in label_selector.items()):
            continue
        node = e.store.get(ObjectStore.NODES, pod.spec.node_name)
        if node is None:
            continue
        domain = node.metadata.labels.get(topology_key)
        if domain is not None:
            counts[domain] = counts.get(domain, 0) + 1
    return counts


def expect_max_skew(e: Env, topology_key: str, label_selector: dict, max_skew: int):
    counts = expect_skew(e, topology_key, label_selector)
    populated = [c for c in counts.values()]
    assert populated, f"no domains for {topology_key}"
    skew = max(populated) - min(populated)
    assert skew <= max_skew, f"skew {skew} > {max_skew}: {counts}"
    return counts


def expect_metric(name: str, value: float, **labels) -> None:
    from karpenter_tpu.utils.metrics import REGISTRY

    got = REGISTRY._families[name].get(**labels)
    assert got == value, f"{name}{labels} = {got}, want {value}"


def expect_metric_at_least(name: str, value: float, **labels) -> float:
    from karpenter_tpu.utils.metrics import REGISTRY

    got = REGISTRY._families[name].get(**labels)
    assert got >= value, f"{name}{labels} = {got}, want >= {value}"
    return got


# -- resource budgets (performance/thresholds.go:28-43) ----------------------


@contextmanager
def measure_resources(result: dict):
    """Measure CURRENT-RSS growth (MB) and CPU seconds across the block —
    the in-process analog of the e2e suite's controller memory/CPU
    thresholds, now backed by the envelope sampler (envelope/sampler.py:
    a 50ms background series, so result also carries the P95-growth and
    average-cores fields the Envelope specs assert). Fills result with
    {"rss_mb", "cpu_s", "rss_mb_p95", "avg_cores"}.

    Uses the live VmRSS (not ru_maxrss): a high-water mark set by an
    excluded warm-up (the XLA compile) would make every later growth
    assertion vacuous; CPU comes from getrusage, which counts ALL threads
    (XLA's pool included) unlike time.process_time on some platforms."""
    from karpenter_tpu.envelope.sampler import ResourceSampler

    rss0 = current_rss_mb()
    with ResourceSampler(interval_s=0.05) as sampler:
        with sampler.stage("measure"):
            yield result
    stats = sampler.stats["measure"]
    result["cpu_s"] = stats.cpu_s
    result["rss_mb"] = current_rss_mb() - rss0
    result["rss_mb_p95"] = stats.rss_mb_p95 - rss0
    result["avg_cores"] = stats.avg_cores


def current_rss_mb() -> float:
    """Live resident set size (VmRSS), not the high-water mark."""
    from karpenter_tpu.envelope.sampler import read_rss_bytes

    return read_rss_bytes() / 2**20


def build_bound_cluster(n_pods: int = 6, pod_cpu: float = 2.0, catalog=None):
    """A cluster of kwok nodes with bound pods pinned to the 4-cpu type
    (2-cpu pods: one node per pod, so consolidation has work to find).

    Returns (clock, store, cloud, mgr) with every pod bound.
    """
    from karpenter_tpu.cloudprovider.fake import new_instance_type
    from karpenter_tpu.controllers.manager import KubeSchedulerSim
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.state.store import ObjectStore

    from karpenter_tpu.models.nodepool import NodePool

    if catalog is None:
        catalog = [new_instance_type("n-4x", cpu=4), new_instance_type("n-8x", cpu=8)]
    e = Env(catalog=catalog)
    clock, store, cloud, mgr = e.clock, e.store, e.cloud, e.mgr
    # plain NodePool: keep the DEFAULT 10% disruption budget — callers
    # that need unrestricted disruption (test_whatif) override explicitly,
    # and the what-if benches must exercise budget-gated behavior
    store.create(ObjectStore.NODEPOOLS, NodePool())
    for i in range(n_pods):
        store.create(
            ObjectStore.PODS,
            make_pod(f"p{i}", cpu=pod_cpu, node_selector={l.LABEL_INSTANCE_TYPE: "n-4x"}),
        )
    mgr.run_until_idle()
    cloud.simulate_kubelet_ready()
    mgr.run_until_idle()
    KubeSchedulerSim(store, mgr.cluster).bind_pending()
    mgr.run_until_idle()
    assert all(p.spec.node_name for p in store.pods())
    return clock, store, cloud, mgr


def node_candidates(store):
    """One FakeCandidate per node carrying bound pods, sorted by name."""
    by_node: dict[str, list] = {}
    for p in store.pods():
        if p.spec.node_name:
            by_node.setdefault(p.spec.node_name, []).append(p)
    return [FakeCandidate(name, pods) for name, pods in sorted(by_node.items())]
