"""Shared simulation fixtures for tests and benchmarks.

The analog of the reference's pkg/test fixture package: canonical small
clusters built through the real kwok provider + manager loop, so tests and
benchmarks measure the same bootstrap the parity suites pin.
"""

from __future__ import annotations


class FakeCandidate:
    """The minimal candidate surface simulate_batch consumes."""

    def __init__(self, name, pods):
        self.name = name
        self.reschedulable_pods = pods


def build_bound_cluster(n_pods: int = 6, pod_cpu: float = 2.0, catalog=None):
    """A cluster of kwok nodes with bound pods pinned to the 4-cpu type
    (2-cpu pods: one node per pod, so consolidation has work to find).

    Returns (clock, store, cloud, mgr) with every pod bound.
    """
    from karpenter_tpu.cloudprovider.fake import new_instance_type
    from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider
    from karpenter_tpu.controllers.manager import KubeSchedulerSim, Manager
    from karpenter_tpu.models import labels as l
    from karpenter_tpu.models.nodepool import NodePool
    from karpenter_tpu.models.pod import make_pod
    from karpenter_tpu.state.store import ObjectStore
    from karpenter_tpu.utils.clock import FakeClock

    clock = FakeClock()
    store = ObjectStore(clock)
    if catalog is None:
        catalog = [new_instance_type("n-4x", cpu=4), new_instance_type("n-8x", cpu=8)]
    cloud = KwokCloudProvider(store, catalog=catalog)
    mgr = Manager(store, cloud, clock)
    store.create(ObjectStore.NODEPOOLS, NodePool())
    for i in range(n_pods):
        store.create(
            ObjectStore.PODS,
            make_pod(f"p{i}", cpu=pod_cpu, node_selector={l.LABEL_INSTANCE_TYPE: "n-4x"}),
        )
    mgr.run_until_idle()
    cloud.simulate_kubelet_ready()
    mgr.run_until_idle()
    KubeSchedulerSim(store, mgr.cluster).bind_pending()
    mgr.run_until_idle()
    assert all(p.spec.node_name for p in store.pods())
    return clock, store, cloud, mgr


def node_candidates(store):
    """One FakeCandidate per node carrying bound pods, sorted by name."""
    by_node: dict[str, list] = {}
    for p in store.pods():
        if p.spec.node_name:
            by_node.setdefault(p.spec.node_name, []).append(p)
    return [FakeCandidate(name, pods) for name, pods in sorted(by_node.items())]
