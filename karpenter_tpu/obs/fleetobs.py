"""Fleet observatory: one cross-replica timeline out of N per-replica ones.

PRs 12/15 gave every replica a flight recorder (ring + JSONL spill) and a
per-round waterfall; PR 16 made the solver a fleet. This module is the
aggregation layer the fleet was missing: it merges

* this process's in-memory ledger ring (which, in co-located fleets like
  ``bench --fleet`` and the tests, all replicas share),
* any number of spilled ledger directories (``KTPU_FLEET_OBS_DIRS``, a
  colon-separated list — point it at peers' ``KTPU_LEDGER_DIR``s on a
  shared filesystem), and
* telemetry frames pumped off the guardrail bus by live ``FleetMember``s
  (peers' rounds arrive compact, no shared disk needed),

into one deduplicated, time-ordered record stream. Records are keyed by
``(replica, seq)`` — each replica's ledger seq is monotone, so the same
round seen via ring + spill + bus collapses to one entry.

On top of that stream sit the two debug surfaces the runtime serves:
``/debug/fleet`` (per-replica rollup + SLO burn rates) and
``/debug/trace/<id>`` (every record stamped with that fleet trace id, in
order — the round's whole journey across retargets and handoffs,
adoption replays marked as such). The stitching contract: among
``source == "local"`` records that are not replays, every round sig
appears exactly once fleet-wide.
"""

from __future__ import annotations

import os
import weakref
from collections import Counter
from typing import Iterable, Optional

from karpenter_tpu.obs import ledger as obs_ledger
from karpenter_tpu.obs.slo import SLO

ENV_OBS_DIRS = "KTPU_FLEET_OBS_DIRS"

#: live FleetMembers whose pumped telemetry frames feed the timeline;
#: weak so a closed/collected member simply drops out
MEMBERS: "weakref.WeakSet" = weakref.WeakSet()


def register(member) -> None:
    MEMBERS.add(member)


def obs_dirs() -> list:
    raw = os.environ.get(ENV_OBS_DIRS, "")
    dirs = [d for d in raw.split(":") if d]
    own = obs_ledger.spill_dir()
    if own and own not in dirs:
        dirs.append(own)
    return dirs


def _key(rec: dict):
    return (rec.get("replica"), rec.get("seq"), rec.get("t"))


def fleet_records(dirs: Optional[Iterable[str]] = None) -> list:
    """The merged fleet timeline, oldest first.

    Ring records win over spilled/bus copies of the same round (they are
    the caller's live dicts); everything is deduplicated by
    ``(replica, seq)`` identity."""
    seen = set()
    out = []

    def take(rec) -> None:
        if not isinstance(rec, dict):
            return
        k = _key(rec)
        if k in seen:
            return
        seen.add(k)
        out.append(rec)

    for rec in obs_ledger.LEDGER.records():
        take(rec)
    for member in list(MEMBERS):
        for rec in list(getattr(member, "remote_rounds", ())):
            take(rec)
    for d in dirs if dirs is not None else obs_dirs():
        for rec in obs_ledger.load_spilled(d):
            take(rec)
    out.sort(key=lambda r: (r.get("t") or 0.0, str(r.get("replica")), r.get("seq") or 0))
    return out


def trace_of(rec: dict) -> Optional[str]:
    trace = rec.get("trace")
    return trace.get("id") if isinstance(trace, dict) else None


def trace_records(trace_id: str, records: Optional[list] = None) -> list:
    records = fleet_records() if records is None else records
    return [r for r in records if trace_of(r) == trace_id]


def round_counts(records: Iterable[dict]) -> Counter:
    """How often each round sig appears as ORIGINAL local work — the
    exactly-once stitching invariant counts these (remote echoes and
    adoption replays are views of a round, not new rounds)."""
    counts: Counter = Counter()
    for rec in records:
        if rec.get("source") != "local" or rec.get("replay"):
            continue
        sig = rec.get("sig")
        if sig:
            counts[sig] += 1
    return counts


def stitch(trace_id: str, records: Optional[list] = None) -> Optional[dict]:
    """Everything the fleet knows about one trace id, time-ordered."""
    rounds = trace_records(trace_id, records)
    if not rounds:
        return None
    replicas = sorted({str(r.get("replica")) for r in rounds})
    traces = [r.get("trace") or {} for r in rounds]
    counts = round_counts(rounds)
    return {
        "trace_id": trace_id,
        "origin": next((t.get("origin") for t in traces if t.get("origin")), ""),
        "tenant": next((t.get("tenant") for t in traces if t.get("tenant")), ""),
        "replicas": replicas,
        "max_hop": max((t.get("hop") or 0 for t in traces), default=0),
        "rounds": rounds,
        "replays": sum(1 for r in rounds if r.get("replay")),
        # a stitched trace is consistent when no original round repeats
        "consistent": all(n == 1 for n in counts.values()),
    }


def fleet_summary(records: Optional[list] = None) -> dict:
    """Per-replica rollup + SLO state — the /debug/fleet payload."""
    records = fleet_records() if records is None else records
    replicas: dict = {}
    traces = set()
    for rec in records:
        rid = str(rec.get("replica"))
        row = replicas.setdefault(
            rid,
            {"rounds": 0, "replays": 0, "errors": 0, "modes": Counter(),
             "wall_s_sum": 0.0, "last_t": 0.0},
        )
        row["rounds"] += 1
        row["modes"][str(rec.get("mode"))] += 1
        if rec.get("replay"):
            row["replays"] += 1
        if rec.get("outcome") not in (None, "ok"):
            row["errors"] += 1
        row["wall_s_sum"] += rec.get("wall_s") or 0.0
        row["last_t"] = max(row["last_t"], rec.get("t") or 0.0)
        tid = trace_of(rec)
        if tid:
            traces.add(tid)
    for row in replicas.values():
        row["modes"] = dict(row["modes"])
        row["wall_s_sum"] = round(row["wall_s_sum"], 6)
    dup = {s: n for s, n in round_counts(records).items() if n != 1}
    return {
        "replicas": replicas,
        "records": len(records),
        "traces": len(traces),
        "duplicate_rounds": dup,
        "slo": SLO.snapshot(),
    }


# ---------------------------------------------------------------------------
# /debug payloads (utils/runtime.py serves these)
# ---------------------------------------------------------------------------


def debug_fleet() -> dict:
    return fleet_summary()


def debug_trace(trace_id: str) -> Optional[dict]:
    return stitch(trace_id)
