"""Compact fleet trace context: one id per solve round, carried everywhere.

The resident tracer (``tracing/tracer.py``) is a heavyweight, opt-in span
recorder gated on ``KTPU_TRACE_DIR``.  This module is its always-on sibling:
a four-field context — ``trace_id``, origin replica, tenant, hop count —
minted once per client round in ``rpc/client.py`` and threaded through the
wire (``ktpu-fleet-trace`` metadata), the round ledger, the waterfall,
handoff capsules, and guardrail-bus frames.  Stamping a dict onto records
that already exist costs nanoseconds; the payoff is that one round's journey
across retargets, sheds, and handoffs stitches into a single tree that
``obs/fleetobs.py`` can query by id.

Wire format is a single pipe-joined string (``id|origin|tenant|hop``) so it
survives gRPC metadata, JSON, and log lines without escaping ceremony.
``KTPU_FLEET_TRACE=0`` disables minting entirely (the bench overhead gate
flips this knob to measure the cost of propagation).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import uuid
from dataclasses import dataclass

METADATA_KEY = "ktpu-fleet-trace"

_ACTIVE: contextvars.ContextVar["TraceContext | None"] = contextvars.ContextVar(
    "ktpu_fleet_trace", default=None
)


def enabled() -> bool:
    return os.environ.get("KTPU_FLEET_TRACE", "1") not in ("0", "false", "no")


@dataclass
class TraceContext:
    """Identity of one round's fleet-wide journey.

    ``hop`` counts wire crossings and retargets: the client mints hop 0,
    bumps on every retarget, and the serving replica activates at hop+1 —
    so a round that failed over reads hop>=2 where a clean round reads 1.
    """

    trace_id: str
    origin: str
    tenant: str = ""
    hop: int = 0

    def to_wire(self) -> str:
        return "|".join(
            (self.trace_id, self.origin, self.tenant, str(self.hop))
        )

    @classmethod
    def from_wire(cls, raw: str) -> "TraceContext | None":
        parts = (raw or "").split("|")
        if len(parts) != 4 or not parts[0]:
            return None
        try:
            hop = int(parts[3])
        except ValueError:
            hop = 0
        return cls(trace_id=parts[0], origin=parts[1], tenant=parts[2], hop=hop)

    def as_dict(self) -> dict:
        return {
            "id": self.trace_id,
            "origin": self.origin,
            "tenant": self.tenant,
            "hop": self.hop,
        }

    @classmethod
    def from_dict(cls, d) -> "TraceContext | None":
        if not isinstance(d, dict) or not d.get("id"):
            return None
        return cls(
            trace_id=str(d["id"]),
            origin=str(d.get("origin", "")),
            tenant=str(d.get("tenant", "")),
            hop=int(d.get("hop", 0) or 0),
        )

    def child(self) -> "TraceContext":
        """Same trace, one hop further along (wire crossing / adoption)."""
        return TraceContext(self.trace_id, self.origin, self.tenant, self.hop + 1)


def mint(origin: str, tenant: str = "") -> TraceContext | None:
    """New trace context, or None when propagation is disabled."""
    if not enabled():
        return None
    return TraceContext(
        trace_id=uuid.uuid4().hex[:16], origin=origin, tenant=tenant, hop=0
    )


def current() -> TraceContext | None:
    return _ACTIVE.get()


@contextlib.contextmanager
def activate(ctx: TraceContext | None):
    """Install ``ctx`` as the round's trace for the duration; None no-ops."""
    if ctx is None:
        yield None
        return
    token = _ACTIVE.set(ctx)
    try:
        yield ctx
    finally:
        _ACTIVE.reset(token)


def current_dict() -> dict | None:
    ctx = _ACTIVE.get()
    return ctx.as_dict() if ctx is not None else None
