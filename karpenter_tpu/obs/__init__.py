"""Observability (ISSUE 12): round ledger + compile observatory.

- ``obs.ledger``: the always-on flight recorder — one compact record per
  solve round (mode, reason, round-sig/fingerprint chain, per-stage
  timings, guard verdicts, fallback reasons, attributed compiles) in a
  bounded ring, optionally spilled as JSONL under ``KTPU_LEDGER_DIR``
  with replayable problem capsules; ``python -m karpenter_tpu.obs.ledger``
  reconstructs incident timelines and materializes any recorded round
  into a ``guard.replay``-compatible bundle.
- ``obs.observatory``: JIT retrace telemetry — compiles attributed to
  named kernels, retrace-storm detection (``KTPU_RETRACE_WARN``),
  per-executable cost analysis, and on-demand ``jax.profiler`` capture
  behind ``/debug/profile``.
- ``obs.waterfall`` (ISSUE 15): the per-round critical-path waterfall —
  a reconciled span tree (topology/encode/dispatch/sync/graft/replay/
  wire/decode + explicit ``other``) stored on each ledger record,
  rendered by ``python -m karpenter_tpu.obs.ledger timeline --waterfall``;
  opt-out ``KTPU_WATERFALL=0``.
- ``obs.bench_diff`` (ISSUE 15): the perf-regression sentinel —
  ``python -m karpenter_tpu.obs.bench_diff A.json B.json`` diffs two
  bench stage JSONs segment-by-segment and exits non-zero past
  ``KTPU_BENCH_DIFF_THRESHOLD``.
"""

from karpenter_tpu.obs.ledger import LEDGER, RoundLedger
from karpenter_tpu.obs.observatory import named_kernel
from karpenter_tpu.obs.waterfall import RoundWaterfall

__all__ = ["LEDGER", "RoundLedger", "RoundWaterfall", "named_kernel"]
