"""Observability (ISSUE 12): round ledger + compile observatory.

- ``obs.ledger``: the always-on flight recorder — one compact record per
  solve round (mode, reason, round-sig/fingerprint chain, per-stage
  timings, guard verdicts, fallback reasons, attributed compiles) in a
  bounded ring, optionally spilled as JSONL under ``KTPU_LEDGER_DIR``
  with replayable problem capsules; ``python -m karpenter_tpu.obs.ledger``
  reconstructs incident timelines and materializes any recorded round
  into a ``guard.replay``-compatible bundle.
- ``obs.observatory``: JIT retrace telemetry — compiles attributed to
  named kernels, retrace-storm detection (``KTPU_RETRACE_WARN``),
  per-executable cost analysis, and on-demand ``jax.profiler`` capture
  behind ``/debug/profile``.
- ``obs.waterfall`` (ISSUE 15): the per-round critical-path waterfall —
  a reconciled span tree (topology/encode/dispatch/sync/graft/replay/
  wire/decode + explicit ``other``) stored on each ledger record,
  rendered by ``python -m karpenter_tpu.obs.ledger timeline --waterfall``;
  opt-out ``KTPU_WATERFALL=0``.
- ``obs.bench_diff`` (ISSUE 15): the perf-regression sentinel —
  ``python -m karpenter_tpu.obs.bench_diff A.json B.json`` diffs two
  bench stage JSONs segment-by-segment and exits non-zero past
  ``KTPU_BENCH_DIFF_THRESHOLD``.
- ``obs.tracectx`` (ISSUE 17): the compact fleet trace context
  (trace_id / origin / tenant / hop) minted per client round, carried as
  ``ktpu-fleet-trace`` metadata, and stamped onto ledger records,
  waterfalls, capsules, and bus frames; opt-out ``KTPU_FLEET_TRACE=0``.
- ``obs.fleetobs`` (ISSUE 17): the fleet observatory — merges ledger
  rings, spilled JSONL dirs (``KTPU_FLEET_OBS_DIRS``), and bus telemetry
  frames into one cross-replica timeline behind ``/debug/fleet`` and
  ``/debug/trace/<id>``.
- ``obs.traceexport`` (ISSUE 17): Chrome-trace/Perfetto JSON export of
  any round window or stitched fleet trace (one track per replica,
  waterfall spans as nested slices, handoffs as flow arrows);
  ``python -m karpenter_tpu.obs.traceexport`` writes a viewer-ready file.
- ``obs.slo`` (ISSUE 17): multi-window SLO burn-rate accounting
  (``ktpu_slo_*``): latency objective from waterfall walls
  (``KTPU_SLO_LATENCY_S``), availability objective from solve outcomes
  plus fleet shed/retarget/handoff/quarantine events, against the
  ``KTPU_SLO_TARGET`` error budget.
"""

from karpenter_tpu.obs.ledger import LEDGER, RoundLedger
from karpenter_tpu.obs.observatory import named_kernel
from karpenter_tpu.obs.waterfall import RoundWaterfall

__all__ = ["LEDGER", "RoundLedger", "RoundWaterfall", "named_kernel"]
