"""Observability (ISSUE 12): round ledger + compile observatory.

- ``obs.ledger``: the always-on flight recorder — one compact record per
  solve round (mode, reason, round-sig/fingerprint chain, per-stage
  timings, guard verdicts, fallback reasons, attributed compiles) in a
  bounded ring, optionally spilled as JSONL under ``KTPU_LEDGER_DIR``
  with replayable problem capsules; ``python -m karpenter_tpu.obs.ledger``
  reconstructs incident timelines and materializes any recorded round
  into a ``guard.replay``-compatible bundle.
- ``obs.observatory``: JIT retrace telemetry — compiles attributed to
  named kernels, retrace-storm detection (``KTPU_RETRACE_WARN``),
  per-executable cost analysis, and on-demand ``jax.profiler`` capture
  behind ``/debug/profile``.
"""

from karpenter_tpu.obs.ledger import LEDGER, RoundLedger
from karpenter_tpu.obs.observatory import named_kernel

__all__ = ["LEDGER", "RoundLedger", "named_kernel"]
