"""SLO burn-rate accounting for the solver fleet.

Two objectives, fed from the round ledger and the guardrail bus:

* **latency** — a round is good when its waterfall wall lands under
  ``KTPU_SLO_LATENCY_S`` (default 1.0 s), bad otherwise.  Every ledger
  record with a wall contributes, including telemetry frames from fleet
  peers, so the burn rate is fleet-wide wherever the bus reaches.
* **availability** — solve outcomes plus the fleet's degradation signals:
  an ``ok`` round is good; an error/quarantined round, an admission shed,
  a client retarget (a replica was unreachable), and a failed handoff are
  bad.  A successful adoption counts good — the whole point of session
  mobility is that the client never saw the loss.

Burn rate follows the multi-window convention: for each window, the
bad-event fraction divided by the error budget ``1 - KTPU_SLO_TARGET``
(default target 0.99, i.e. a 1% budget).  Burn 1.0 spends the budget
exactly at the objective's edge; paging rules typically fire when both a
short and a long window burn hot, which is why both are exported as
``ktpu_slo_burn_rate{objective,window}`` gauges.

Cost model: the tracker sits on the ledger's record path, which pins its
overhead below 100us/record — so windows keep incremental good/bad
counters (append + amortized front-eviction, O(1) per event) and gauge
export is throttled to every ``_EXPORT_EVERY`` events; ``snapshot()``
always recomputes and re-exports.  The clock is injectable so tests
drive time by hand.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..utils import metrics

# (label, seconds) — short window catches fast burns, long window catches
# slow leaks; both must run hot before anyone should be paged.
WINDOWS = (("5m", 300.0), ("1h", 3600.0))

OBJECTIVES = ("latency", "availability")

_MAX_EVENTS = 8192  # per objective per window; oldest evict first
_EXPORT_EVERY = 32  # gauge export cadence on the hot record path


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _Window:
    """One sliding window's event deque with incremental good/bad counts."""

    __slots__ = ("span", "events", "total", "bad")

    def __init__(self, span: float):
        self.span = span
        self.events: deque = deque()
        self.total = 0
        self.bad = 0

    def add(self, t: float, good: bool) -> None:
        self.events.append((t, good))
        self.total += 1
        self.bad += 0 if good else 1
        self.expire(t)
        while len(self.events) > _MAX_EVENTS:
            self._evict()

    def expire(self, now: float) -> None:
        horizon = now - self.span
        while self.events and self.events[0][0] < horizon:
            self._evict()

    def _evict(self) -> None:
        _, good = self.events.popleft()
        self.total -= 1
        self.bad -= 0 if good else 1


class SLOTracker:
    """Sliding-window good/bad event accounting with burn-rate export."""

    def __init__(self, *, target=None, latency_s=None, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._since_export = 0
        self.reconfigure(target=target, latency_s=latency_s)
        self._windows = {
            o: {label: _Window(span) for label, span in WINDOWS}
            for o in OBJECTIVES
        }

    def reconfigure(self, *, target=None, latency_s=None) -> None:
        """(Re)read objectives; env wins only when no explicit value given."""
        self.target = (
            target
            if target is not None
            else min(0.9999, max(0.5, _env_float("KTPU_SLO_TARGET", 0.99)))
        )
        self.latency_s = (
            latency_s
            if latency_s is not None
            else max(1e-6, _env_float("KTPU_SLO_LATENCY_S", 1.0))
        )

    def reset(self) -> None:
        with self._lock:
            for per in self._windows.values():
                for label, span in WINDOWS:
                    per[label] = _Window(span)
            self._since_export = 0
        self._export()

    # ------------------------------------------------------------- feeds
    def observe_latency(self, wall_s, *, t=None) -> None:
        if wall_s is None:
            return
        self._observe("latency", float(wall_s) <= self.latency_s, t)

    def observe_availability(self, good: bool, *, kind: str = "round", t=None) -> None:
        del kind  # reserved for future per-kind breakdowns
        self._observe("availability", bool(good), t)

    def observe_record(self, rec) -> None:
        """Fold one round-ledger record (local, remote, or bus frame) in."""
        if not isinstance(rec, dict):
            return
        wall = rec.get("wall_s")
        if wall is not None:
            self.observe_latency(wall)
        outcome = rec.get("outcome")
        if outcome is not None:
            bad = outcome != "ok" or rec.get("mode") == "quarantined"
            self.observe_availability(not bad)

    def _observe(self, objective: str, good: bool, t=None) -> None:
        now = self._clock() if t is None else t
        with self._lock:
            for window in self._windows[objective].values():
                window.add(now, good)
            self._since_export += 1
            due = self._since_export >= _EXPORT_EVERY
            if due:
                self._since_export = 0
        metrics.SLO_EVENTS.inc(
            objective=objective, outcome="good" if good else "bad"
        )
        if due:
            self._export(now=now)

    # ----------------------------------------------------------- reports
    def burn_rates(self, *, now=None) -> dict:
        """{objective: {window: {total, bad, burn_rate}}} over live windows."""
        now = self._clock() if now is None else now
        budget = max(1e-9, 1.0 - self.target)
        out = {}
        with self._lock:
            for objective, per in self._windows.items():
                cells = {}
                for label, window in per.items():
                    window.expire(now)
                    frac = (window.bad / window.total) if window.total else 0.0
                    cells[label] = {
                        "total": window.total,
                        "bad": window.bad,
                        "burn_rate": round(frac / budget, 4),
                    }
                out[objective] = cells
        return out

    def budget_remaining(self, *, now=None) -> dict:
        """Fraction of the long-window error budget unspent, per objective."""
        rates = self.burn_rates(now=now)
        label = WINDOWS[-1][0]
        return {
            objective: round(max(0.0, 1.0 - per[label]["burn_rate"]), 4)
            for objective, per in rates.items()
        }

    def snapshot(self, *, now=None) -> dict:
        now = self._clock() if now is None else now
        self._export(now=now)
        return {
            "target": self.target,
            "latency_objective_s": self.latency_s,
            "windows": {label: span for label, span in WINDOWS},
            "burn_rates": self.burn_rates(now=now),
            "budget_remaining": self.budget_remaining(now=now),
        }

    def _export(self, *, now=None) -> None:
        rates = self.burn_rates(now=now)
        for objective, per in rates.items():
            for label, cell in per.items():
                metrics.SLO_BURN_RATE.set(
                    cell["burn_rate"], objective=objective, window=label
                )
        for objective, remaining in self.budget_remaining(now=now).items():
            metrics.SLO_BUDGET_REMAINING.set(remaining, objective=objective)


SLO = SLOTracker()
