"""Round ledger: an always-on flight recorder for solve rounds.

The guardrails PR gave every resident session a blake2s committed-round
fingerprint chain — "the exact transcript needed for replay" — but until
now nothing recorded it: when a round was slow, quarantined, or
divergent, the evidence was gone unless a sampled audit happened to
fire. The ledger keeps one COMPACT record per solve round in a bounded
in-memory ring (``KTPU_LEDGER_RING``, default 256), optionally spilled
as JSONL under ``KTPU_LEDGER_DIR`` with size-capped rotation:

- the session round-sig and fingerprint (the replay-transcript chain),
- mode (``delta|full|invalidated|quarantined``) and its gate reason,
- per-stage ``last_timings`` (padding/scan/pipeline/shard/kscan) plus
  wall/encode/device/decode seconds,
- the shadow-audit verdict, host-fallback reason, and any compiles the
  observatory attributed to the round (kernel, seconds, flops/bytes).

When spill is enabled, a resident round additionally writes a *problem
capsule* — a full guard-bundle document (templates/pods/existing as the
RPC codec encodes them, plus the backend/env signature) whose ``rounds``
field is the session transcript up to that round. ``python -m
karpenter_tpu.obs.ledger materialize <seq>`` resolves a record to its
capsule and emits a bundle that ``python -m karpenter_tpu.guard.replay``
re-runs bit-exactly (exit 0 = the recorded round reproduces clean).

Cost model: recording is dict assembly plus one lock-guarded deque
append — no encoding, no I/O unless spill is opted in. ``bench.py
--guard`` pins the in-memory record cost below 1% of a solve.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Optional

from karpenter_tpu.utils.metrics import LEDGER_ROUNDS

ENV_DIR = "KTPU_LEDGER_DIR"
ENV_RING = "KTPU_LEDGER_RING"
DEFAULT_RING = 256

# JSONL spill rotation: rounds.jsonl rolls to .1/.2/.3 at the size cap
SPILL_FILE = "rounds.jsonl"
SPILL_MAX_BYTES = 4 * 2**20
SPILL_KEEP = 3

# the stage keys of TPUScheduler.last_timings worth keeping per record
_STAGE_KEYS = ("padding", "scan", "pipeline", "shard", "kscan")


def ring_size() -> int:
    try:
        n = int(os.environ.get(ENV_RING, DEFAULT_RING))
    except ValueError:
        return DEFAULT_RING
    return max(n, 1)


def spill_dir() -> Optional[str]:
    return os.environ.get(ENV_DIR) or None


# which replica's name gets stamped on records: the RPC service scopes
# each solve to its fleet member's id; outside any scope the process pid
# stands in (a single-process deployment IS one replica)
_REPLICA: contextvars.ContextVar = contextvars.ContextVar(
    "ktpu_ledger_replica", default=""
)


def current_replica() -> str:
    rid = _REPLICA.get()
    return rid or os.environ.get("KTPU_REPLICA_ID", "") or f"proc-{os.getpid()}"


@contextlib.contextmanager
def replica_scope(replica_id: str):
    """Stamp records made inside the scope with this replica id."""
    token = _REPLICA.set(replica_id or "")
    try:
        yield
    finally:
        _REPLICA.reset(token)


class RoundLedger:
    """Bounded ring of per-round records + optional JSONL spill."""

    def __init__(self, now=time.time):
        self._now = now
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring_size())
        self._seq = itertools.count(1)
        # capsule-sig -> filename already written (spill dedup)
        self._capsules: dict = {}

    # -- recording ---------------------------------------------------------

    def record(self, rec: dict) -> dict:
        """Stamp seq/t onto ``rec``, append it to the ring, spill it when
        KTPU_LEDGER_DIR is set, and count it. Returns the stamped record
        (the caller's dict, mutated)."""
        rec["seq"] = next(self._seq)
        rec["t"] = self._now()
        rec.setdefault("source", "local")
        rec.setdefault("replica", current_replica())
        if "trace" not in rec:
            from karpenter_tpu.obs import tracectx

            ctx = tracectx.current()
            if ctx is None:
                # no fleet round in flight: mint a local one so every
                # round — in-process solves included — is trace-queryable
                ctx = tracectx.mint(origin=rec["replica"])
            if ctx is not None:
                rec["trace"] = ctx.as_dict()
        with self._lock:
            if self._ring.maxlen != ring_size():
                self._ring = deque(self._ring, maxlen=ring_size())
            self._ring.append(rec)
        LEDGER_ROUNDS.inc(source=rec["source"])
        from karpenter_tpu.obs.slo import SLO

        SLO.observe_record(rec)
        d = spill_dir()
        if d:
            self._spill(rec, d)
        return rec

    def _spill(self, rec: dict, d: str) -> None:
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, SPILL_FILE)
            line = json.dumps(rec, sort_keys=True) + "\n"
            try:
                if os.path.getsize(path) + len(line) > SPILL_MAX_BYTES:
                    self._rotate(path)
            except OSError:
                pass  # no file yet
            with open(path, "a") as fh:
                fh.write(line)
        except OSError:
            pass  # the flight recorder must never take down a solve

    @staticmethod
    def _rotate(path: str) -> None:
        for i in range(SPILL_KEEP, 1, -1):
            src = f"{path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{path}.{i}")
        if os.path.exists(path):
            os.replace(path, f"{path}.1")

    # -- capsules ----------------------------------------------------------

    def save_capsule(self, doc: dict, sig: str) -> Optional[str]:
        """Write a guard-bundle-format problem capsule once per distinct
        signature; returns the filename (relative to the spill dir) or
        None when spill is disabled / the write failed."""
        d = spill_dir()
        if not d:
            return None
        with self._lock:
            cached = self._capsules.get(sig)
        if cached is not None:
            return cached
        from karpenter_tpu.guard import bundle as guard_bundle

        fname = f"capsule-{sig}.json"
        try:
            guard_bundle.write_doc(doc, d, fname)
        except OSError:
            return None
        with self._lock:
            self._capsules[sig] = fname
        return fname

    # -- readout -----------------------------------------------------------

    def records(self, n: Optional[int] = None) -> list:
        with self._lock:
            out = list(self._ring)
        return out if n is None else out[-n:]

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def seq(self) -> int:
        """The last assigned sequence number (0 before any record)."""
        with self._lock:
            return self._ring[-1]["seq"] if self._ring else 0

    def since(self, seq: int) -> list:
        return [r for r in self.records() if r["seq"] > seq]

    def reset(self) -> None:
        """Drop all recorded state (tests; never called in production)."""
        with self._lock:
            self._ring.clear()
            self._capsules.clear()


LEDGER = RoundLedger()


# ---------------------------------------------------------------------------
# record assembly (the scheduler-side choke points call these)
# ---------------------------------------------------------------------------


def _stage_detail(timings: dict) -> dict:
    return {k: timings[k] for k in _STAGE_KEYS if k in timings}


def _drain_compiles() -> list:
    from karpenter_tpu.obs import observatory

    return observatory.drain_notes()


def record_solve(sched, *, pods: int, wall_s: float, mode: str = "full",
                 reason: str = "snapshot", outcome: str = "ok",
                 pod_list=None, existing_nodes=None) -> dict:
    """One record for a plain (non-resident) TPUScheduler.solve round.

    When the caller hands over the actual pod objects (and the pristine
    existing nodes), a spill-enabled ledger writes the same deduped
    problem capsule resident rounds get — a single-round transcript — so
    ``materialize`` and ``/debug/trace/<id>`` work for snapshot solves
    too, not just resident sessions."""
    timings = dict(getattr(sched, "last_timings", None) or {})
    fallback = getattr(sched, "_last_fallback", None)
    rec = {
        "source": "local",
        "mode": mode,
        "reason": fallback or reason,
        "outcome": outcome,
        "pods": pods,
        "wall_s": round(wall_s, 6),
        "fallback": fallback,
        "sig": None,
        "fpr": None,
    }
    if fallback is None and outcome == "ok":
        for k in ("encode_s", "device_s", "decode_s"):
            if k in timings:
                rec[k] = round(timings[k], 6)
        stages = _stage_detail(timings)
        if stages:
            rec["stages"] = stages
        if timings.get("waterfall"):
            rec["waterfall"] = timings["waterfall"]
    if pod_list is not None and spill_dir():
        transcript = [[str(p.uid) for p in pod_list]]
        capsule = _plain_capsule(sched, pod_list, existing_nodes or (), transcript)
        if capsule is not None:
            rec["transcript"] = transcript
            rec["capsule"] = capsule
    compiles = _drain_compiles()
    if compiles:
        rec["compiles"] = compiles
    return LEDGER.record(rec)


def record_session_round(session, *, pods: int, wall_s: float) -> dict:
    """One record for a ResidentSession round: mode/reason/audit from the
    session, the round-sig + fingerprint chain link, and (when spill is
    on) a replayable problem capsule reference."""
    mode, reason = session.last_mode, session.last_reason
    if reason == "quarantined":
        mode = "quarantined"
    timings = dict(getattr(session, "last_timings", None) or {})
    rec = {
        "source": "local",
        "mode": mode,
        "reason": reason,
        "outcome": "ok",
        "pods": pods,
        "wall_s": round(wall_s, 6),
        "fallback": getattr(session.sched, "_last_fallback", None),
        "sig": None,
        "fpr": session.fingerprint or None,
    }
    for k in ("encode_s", "device_s", "decode_s"):
        if k in timings:
            rec[k] = round(timings[k], 6)
    stages = _stage_detail(timings)
    if stages:
        rec["stages"] = stages
    if timings.get("waterfall"):
        # quarantined / full rounds ran the instrumented full path; delta
        # rounds already dropped the stale copy session-side
        rec["waterfall"] = timings["waterfall"]
    audit = getattr(session, "last_audit", None)
    if audit is not None:
        rec["guard"] = {
            "verdict": audit.get("verdict"),
            "twin_s": audit.get("twin_s"),
            "bundle": audit.get("bundle"),
        }
    if getattr(session, "_replaying", False):
        # an adoption replay re-solves the capsule transcript; its records
        # are real work on this replica but the rounds themselves already
        # happened on the origin — mark them so fleet stitching counts
        # each round id exactly once
        rec["replay"] = True
    r = getattr(session, "_r", None)
    if r is not None and r.get("rounds"):
        last = r["rounds"][-1]
        rec["sig"] = last["sig"].hex()
        base_uids = [str(u) for u in r["order"][: last["start_idx"]]]
        all_uids = [str(u) for u in r["order"]]
        transcript = [base_uids, all_uids] if base_uids else [all_uids]
        rec["transcript"] = transcript
        rec["capsule"] = _maybe_capsule(session, transcript)
    compiles = _drain_compiles()
    if compiles:
        rec["compiles"] = compiles
    return LEDGER.record(rec)


def session_chain_transcript(session) -> Optional[list]:
    """Full cumulative per-round uid lists for *fingerprint-exact* replay.

    The ledger's wire transcript compresses history to two rounds
    ([base, all]) — enough to reproduce the final packing, but replaying
    it yields a different round-sig chain for 3+-round sessions. Session
    mobility needs the chain itself: round k's list is order[:boundary_k]
    where the boundaries are each later round's start_idx plus the full
    length, so replaying list-by-list reproduces every per-round arrival
    set (solve computes arrivals as the set difference against resident
    uids) and therefore every blake2s round sig — fingerprint equality
    falls out."""
    r = getattr(session, "_r", None)
    if r is None or not r.get("rounds"):
        return None
    order = r["order"]
    bounds = [rec["start_idx"] for rec in r["rounds"][1:]] + [len(order)]
    return [[str(u) for u in order[:b]] for b in bounds]


def _maybe_capsule(session, transcript: list) -> Optional[str]:
    """Write the round's problem capsule (a full guard-bundle doc whose
    rounds field is the session transcript) when spill is enabled."""
    if not spill_dir():
        return None
    r = session._r
    h = hashlib.blake2s(digest_size=8)
    for uids in transcript:
        h.update(b"\x01")
        for u in sorted(uids):
            h.update(str(u).encode())
            h.update(b"\x00")
    h.update(repr(r["exist_sig"]).encode())
    sig = h.hexdigest()
    with LEDGER._lock:
        cached = LEDGER._capsules.get(sig)
    if cached is not None:
        return cached
    from karpenter_tpu.guard import bundle as guard_bundle

    try:
        doc = guard_bundle.make_bundle(
            "resident",
            "round-ledger problem capsule",
            session.sched,
            dict(r["pod_by_uid"]),
            transcript,
            existing_nodes=r["exist_pristine"],
            detail={"fingerprint": session.fingerprint},
        )
    except Exception:
        return None  # capsule is best-effort diagnostics
    return LEDGER.save_capsule(doc, sig)


def _plain_capsule(sched, pod_list, existing_nodes, transcript: list) -> Optional[str]:
    """A deduped single-round problem capsule for a snapshot solve."""
    if not spill_dir():
        return None
    h = hashlib.blake2s(digest_size=8)
    for uids in transcript:
        h.update(b"\x01")
        for u in sorted(uids):
            h.update(str(u).encode())
            h.update(b"\x00")
    for n in existing_nodes:
        h.update(str(getattr(n, "name", n)).encode())
        h.update(b"\x00")
    sig = h.hexdigest()
    with LEDGER._lock:
        cached = LEDGER._capsules.get(sig)
    if cached is not None:
        return cached
    from karpenter_tpu.guard import bundle as guard_bundle

    try:
        doc = guard_bundle.make_bundle(
            "snapshot",
            "round-ledger problem capsule (plain solve)",
            sched,
            {p.uid: p for p in pod_list},
            transcript,
            existing_nodes=existing_nodes,
            detail={},
        )
    except Exception:
        return None  # capsule is best-effort diagnostics
    return LEDGER.save_capsule(doc, sig)


# ---------------------------------------------------------------------------
# wire form (SolveStream trailing metadata) + remote ingestion
# ---------------------------------------------------------------------------

# gRPC trailing metadata has a small default size cap; the wire record
# keeps scalars + the sig chain and drops bulky per-stage detail
_WIRE_KEYS = (
    "mode", "reason", "outcome", "pods", "wall_s", "encode_s", "device_s",
    "decode_s", "fallback", "sig", "fpr", "guard", "trace", "replica",
    "replay",
)
_WIRE_BUDGET = 6000


def wire_record(rec: dict) -> str:
    """Compact ascii-JSON form of a record for trailing metadata."""
    out = {k: rec[k] for k in _WIRE_KEYS if rec.get(k) is not None}
    if "stages" in rec:
        body = json.dumps(rec["stages"], sort_keys=True)
        if len(body) < _WIRE_BUDGET:
            out["stages"] = rec["stages"]
    if "waterfall" in rec:
        # the bounded columnar waterfall rides whenever the record still
        # fits the trailing-metadata budget with it aboard
        trial = dict(out, waterfall=rec["waterfall"])
        if len(json.dumps(trial, sort_keys=True)) < _WIRE_BUDGET:
            out = trial
    return json.dumps(out, sort_keys=True, ensure_ascii=True)


def ingest_remote(raw: str) -> Optional[dict]:
    """Record a wire-form round received from the solver service."""
    try:
        rec = json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(rec, dict):
        return None
    rec["source"] = "remote"
    return LEDGER.record(rec)


def telemetry_frame(rec: dict) -> Optional[dict]:
    """A compact bus-frame form of a record for the fleet telemetry topic.

    Same wire-safe keys as the trailing-metadata form, plus the stitching
    identity (seq/t/capsule) — peers fold these into their SLO windows
    and ``obs/fleetobs.py`` merges them into the cross-replica timeline."""
    if not isinstance(rec, dict):
        return None
    frame = {k: rec[k] for k in _WIRE_KEYS if rec.get(k) is not None}
    for k in ("seq", "t", "capsule"):
        if rec.get(k) is not None:
            frame[k] = rec[k]
    return frame or None


# ---------------------------------------------------------------------------
# CLI: incident timeline + round -> bundle materialization
# ---------------------------------------------------------------------------


def load_spilled(d: str) -> list:
    """All spilled records (rotated files included), oldest first."""
    out: list = []
    paths = [os.path.join(d, f"{SPILL_FILE}.{i}") for i in range(SPILL_KEEP, 0, -1)]
    paths.append(os.path.join(d, SPILL_FILE))
    for path in paths:
        try:
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail line mid-write
        except OSError:
            continue
    return out


def timeline_line(rec: dict) -> str:
    stamp = time.strftime("%H:%M:%S", time.gmtime(rec.get("t", 0)))
    flags = []
    if rec.get("fallback"):
        flags.append(f"fallback={rec['fallback']}")
    guard = rec.get("guard") or {}
    if guard.get("verdict"):
        flags.append(f"audit={guard['verdict']}")
    for c in rec.get("compiles", ()):
        flags.append(f"compile={c.get('kernel')}:{c.get('seconds', 0):.2f}s")
    if rec.get("capsule"):
        flags.append(f"capsule={rec['capsule']}")
    wf = rec.get("waterfall")
    if wf:
        flags.append(f"wf_other={wf.get('other_frac', 0.0):.1%}")
    return (
        f"#{rec.get('seq', '?'):>5} {stamp} {rec.get('source', '?'):>6} "
        f"{rec.get('mode', '?'):>11} {str(rec.get('reason', '')):<20} "
        f"pods={rec.get('pods', 0):<6} wall={rec.get('wall_s', 0.0):8.4f}s "
        f"sig={rec.get('sig') or '-':<16}"
        + ("  " + " ".join(flags) if flags else "")
    )


def materialize_record(rec: dict, d: str) -> dict:
    """Ledger record -> guard-bundle document, via its problem capsule."""
    capsule = rec.get("capsule")
    if not capsule:
        raise ValueError(
            f"round #{rec.get('seq')} has no capsule (non-resident round, "
            "or KTPU_LEDGER_DIR was unset when it was recorded)"
        )
    from karpenter_tpu.guard import bundle as guard_bundle

    doc = guard_bundle.load_bundle(os.path.join(d, capsule))
    doc["reason"] = (
        f"round-ledger materialization: seq={rec.get('seq')} "
        f"mode={rec.get('mode')} sig={rec.get('sig')}"
    )
    if rec.get("transcript"):
        doc["rounds"] = [list(r) for r in rec["transcript"]]
    doc.setdefault("detail", {})["ledger_seq"] = rec.get("seq")
    return doc


def main(argv: Optional[list] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.obs.ledger",
        description="round-ledger incident timeline + repro materialization",
    )
    parser.add_argument(
        "--dir", default=None,
        help=f"ledger spill directory (default: ${ENV_DIR})",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    tl = sub.add_parser("timeline", help="reconstruct the incident timeline")
    tl.add_argument("-n", type=int, default=None, help="last N rounds only")
    tl.add_argument(
        "--waterfall", action="store_true",
        help="render each round's ASCII critical-path waterfall",
    )
    mat = sub.add_parser(
        "materialize",
        help="emit a guard-replay bundle for one recorded round",
    )
    mat.add_argument("seq", type=int, help="ledger sequence number")
    mat.add_argument(
        "--out", default=None,
        help="output bundle path (default: ledger-round-<seq>.json in --dir)",
    )
    args = parser.parse_args(argv)

    d = args.dir or spill_dir()
    if not d:
        parser.error(f"no ledger directory: pass --dir or set ${ENV_DIR}")
    records = load_spilled(d)
    if args.cmd == "timeline":
        window = records if args.n is None else records[-args.n:]
        for rec in window:
            print(timeline_line(rec))
            if args.waterfall and rec.get("waterfall"):
                from karpenter_tpu.obs import waterfall as wf_mod

                for line in wf_mod.render(rec["waterfall"]):
                    print("       " + line)
        if not window:
            print(f"(no spilled rounds under {d})")
        return 0
    by_seq = {r.get("seq"): r for r in records}
    rec = by_seq.get(args.seq)
    if rec is None:
        print(f"round #{args.seq} not found under {d}")
        return 2
    try:
        doc = materialize_record(rec, d)
    except (ValueError, OSError) as err:
        print(str(err))
        return 2
    from karpenter_tpu.guard import bundle as guard_bundle

    out = args.out or os.path.join(d, f"ledger-round-{args.seq}.json")
    guard_bundle.write_doc(doc, os.path.dirname(out) or ".", os.path.basename(out))
    print(out)
    print(f"replay with: python -m karpenter_tpu.guard.replay {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
