"""Perf-regression sentinel (ISSUE 15): segment-by-segment bench diffs.

``python -m karpenter_tpu.obs.bench_diff A.json B.json`` compares two
bench stage JSON documents (the files ``bench.py --json-out`` writes, or
any committed ``BENCH_*.json``) leaf-by-leaf over their TIMING leaves —
every numeric key ending ``_s``/``_seconds`` plus every waterfall
``segments`` entry — instead of just end-to-end wall. A leaf regresses
when B exceeds A by more than the relative threshold AND by more than a
small absolute floor (sub-5ms jitter on tiny segments must not page
anyone). Exit status: 0 when nothing regressed (an identical self-diff
always passes), 1 past the threshold, 2 on unreadable input.

Threshold resolution order: ``--threshold`` flag, then
``KTPU_BENCH_DIFF_THRESHOLD``, then 0.25 (25%). ``bench.py --baseline``
runs the same diff in-process against a committed baseline document.

Leaves present in only one document are reported as structural notes,
never as regressions: a new stage or a renamed segment is a review
question, not a perf page.
"""

from __future__ import annotations

import json
import os
from typing import Optional

DEFAULT_THRESHOLD = 0.25
ENV_THRESHOLD = "KTPU_BENCH_DIFF_THRESHOLD"
# absolute regression floor: relative noise on microsecond segments is
# meaningless — a regression must also cost real wall
MIN_ABS_S = 0.005
# dp coverage ratchet (ISSUE 20): a per-family coverage fraction under a
# stage's "coverage_fraction" key that DROPS by at least this much is a
# regression — a family silently sliding off the dp path costs the
# speculation win without touching any timing leaf. Families absent
# from either document (zero-routed runs) are structural notes only.
COVERAGE_DROP = 0.05


def threshold_default() -> float:
    try:
        return float(os.environ.get(ENV_THRESHOLD, "") or DEFAULT_THRESHOLD)
    except ValueError:
        return DEFAULT_THRESHOLD


def _timing_leaves(doc, prefix: str = ""):
    """Yield (path, seconds) for every timing leaf of a bench document:
    numeric values under keys ending _s/_seconds, and every waterfall
    segments entry (segment names carry no suffix)."""
    if isinstance(doc, dict):
        for k, v in doc.items():
            path = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, (dict, list)):
                yield from _timing_leaves(v, path)
            elif isinstance(v, (int, float)) and not isinstance(v, bool):
                if str(k).endswith(("_s", "_seconds")) or ".segments." in path:
                    yield path, float(v)
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            yield from _timing_leaves(v, f"{prefix}[{i}]")


def _coverage_leaves(doc, prefix: str = ""):
    """Yield (path, fraction) for every per-family dp coverage fraction —
    the {family: dp/(dp+sequential)} maps bench stages record under a
    "coverage_fraction" key (zero-routed families are never written)."""
    if not isinstance(doc, dict):
        if isinstance(doc, list):
            for i, v in enumerate(doc):
                yield from _coverage_leaves(v, f"{prefix}[{i}]")
        return
    for k, v in doc.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if k == "coverage_fraction" and isinstance(v, dict):
            for fam, frac in v.items():
                if isinstance(frac, (int, float)) and not isinstance(frac, bool):
                    yield f"{path}.{fam}", float(frac)
        elif isinstance(v, (dict, list)):
            yield from _coverage_leaves(v, path)


def diff_docs(
    a: dict, b: dict,
    threshold: Optional[float] = None,
    min_abs: float = MIN_ABS_S,
) -> dict:
    """Compare every shared timing leaf of two bench documents.

    Returns {"rows": [...], "regressions": [...], "only_a": [...],
    "only_b": [...]}; a timing row regresses iff b > a*(1+threshold) and
    (b - a) > min_abs. Coverage rows (per-family dp coverage fractions)
    ratchet the other way: a fraction DECREASE >= COVERAGE_DROP
    regresses — more time is fine, less speculation coverage is not."""
    thr = threshold_default() if threshold is None else threshold
    av = dict(_timing_leaves(a))
    bv = dict(_timing_leaves(b))
    rows = []
    for path in sorted(set(av) & set(bv)):
        x, y = av[path], bv[path]
        if x > 0:
            ratio = y / x
        else:
            ratio = float("inf") if y > 0 else 1.0
        rows.append({
            "path": path,
            "a_s": x,
            "b_s": y,
            "delta_s": round(y - x, 6),
            "ratio": round(ratio, 4) if ratio != float("inf") else ratio,
            "regressed": bool(y > x * (1.0 + thr) and (y - x) > min_abs),
        })
    ca = dict(_coverage_leaves(a))
    cb = dict(_coverage_leaves(b))
    coverage_rows = []
    for path in sorted(set(ca) & set(cb)):
        x, y = ca[path], cb[path]
        coverage_rows.append({
            "path": path,
            "a_frac": x,
            "b_frac": y,
            "delta": round(y - x, 4),
            "regressed": bool(x - y >= COVERAGE_DROP),
        })
    return {
        "threshold": thr,
        "min_abs_s": min_abs,
        "rows": rows,
        "coverage_rows": coverage_rows,
        "regressions": [r for r in rows if r["regressed"]]
        + [r for r in coverage_rows if r["regressed"]],
        "only_a": sorted(set(av) - set(bv)) + sorted(set(ca) - set(cb)),
        "only_b": sorted(set(bv) - set(av)) + sorted(set(cb) - set(ca)),
    }


def format_report(diff: dict, a_name: str = "A", b_name: str = "B") -> list:
    """Human-readable report lines for a diff_docs result."""
    rows = diff["rows"]
    regs = diff["regressions"]
    lines = [
        f"bench_diff: {len(rows)} shared timing leaves, "
        f"threshold={diff['threshold']:.0%} (+{diff['min_abs_s'] * 1e3:.0f}ms floor)"
    ]
    for r in regs:
        if "a_frac" in r:
            lines.append(
                f"  REGRESSED {r['path']}: dp coverage "
                f"{r['a_frac']:.2f} -> {r['b_frac']:.2f} "
                f"({r['delta']:+.2f}; drop >= {COVERAGE_DROP:.2f})"
            )
        else:
            lines.append(
                f"  REGRESSED {r['path']}: {r['a_s']:.4f}s -> {r['b_s']:.4f}s "
                f"({r['ratio']:.2f}x, +{r['delta_s']:.4f}s)"
            )
    for path in diff["only_a"]:
        lines.append(f"  note: only in {a_name}: {path}")
    for path in diff["only_b"]:
        lines.append(f"  note: only in {b_name}: {path}")
    if not regs:
        lines.append("  ok: no segment regressed")
    return lines


def main(argv: Optional[list] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.obs.bench_diff",
        description="segment-by-segment bench regression sentinel",
    )
    parser.add_argument("a", help="baseline bench JSON")
    parser.add_argument("b", help="candidate bench JSON")
    parser.add_argument(
        "--threshold", type=float, default=None,
        help=f"relative regression threshold (default ${ENV_THRESHOLD} "
        f"or {DEFAULT_THRESHOLD})",
    )
    parser.add_argument(
        "--min-abs", type=float, default=MIN_ABS_S,
        help="absolute regression floor in seconds",
    )
    args = parser.parse_args(argv)
    try:
        with open(args.a) as fh:
            doc_a = json.load(fh)
        with open(args.b) as fh:
            doc_b = json.load(fh)
    except (OSError, ValueError) as err:
        print(f"bench_diff: unreadable input: {err}")
        return 2
    diff = diff_docs(doc_a, doc_b, threshold=args.threshold, min_abs=args.min_abs)
    for line in format_report(diff, args.a, args.b):
        print(line)
    return 1 if diff["regressions"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
