"""Per-round critical-path waterfall (ISSUE 15 tentpole).

Decomposes one solve round into an ordered, non-overlapping span tree —
topology -> encode -> per-mode dispatch/enqueue -> dp-merge device
waits / verdict syncs / grafts / replays -> wire -> decode — and
reconciles the tree against the round's measured wall so that any
unattributed time surfaces as an explicit ``other`` segment instead of
silently vanishing.

Exactness of the accounting (the argument STATUS.md §Observability
repeats): every timer here measures *host wall-clock on the single
solve thread*. Spans are context-managed (or strictly open/close
paired), so the span tree is well-formed by construction — a child's
interval is contained in its parent's, and siblings never overlap.
Device work is asynchronous, but it only ever becomes *observable* to
the host through some blocking wait (a ``fetch_tree`` wire transfer, a
``block_until_ready`` drain, a verdict-word sync) — and each of those
waits is itself a recorded leaf. Therefore every microsecond between
waterfall start and ``finalize()`` lands in exactly one *self-time*
bucket: the innermost span covering it, or ``other`` when no span
covers it. Algebraically::

    self(span)  = duration(span) - sum(duration(children))
    sum(self over all spans) = sum(duration over top-level spans)
    other = wall - sum(duration over top-level spans)
    =>  sum(segments) + other = wall          (telescoping, exact)

The identity holds even if an externally-measured leaf (``add()``)
double-books wall its siblings also measured — the parent's self-time
absorbs the difference — so the ``other <= 5%`` reconciliation pinned in
tests is a real invariant, not a tuning outcome.

Cost model: recording a span is two ``perf_counter()`` calls plus a few
list appends; the bench ``--guard`` stage hard-gates the per-round
recording cost below 1% of a solve. ``KTPU_WATERFALL=0`` opts the whole
instrument out (``round_waterfall()`` then activates nothing, and every
helper below degrades to a no-op costing one contextvar read).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from typing import Optional

ENV_OPT_OUT = "KTPU_WATERFALL"

# bounded record: the ordered span list keeps at most MAX_SPANS entries
# (overflow is counted in `dropped`, never silently lost — and the
# rollup/other accounting stays exact because overflow spans still
# debit their parents); the per-name rollup keeps MAX_NAMES names with
# the smallest remainder folded into `misc`.
MAX_SPANS = 160
MAX_NAMES = 24


def enabled() -> bool:
    return os.environ.get(ENV_OPT_OUT, "1") != "0"


_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "ktpu_waterfall", default=None
)


def current() -> Optional["RoundWaterfall"]:
    """The round waterfall active on this thread/context, if any."""
    return _ACTIVE.get()


def add_current(name: str, seconds: float) -> None:
    """Attribute an externally measured duration (ending now) as a leaf
    of the active waterfall; no-op when none is active. This is the
    hook ``ops.kernels.fetch_tree`` / the solver dispatch wrappers use
    so wire and enqueue time lands in the round's tree without
    threading a waterfall handle through every call."""
    wf = _ACTIVE.get()
    if wf is not None:
        wf.add(name, seconds)


class _Span:
    __slots__ = ("_wf", "name", "t0", "child_s", "_closed")

    def __init__(self, wf: "RoundWaterfall", name: str):
        self._wf = wf
        self.name = name
        self.t0 = 0.0
        self.child_s = 0.0
        self._closed = False

    def __enter__(self) -> "_Span":
        self._wf._stack.append(self)
        self.t0 = time.perf_counter() - self._wf.t0
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        wf = self._wf
        stack = wf._stack
        t1 = time.perf_counter() - wf.t0
        # children left open by an unwound exception close implicitly
        while stack and stack[-1] is not self:
            stack.pop()
        if stack:
            stack.pop()
        dur = t1 - self.t0
        if stack:
            stack[-1].child_s += dur
        wf._push(self.name, self.t0, dur, len(stack), dur - self.child_s)


class RoundWaterfall:
    """One solve round's span recorder. Single-threaded by design (the
    solve path is), bounded, and reconciled at ``finalize()``."""

    __slots__ = (
        "t0", "_stack", "_names", "_starts", "_durs", "_depths",
        "_self", "_top_s", "dropped",
    )

    def __init__(self):
        self.t0 = time.perf_counter()
        self._stack: list = []
        self._names: list = []
        self._starts: list = []
        self._durs: list = []
        self._depths: list = []
        self._self: dict = {}
        self._top_s = 0.0
        self.dropped = 0

    # -- recording ---------------------------------------------------------

    def span(self, name: str) -> _Span:
        """Context-managed span; nest freely."""
        return _Span(self, name)

    def add(self, name: str, seconds: float) -> None:
        """Record an externally measured duration as a leaf ending now.
        Debits the enclosing open span (if any), exactly like a nested
        span would, so the self-time algebra stays telescoping."""
        t1 = time.perf_counter() - self.t0
        stack = self._stack
        if stack:
            stack[-1].child_s += seconds
        self._push(name, max(t1 - seconds, 0.0), seconds, len(stack), seconds)

    def _push(self, name, start, dur, depth, self_s) -> None:
        self._self[name] = self._self.get(name, 0.0) + self_s
        if depth == 0:
            self._top_s += dur
        if len(self._names) >= MAX_SPANS:
            self.dropped += 1
            return
        self._names.append(name)
        self._starts.append(start)
        self._durs.append(dur)
        self._depths.append(depth)

    # -- reconciliation ----------------------------------------------------

    def finalize(self, wall_s: Optional[float] = None) -> dict:
        """Close any spans an exception left open, reconcile against the
        round wall (measured from waterfall start when not given), and
        return the compact columnar record the ledger stores."""
        while self._stack:
            self._stack[-1].close()
        wall = (
            wall_s if wall_s is not None else time.perf_counter() - self.t0
        )
        other = max(wall - self._top_s, 0.0)
        segments = {
            name: round(s, 6) for name, s in sorted(
                self._self.items(), key=lambda kv: -kv[1]
            )
        }
        if len(segments) > MAX_NAMES:
            items = list(segments.items())
            segments = dict(items[:MAX_NAMES])
            segments["misc"] = round(
                sum(s for _n, s in items[MAX_NAMES:]), 6
            )
        segments["other"] = round(other, 6)
        rec = {
            "wall_s": round(wall, 6),
            "other_frac": round(other / wall, 4) if wall > 0 else 0.0,
            "segments": segments,
            "spans": {
                "name": list(self._names),
                "start_s": [round(s, 6) for s in self._starts],
                "dur_s": [round(d, 6) for d in self._durs],
                "depth": list(self._depths),
            },
        }
        if self.dropped:
            rec["dropped_spans"] = self.dropped
        from karpenter_tpu.obs import tracectx

        ctx = tracectx.current()
        if ctx is not None:
            # the fleet trace id rides the waterfall too, so an exported
            # span tree is self-identifying even away from its record
            rec["trace_id"] = ctx.trace_id
        return rec


# ---------------------------------------------------------------------------
# module helpers (the instrumented code paths use ONLY these)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def round_waterfall():
    """Activate a fresh RoundWaterfall for one solve round (yields None
    when ``KTPU_WATERFALL=0``)."""
    if not enabled():
        yield None
        return
    wf = RoundWaterfall()
    token = _ACTIVE.set(wf)
    try:
        yield wf
    finally:
        _ACTIVE.reset(token)


@contextlib.contextmanager
def span(name: str):
    """Nest a named span under the active waterfall (no-op when none)."""
    wf = _ACTIVE.get()
    if wf is None:
        yield None
    else:
        with wf.span(name) as s:
            yield s


def open_span(name: str) -> Optional[_Span]:
    """Manual open/close pairing for loop bodies where a ``with`` block
    would force a re-indent of a long arm; pair with ``close_span``."""
    wf = _ACTIVE.get()
    if wf is None:
        return None
    return wf.span(name).__enter__()


def close_span(sp: Optional[_Span]) -> None:
    if sp is not None:
        sp.close()


# ---------------------------------------------------------------------------
# ASCII rendering (the ledger timeline CLI and /debug surface)
# ---------------------------------------------------------------------------


def render(rec: dict, width: int = 56) -> list:
    """Render a finalized waterfall record as ASCII flame/waterfall
    lines: one bar per span, positioned by start offset, indented by
    depth, with the reconciled ``other`` remainder last."""
    spans = rec.get("spans") or {}
    names = spans.get("name") or []
    starts = spans.get("start_s") or []
    durs = spans.get("dur_s") or []
    depths = spans.get("depth") or []
    wall = rec.get("wall_s") or 0.0
    if wall <= 0.0:
        wall = max(
            (s + d for s, d in zip(starts, durs)), default=1e-9
        )
    other = (rec.get("segments") or {}).get("other", 0.0)
    lines = [
        f"waterfall wall={wall * 1e3:.3f}ms other={other * 1e3:.3f}ms "
        f"({rec.get('other_frac', 0.0):.1%})"
        + (f" dropped={rec['dropped_spans']}" if rec.get("dropped_spans") else "")
    ]
    order = sorted(
        range(len(names)), key=lambda i: (starts[i], depths[i], i)
    )
    for i in order:
        off = min(int(starts[i] / wall * width), width - 1)
        w = max(int(durs[i] / wall * width), 1)
        bar = " " * off + "#" * min(w, width - off)
        label = ("  " * depths[i] + names[i])[:26]
        lines.append(
            f"  {label:<26} {durs[i] * 1e3:9.3f}ms |{bar:<{width}}|"
        )
    return lines
