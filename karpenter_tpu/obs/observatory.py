"""Compile observatory: attribute XLA compiles to named solver kernels.

The mesh is part of jit's cache key (ops/solver.py shard_hint), pad
buckets feed static shapes, and the encode epoch rebuilds catalogs — all
retrace hazards the process previously could not see. The observatory
makes every compile attributable and every retrace storm loud:

- ``named_kernel("solve_fill")`` wraps a jitted entry point; while the
  observatory is enabled, calls set a contextvar naming the kernel for
  the dynamic extent of the call (attribute access delegates to the
  wrapped function, so ``.lower`` / cache introspection keep working).
- ``jax.monitoring`` event-duration listeners observe
  ``/jax/core/compile/backend_compile_duration`` and credit the compile
  to the current kernel: ``ktpu_jit_compiles_total{kernel}`` +
  ``ktpu_jit_compile_seconds``.
- a wrap around ``jax._src.compiler.backend_compile`` captures the
  LoadedExecutable long enough to read ``cost_analysis()`` (flops /
  bytes accessed) once per compile; the next ledger record folds the
  note in.
- a retrace-storm detector fires once per kernel when its compile count
  exceeds ``KTPU_RETRACE_WARN`` (default 3): Warning event through the
  guard event recorder, a log line, and
  ``ktpu_jit_retrace_storms_total{kernel}``.

Everything is gated on an enabled flag (``--enable-profiling`` /
``enable()``): disabled, a named-kernel call is one attribute check and
the listener returns immediately — jax offers no per-listener
unregistration, so the hooks install once and stay.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
from typing import Optional

from karpenter_tpu.utils.metrics import (
    JIT_COMPILE_SECONDS,
    JIT_COMPILES,
    JIT_RETRACE_STORMS,
)

ENV_RETRACE_WARN = "KTPU_RETRACE_WARN"
DEFAULT_RETRACE_WARN = 3

# the jax.monitoring event that marks one backend (XLA) compile
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_KERNEL: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ktpu_obs_kernel", default="anonymous"
)
# fallback attribution scope (ISSUE 14 satellite): host helpers jitted
# OUTSIDE a named_kernel entry point (chunk gathers, pad-bucket
# re-dispatches, fetch preps) used to land in the `anonymous` bucket;
# the enclosing solve round opens a kernel_scope and compiles with no
# named kernel active inherit its name instead
_SCOPE: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ktpu_obs_scope", default="anonymous"
)


def _current_kernel() -> str:
    """Attribution name for the compile happening NOW: the innermost
    named_kernel if one is active, else the enclosing kernel_scope, else
    `anonymous`."""
    kernel = _KERNEL.get()
    if kernel != "anonymous":
        return kernel
    return _SCOPE.get()


@contextlib.contextmanager
def kernel_scope(name: str):
    """Name every otherwise-anonymous compile inside the block (nested
    named_kernel entry points still win)."""
    token = _SCOPE.set(name)
    try:
        yield
    finally:
        _SCOPE.reset(token)

_MAX_NOTES = 64  # pending compile notes between ledger records


class _State:
    def __init__(self):
        self.enabled = False
        self.installed = False
        self.lock = threading.Lock()
        self.compiles: dict = {}  # kernel -> count
        self.seconds: dict = {}  # kernel -> cumulative compile seconds
        self.cost: dict = {}  # kernel -> last cost_analysis summary
        self.stormed: set = set()  # kernels already reported this storm
        self.notes: list = []  # pending per-compile notes for the ledger
        self.pending_cost: dict = {}  # kernel -> cost awaiting its event


_STATE = _State()


def retrace_warn() -> int:
    try:
        return int(os.environ.get(ENV_RETRACE_WARN, DEFAULT_RETRACE_WARN))
    except ValueError:
        return DEFAULT_RETRACE_WARN


class _NamedKernel:
    """Jit-entry-point wrapper: names the kernel for compile attribution
    while enabled; transparent passthrough (including attribute access)
    otherwise."""

    def __init__(self, name: str, fn):
        self._name = name
        self._fn = fn
        self.__name__ = getattr(fn, "__name__", name)
        self.__doc__ = getattr(fn, "__doc__", None)

    def __call__(self, *args, **kwargs):
        if not _STATE.enabled:
            return self._fn(*args, **kwargs)
        token = _KERNEL.set(self._name)
        try:
            return self._fn(*args, **kwargs)
        finally:
            _KERNEL.reset(token)

    def __getattr__(self, item):
        return getattr(self._fn, item)


def named_kernel(name: str):
    def deco(fn):
        return _NamedKernel(name, fn)

    return deco


# -- hooks ------------------------------------------------------------------


#: compile fan-out: fleet members subscribe to announce fresh kernel keys
#: to peers (the cross-process compile-cache warmer)
_COMPILE_LISTENERS: list = []


def add_compile_listener(fn) -> None:
    if fn not in _COMPILE_LISTENERS:
        _COMPILE_LISTENERS.append(fn)


def remove_compile_listener(fn) -> None:
    if fn in _COMPILE_LISTENERS:
        _COMPILE_LISTENERS.remove(fn)


def _on_event_duration(event: str, duration: float, **kwargs) -> None:
    if not _STATE.enabled or event != _COMPILE_EVENT:
        return
    kernel = _current_kernel()
    JIT_COMPILES.inc(kernel=kernel)
    JIT_COMPILE_SECONDS.observe(duration)
    note = {"kernel": kernel, "seconds": round(duration, 4)}
    storm: Optional[int] = None
    with _STATE.lock:
        n = _STATE.compiles.get(kernel, 0) + 1
        _STATE.compiles[kernel] = n
        _STATE.seconds[kernel] = _STATE.seconds.get(kernel, 0.0) + duration
        cost = _STATE.pending_cost.pop(kernel, None)
        if cost is not None:
            _STATE.cost[kernel] = cost
            note.update(cost)
        if len(_STATE.notes) < _MAX_NOTES:
            _STATE.notes.append(note)
        if n > retrace_warn() and kernel not in _STATE.stormed:
            _STATE.stormed.add(kernel)
            storm = n
    for fn in list(_COMPILE_LISTENERS):
        try:
            fn(dict(note))
        except Exception:  # a broken bus must not break compile tracking
            pass
    if storm is not None:
        _report_storm(kernel, storm)


def _report_storm(kernel: str, count: int) -> None:
    JIT_RETRACE_STORMS.inc(kernel=kernel)
    msg = (
        f"retrace storm: kernel {kernel!r} compiled {count} times "
        f"(> KTPU_RETRACE_WARN={retrace_warn()}); a mesh flip, pad-bucket "
        "churn, or an unstable static argument is thrashing jit's cache"
    )
    from karpenter_tpu.utils.logging import get_logger

    get_logger().with_values(controller="obs").warn(
        "observatory: " + msg, kernel=kernel, compiles=count
    )
    from karpenter_tpu.guard import config as guard_config

    recorder = guard_config.event_recorder()
    if recorder is not None:
        try:
            from karpenter_tpu.utils.events import Event

            recorder.publish(
                Event("Solver", kernel, "Warning", "RetraceStorm", msg)
            )
        except Exception:
            pass  # eventing is best-effort


def _wrap_backend_compile() -> None:
    """Intercept ``jax._src.compiler.backend_compile`` (the module-global
    ``compile_or_get_cached`` calls) to read one ``cost_analysis()`` per
    fresh executable. Version drift in the signature or the analysis
    surface degrades to counts-only, never to a failed compile."""
    try:
        from jax._src import compiler as _jc
    except Exception:
        return
    orig = getattr(_jc, "backend_compile", None)
    if orig is None or getattr(orig, "_ktpu_obs", False):
        return

    def wrapped(*args, **kwargs):
        exe = orig(*args, **kwargs)
        if _STATE.enabled:
            try:
                cost = exe.cost_analysis()
                if isinstance(cost, (list, tuple)):
                    cost = cost[0] if cost else {}
                summary = {}
                if "flops" in cost:
                    summary["flops"] = float(cost["flops"])
                if "bytes accessed" in cost:
                    summary["bytes"] = float(cost["bytes accessed"])
                if summary:
                    with _STATE.lock:
                        _STATE.pending_cost[_current_kernel()] = summary
            except Exception:
                pass
        return exe

    wrapped._ktpu_obs = True
    _jc.backend_compile = wrapped


def enable() -> None:
    """Install the hooks (once) and start attributing compiles."""
    if not _STATE.installed:
        try:
            import jax.monitoring as _jm

            _jm.register_event_duration_secs_listener(_on_event_duration)
        except Exception:
            pass  # no monitoring API: cost wrap still counts nothing,
            # but enable() must never break the operator
        _wrap_backend_compile()
        _STATE.installed = True
    _STATE.enabled = True


def disable() -> None:
    _STATE.enabled = False


def enabled() -> bool:
    return _STATE.enabled


def reset() -> None:
    """Drop attribution state (tests)."""
    with _STATE.lock:
        _STATE.compiles.clear()
        _STATE.seconds.clear()
        _STATE.cost.clear()
        _STATE.stormed.clear()
        _STATE.notes.clear()
        _STATE.pending_cost.clear()


def snapshot() -> dict:
    """Per-kernel compile counts / cumulative seconds / last cost."""
    with _STATE.lock:
        return {
            k: {
                "compiles": n,
                "seconds": round(_STATE.seconds.get(k, 0.0), 4),
                **({"cost": _STATE.cost[k]} if k in _STATE.cost else {}),
            }
            for k, n in sorted(_STATE.compiles.items())
        }


def drain_notes() -> list:
    """Pop the compile notes accumulated since the last ledger record."""
    if not _STATE.enabled:
        return []
    with _STATE.lock:
        notes, _STATE.notes = _STATE.notes, []
    return notes


# -- on-demand device profiling (/debug/profile?seconds=) -------------------

_PROFILE_LOCK = threading.Lock()
_PROFILE_MAX_SECONDS = 30.0


def capture_device_profile(seconds: float, out_dir: Optional[str] = None) -> dict:
    """Capture a ``jax.profiler`` device trace for ``seconds`` (clamped
    to 30s) into ``out_dir`` (default: a per-pid directory under the
    ledger spill dir or the system tmpdir) and report the files written.
    One capture at a time; a concurrent request fails fast."""
    import tempfile

    import jax

    secs = min(max(float(seconds), 0.05), _PROFILE_MAX_SECONDS)
    if out_dir is None:
        from karpenter_tpu.obs import ledger as obs_ledger

        base = obs_ledger.spill_dir() or tempfile.gettempdir()
        out_dir = os.path.join(
            base, f"ktpu-profile-{os.getpid()}-{int(time.time())}"
        )
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise RuntimeError("a profile capture is already running")
    try:
        jax.profiler.start_trace(out_dir)
        time.sleep(secs)
        jax.profiler.stop_trace()
    finally:
        _PROFILE_LOCK.release()
    files = []
    for root, _, names in os.walk(out_dir):
        for name in names:
            files.append(os.path.relpath(os.path.join(root, name), out_dir))
    return {"seconds": secs, "dir": out_dir, "files": sorted(files)}
