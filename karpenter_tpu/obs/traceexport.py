"""Chrome-trace / Perfetto export for ledger rounds and fleet traces.

Any list of round-ledger records — one replica's ring, a spilled incident
window, or a stitched cross-replica trace out of ``obs/fleetobs.py`` —
becomes a standard Chrome trace-event JSON document (``{"traceEvents":
[...]}``) that https://ui.perfetto.dev and chrome://tracing open as-is:

* one track (pid) per replica, named by process-name metadata events;
* each round is a complete ("X") slice spanning its wall, with its
  waterfall spans nested inside as child slices (the waterfall records
  offsets relative to round start, so nesting is exact);
* handoffs are flow arrows ("s"/"f"): when consecutive records of one
  trace id sit on different replicas — a retargeted round, an adoption
  replay — an arrow connects them across tracks;
* the round slice's args carry the stitching identity (trace id, sig,
  hop, replay mark) and the waterfall's reconciled segment table, so the
  exactness invariant (Σ segments + other = wall) can be re-checked on
  the exported document alone: ``validate()`` does exactly that, and the
  schema round-trip test runs it on every export.

Timestamps are microseconds relative to the earliest round start in the
batch (Chrome traces don't need an epoch, and small numbers keep the
JSON compact).
"""

from __future__ import annotations

import json
import zlib
from typing import Iterable, Optional

_US = 1e6

# validation tolerance: segments/spans are stored rounded to 1e-6 s, so a
# round with MAX_NAMES segments accumulates at most ~1e-4 s of rounding
_TOL_S = 1e-3


def _pid_map(records: list) -> dict:
    replicas = sorted({str(r.get("replica")) for r in records})
    return {rid: i + 1 for i, rid in enumerate(replicas)}


def _round_name(rec: dict) -> str:
    mode = rec.get("mode") or "round"
    tag = " (replay)" if rec.get("replay") else ""
    return f"{mode} #{rec.get('seq', '?')}{tag}"


def chrome_trace(records: Iterable[dict], *, flows: bool = True) -> dict:
    """Records -> Chrome trace-event document (one track per replica)."""
    recs = [
        r for r in records
        if isinstance(r, dict) and (r.get("wall_s") or 0) > 0 and r.get("t")
    ]
    recs.sort(key=lambda r: r.get("t") or 0.0)
    pids = _pid_map(recs)
    events: list = []
    for rid, pid in pids.items():
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"replica {rid}"},
        })
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": 1,
            "args": {"name": "solve rounds"},
        })
    if not recs:
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def start_of(rec: dict) -> float:
        wf = rec.get("waterfall") or {}
        wall = wf.get("wall_s") or rec.get("wall_s") or 0.0
        return (rec.get("t") or 0.0) - wall

    t0 = min(start_of(r) for r in recs)
    slice_bounds = {}  # record identity -> (pid, ts_us, dur_us)
    for rec in recs:
        pid = pids[str(rec.get("replica"))]
        wf = rec.get("waterfall") or {}
        wall = wf.get("wall_s") or rec.get("wall_s") or 0.0
        ts = round((start_of(rec) - t0) * _US, 3)
        dur = round(wall * _US, 3)
        trace = rec.get("trace") or {}
        args = {
            "trace_id": trace.get("id"),
            "hop": trace.get("hop"),
            "tenant": trace.get("tenant"),
            "replica": rec.get("replica"),
            "seq": rec.get("seq"),
            "source": rec.get("source"),
            "reason": rec.get("reason"),
            "outcome": rec.get("outcome"),
            "sig": rec.get("sig"),
            "replay": bool(rec.get("replay")),
        }
        if wf.get("segments"):
            # the reconciled self-time table: Σ (incl. other) == wall —
            # validate() re-checks this invariant on the exported doc
            args["segments"] = wf["segments"]
            args["wall_s"] = wf.get("wall_s")
        events.append({
            "ph": "X", "cat": "round", "name": _round_name(rec),
            "pid": pid, "tid": 1, "ts": ts, "dur": dur,
            "args": {k: v for k, v in args.items() if v is not None},
        })
        slice_bounds[id(rec)] = (pid, ts, dur)
        spans = wf.get("spans") or {}
        names = spans.get("name") or []
        starts = spans.get("start_s") or []
        durs = spans.get("dur_s") or []
        depths = spans.get("depth") or []
        for name, s, d, depth in zip(names, starts, durs, depths):
            events.append({
                "ph": "X", "cat": "span", "name": name,
                "pid": pid, "tid": 1,
                "ts": round((start_of(rec) - t0 + s) * _US, 3),
                "dur": round(d * _US, 3),
                "args": {"depth": depth},
            })
    if flows:
        events.extend(_flow_events(recs, slice_bounds))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _flow_events(recs: list, slice_bounds: dict) -> list:
    """Handoff / retarget arrows: consecutive records of one trace id on
    DIFFERENT replicas get a flow step from the earlier slice to the
    later one (e.g. origin round -> adoption replay on the peer)."""
    by_trace: dict = {}
    for rec in recs:
        tid = (rec.get("trace") or {}).get("id")
        if tid:
            by_trace.setdefault(tid, []).append(rec)
    out = []
    for tid, chain in by_trace.items():
        for a, b in zip(chain, chain[1:]):
            if a.get("replica") == b.get("replica"):
                continue
            flow_id = zlib.crc32(f"{tid}:{b.get('seq')}".encode()) & 0x7FFFFFFF
            pid_a, ts_a, dur_a = slice_bounds[id(a)]
            pid_b, ts_b, dur_b = slice_bounds[id(b)]
            common = {"cat": "flow", "name": "handoff", "id": flow_id}
            out.append(dict(
                common, ph="s", pid=pid_a, tid=1,
                ts=round(ts_a + dur_a, 3),
            ))
            out.append(dict(
                common, ph="f", bp="e", pid=pid_b, tid=1,
                ts=round(ts_b + max(dur_b, 1.0) / 2, 3),
            ))
    return out


def validate(doc: dict, *, tol_s: float = _TOL_S) -> list:
    """Schema + invariant check of an exported document; returns a list
    of problem strings (empty = the trace is well-formed and every round
    slice's segment table reconciles: Σ segments + other = wall)."""
    problems = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or "ph" not in ev:
            problems.append(f"event {i}: no phase")
            continue
        ph = ev["ph"]
        if ph == "X":
            missing = [k for k in ("name", "pid", "tid", "ts", "dur") if k not in ev]
            if missing:
                problems.append(f"event {i} ({ev.get('name')}): missing {missing}")
            elif ev["dur"] < 0 or ev["ts"] < 0:
                problems.append(f"event {i} ({ev.get('name')}): negative time")
        elif ph in ("s", "f"):
            if "id" not in ev or "ts" not in ev:
                problems.append(f"flow event {i}: missing id/ts")
    # flows must pair up: every start has a finish and vice versa
    starts = {e["id"] for e in events if e.get("ph") == "s" and "id" in e}
    ends = {e["id"] for e in events if e.get("ph") == "f" and "id" in e}
    for orphan in starts ^ ends:
        problems.append(f"flow {orphan}: unpaired start/finish")
    # the waterfall exactness invariant, re-checked on the export alone
    for ev in events:
        if ev.get("ph") != "X" or ev.get("cat") != "round":
            continue
        segments = (ev.get("args") or {}).get("segments")
        if not segments:
            continue
        wall = (ev.get("args") or {}).get("wall_s") or ev["dur"] / _US
        total = sum(segments.values())
        if abs(total - wall) > max(tol_s, 0.01 * wall):
            problems.append(
                f"round {ev.get('name')}: segments sum {total:.6f}s != "
                f"wall {wall:.6f}s"
            )
    return problems


def export_trace(trace_id: str, records: Optional[list] = None) -> Optional[dict]:
    """Stitch one fleet trace id and export it; None when unknown."""
    from karpenter_tpu.obs import fleetobs

    stitched = fleetobs.stitch(trace_id, records)
    if stitched is None:
        return None
    return chrome_trace(stitched["rounds"])


def main(argv: Optional[list] = None) -> int:
    import argparse

    from karpenter_tpu.obs import fleetobs

    parser = argparse.ArgumentParser(
        prog="python -m karpenter_tpu.obs.traceexport",
        description="export ledger rounds / fleet traces as Perfetto JSON",
    )
    parser.add_argument(
        "--dir", action="append", default=None,
        help="ledger spill directory (repeatable; default: "
             "$KTPU_FLEET_OBS_DIRS + $KTPU_LEDGER_DIR)",
    )
    parser.add_argument("--trace", default=None, help="one fleet trace id only")
    parser.add_argument("-n", type=int, default=None, help="last N rounds only")
    parser.add_argument("--out", default="fleet-trace.json", help="output path")
    args = parser.parse_args(argv)

    records = fleet_records = fleetobs.fleet_records(args.dir)
    if args.trace:
        records = fleetobs.trace_records(args.trace, fleet_records)
        if not records:
            print(f"trace {args.trace!r} not found")
            return 2
    if args.n is not None:
        records = records[-args.n:]
    doc = chrome_trace(records)
    problems = validate(doc)
    with open(args.out, "w") as fh:
        json.dump(doc, fh, sort_keys=True)
    n_rounds = sum(
        1 for e in doc["traceEvents"]
        if e.get("ph") == "X" and e.get("cat") == "round"
    )
    print(f"{args.out}: {n_rounds} rounds, {len(doc['traceEvents'])} events")
    for p in problems:
        print(f"INVARIANT: {p}")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
