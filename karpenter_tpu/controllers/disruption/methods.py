"""Disruption methods, in controller priority order.

Counterpart of reference disruption/{emptiness,drift,consolidation,
multinodeconsolidation,singlenodeconsolidation}.go. Each method computes a
Command = (candidates to delete, replacement claims); first non-empty
command wins the loop (controller.go:101-115).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from karpenter_tpu.controllers.disruption.candidates import Candidate, atomic_units
from karpenter_tpu.controllers.provisioning.host_scheduler import SchedulingResult, SimClaim
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import (
    CONSOLIDATION_WHEN_EMPTY,
    CONSOLIDATION_WHEN_EMPTY_OR_UNDERUTILIZED,
    REASON_DRIFTED,
    REASON_EMPTY,
    REASON_UNDERUTILIZED,
)
from karpenter_tpu.models.nodeclaim import COND_DRIFTED

# multinodeconsolidation.go:81 batch cap
MAX_MULTI_NODE_BATCH = 100
# consolidation.go:47-48 spot-churn guards
MIN_INSTANCE_TYPES_FOR_SPOT_TO_SPOT = 15
MAX_SPOT_TO_SPOT_LAUNCH_FLEXIBILITY = 15
# multinodeconsolidation.go:35 — expire the prefix search, return the last
# valid command; singlenodeconsolidation.go:33 — abandon the candidate walk
MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS = 60.0
SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS = 180.0

# simulate(candidates, deadline=None) ->
#   (SchedulingResult, unscheduled_candidate_pod_uids)
# deadline is the calling method's (1m multi-node / 3m single-node); the
# reference's SimulateScheduling inherits the method context the same way.
SimulateFn = Callable[..., tuple[Optional[SchedulingResult], set[str]]]


@dataclass
class Command:
    candidates: list[Candidate] = field(default_factory=list)
    replacements: list[SimClaim] = field(default_factory=list)
    reason: str = ""
    results: Optional[SchedulingResult] = None

    @property
    def is_empty(self) -> bool:
        return not self.candidates

    def total_price(self) -> float:
        return sum(c.price for c in self.candidates)


def _within_budget(candidates: list[Candidate], budgets: dict[str, int]) -> list[Candidate]:
    """Prefilter preserving order so no pool exceeds its budget
    (multinodeconsolidation.go:52-80). Selection is by ATOMIC UNIT: a
    gang's slice hosts are taken together or not at all — a budget that
    cannot absorb the whole slice skips the gang instead of splitting it.
    Non-gang candidates behave exactly as before (singleton units)."""
    taken: dict[str, int] = {}
    out = []
    for unit in atomic_units(candidates):
        need: dict[str, int] = {}
        for c in unit:
            need[c.nodepool.name] = need.get(c.nodepool.name, 0) + 1
        if all(taken.get(p, 0) + n <= budgets.get(p, 0) for p, n in need.items()):
            for p, n in need.items():
                taken[p] = taken.get(p, 0) + n
            out.extend(unit)
    return out


def _complete_units(
    filtered: list[Candidate], all_candidates: list[Candidate]
) -> list[Candidate]:
    """Drop gang candidates whose slice peers did not survive a method's
    eligibility filter: a strict subset of a slice is never disruptable
    (the all-or-none eviction invariant)."""
    pops: dict[str, int] = {}
    for c in all_candidates:
        if c.gang_key:
            pops[c.gang_key] = pops.get(c.gang_key, 0) + 1
    have: dict[str, int] = {}
    for c in filtered:
        if c.gang_key:
            have[c.gang_key] = have.get(c.gang_key, 0) + 1
    return [
        c
        for c in filtered
        if not c.gang_key or have[c.gang_key] >= pops.get(c.gang_key, 0)
    ]


def _unit_savings_ratio(unit: list[Candidate]) -> float:
    """The unit analog of Candidate.savings_ratio: an ordinary node keeps
    its own ratio (pre-gang sort order, bit-for-bit); a slice is priced
    and cost-weighted as a whole."""
    if len(unit) == 1:
        return unit[0].savings_ratio
    price = sum(c.price for c in unit)
    cost = sum(c.disruption_cost for c in unit)
    return price / cost if cost else price


def _unit_zone(unit: list[Candidate]) -> str:
    sn = unit[0].state_node
    obj = sn.node or sn.node_claim
    if obj is None:
        return ""
    return obj.metadata.labels.get(l.LABEL_TOPOLOGY_ZONE, "")


def _order_units(units: list[list[Candidate]]) -> list[list[Candidate]]:
    """Consolidation's half of the placement objective: order atomic
    units by the SAME scores provisioning optimizes (objectives/), so
    both controllers pull the fleet toward one consistent objective.

    ``lexical`` reproduces the legacy savings-ratio sort bit-for-bit.
    ``cost_min`` walks the priciest units first (each successful
    consolidation frees the most dollars), EXCLUDING unknown-price units
    from the cost ranking — they trail in legacy order instead of
    masquerading as free (the candidates.py price_known fix). The other
    policies mirror their provisioning scores: ``frag_aware`` empties
    the sparsest nodes first, ``topo_spread`` drains the most crowded
    zone first, ``gang_slice`` prefers single hosts over whole slices."""
    from karpenter_tpu import objectives

    policy = objectives.active_policy()
    if policy == "lexical":
        return sorted(units, key=_unit_savings_ratio)
    if policy == "cost_min":
        known = [u for u in units if all(c.price_known for c in u)]
        unknown = [u for u in units if not all(c.price_known for c in u)]
        known.sort(key=lambda u: (-sum(c.price for c in u), _unit_savings_ratio(u)))
        unknown.sort(key=_unit_savings_ratio)
        return known + unknown
    if policy == "frag_aware":
        return sorted(
            units,
            key=lambda u: (
                sum(len(c.reschedulable_pods) for c in u),
                _unit_savings_ratio(u),
            ),
        )
    if policy == "topo_spread":
        crowd: dict[str, int] = {}
        for u in units:
            z = _unit_zone(u)
            crowd[z] = crowd.get(z, 0) + len(u)
        return sorted(
            units,
            key=lambda u: (-crowd.get(_unit_zone(u), 0), _unit_savings_ratio(u)),
        )
    if policy == "gang_slice":
        return sorted(units, key=lambda u: (len(u), _unit_savings_ratio(u)))
    return sorted(units, key=_unit_savings_ratio)


def _consolidatable(c: Candidate, clock, policy_filter: tuple[str, ...]) -> bool:
    """consolidateAfter gating: policy matches and the idle window elapsed
    since the last pod event (nodeclaim.disruption Consolidatable)."""
    disruption = c.nodepool.spec.disruption
    if disruption.consolidation_policy not in policy_filter:
        return False
    after = disruption.consolidate_after_seconds
    if after is None:
        return False
    claim = c.state_node.node_claim
    anchor = claim.status.last_pod_event_time or claim.metadata.creation_timestamp
    return clock.now() - anchor >= after


class Emptiness:
    """Delete nodes with zero reschedulable pods (emptiness.go:42-121).
    Nodes hosting virtual buffer headroom are not empty
    (cluster.bufferPodCounts, buffers.go:145-150)."""

    reason = REASON_EMPTY

    def __init__(self, clock, cluster=None, store=None):
        self.clock = clock
        self.cluster = cluster
        self.store = store

    def compute(self, candidates: list[Candidate], budgets: dict[str, int]) -> Command:
        buffered = (
            self.cluster.buffer_pod_counts if self.cluster is not None else {}
        )
        if buffered is None:
            # no provisioning pass since restart: headroom placement is
            # unknown — with live buffers, deleting "empty" nodes could
            # reap warm capacity, so defer until a solve records counts
            from karpenter_tpu.controllers.capacity_buffer import resolved_replicas

            if self.store is not None and any(
                resolved_replicas(b) > 0
                for b in self.store.list(self.store.CAPACITY_BUFFERS)
            ):
                return Command(candidates=[], reason=self.reason)
            buffered = {}
        empty = [
            c
            for c in candidates
            if not c.owned_by_static
            and not c.reschedulable_pods
            and not buffered.get(c.name)
            and _consolidatable(
                c,
                self.clock,
                (CONSOLIDATION_WHEN_EMPTY, CONSOLIDATION_WHEN_EMPTY_OR_UNDERUTILIZED),
            )
        ]
        # a slice whose training job finished empties as a whole; a gang
        # with any non-empty host keeps every host (all-or-none eviction)
        chosen = _within_budget(_complete_units(empty, candidates), budgets)
        return Command(candidates=chosen, reason=self.reason)


class Drift:
    """Delete Drifted claims; replacements come from re-provisioning the
    evicted pods (drift.go:58-119)."""

    reason = REASON_DRIFTED

    def __init__(self, simulate: SimulateFn):
        self.simulate = simulate

    def compute(self, candidates: list[Candidate], budgets: dict[str, int]) -> Command:
        def claim_drifted(c: Candidate) -> bool:
            return (
                c.state_node.node_claim is not None
                and c.state_node.node_claim.conditions.is_true(COND_DRIFTED)
            )

        # a drifted slice host recycles the WHOLE slice: replacing one
        # host would break the gang's rank layout, so any drifted member
        # pulls every host of its unit into the command
        drifted_units = [
            u
            for u in atomic_units(candidates)
            if not any(c.owned_by_static for c in u) and any(claim_drifted(c) for c in u)
        ]
        chosen = _within_budget(
            [c for u in drifted_units for c in u], budgets
        )
        if not chosen:
            return Command(reason=self.reason)
        # one unit at a time, verifying pods have somewhere to go
        # (drift.go:98+); a gang unit re-provisions a full new slice
        for unit in atomic_units(chosen):
            results, unscheduled = self.simulate(unit)
            if results is None or unscheduled:
                continue
            return Command(
                candidates=list(unit),
                replacements=list(results.claims),
                reason=self.reason,
                results=results,
            )
        return Command(reason=self.reason)


class StaticDrift:
    """Replace-then-delete for drifted static-pool nodes
    (staticdrift.go:49-107): the replacement claim comes straight from the
    pool template (no pod placement — the pool holds a fixed replica
    count), created BEFORE the old node is removed so capacity never dips
    below replicas."""

    reason = REASON_DRIFTED

    def __init__(self, store, cloud):
        self.store = store
        self.cloud = cloud

    def compute(self, candidates: list["Candidate"], budgets: dict[str, int]) -> Command:
        from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import build_template

        drifted = [
            c
            for c in candidates
            if c.owned_by_static
            and c.state_node.node_claim is not None
            and c.state_node.node_claim.conditions.is_true(COND_DRIFTED)
        ]
        for c in drifted:
            pool = c.nodepool
            if budgets.get(pool.name, 0) <= 0:
                continue
            claims = [
                cl
                for cl in self.store.nodeclaims()
                if cl.nodepool_name == pool.name and not cl.metadata.deleting
            ]
            # wait out in-progress scale-down (staticdrift.go:74-77)
            if len(claims) > (pool.spec.replicas or 0):
                continue
            # node limit guards the temporary replicas+1 overlap
            # (staticdrift.go:68-88 ReserveNodeCount)
            limit = (pool.spec.limits.resources.get("nodes") if pool.spec.limits else None)
            if limit is not None and len(claims) + 1 > limit:
                continue
            from karpenter_tpu.cloudprovider.errors import instance_types_or_none

            pool_its = instance_types_or_none(self.cloud, pool)
            if pool_its is None:
                continue  # unevaluated pool: skip this candidate's pool pass
            template = build_template(pool, pool_its)
            replacement = SimClaim(
                template=template,
                requirements=template.requirements.copy(),
                used=dict(template.daemon_requests),
                instance_types=list(template.instance_types),
                pods=[],
                slot=0,
            )
            return Command(candidates=[c], replacements=[replacement], reason=self.reason)
        return Command(reason=self.reason)


class _ConsolidationBase:
    reason = REASON_UNDERUTILIZED

    def __init__(
        self,
        simulate: SimulateFn,
        clock,
        spot_to_spot_enabled: bool = False,
        simulate_batch=None,
    ):
        self.simulate = simulate
        self.clock = clock
        self.spot_to_spot_enabled = spot_to_spot_enabled
        # Batched what-if prefilter (one vmapped device dispatch for all
        # candidate sets); None falls back to sequential simulation. The
        # batch over-approximates feasibility, so every chosen scenario is
        # confirmed with the sequential simulate before acting.
        self.simulate_batch = simulate_batch

    def eligible(self, candidates: list[Candidate]) -> list[Candidate]:
        out = [
            c
            for c in candidates
            if not c.owned_by_static
            and _consolidatable(c, self.clock, (CONSOLIDATION_WHEN_EMPTY_OR_UNDERUTILIZED,))
        ]
        # all-or-none: a gang consolidates only as a complete slice
        return _complete_units(out, candidates)

    # -- computeConsolidation (consolidation.go:159-343) --------------------

    def compute_consolidation(
        self, candidates: list[Candidate], deadline: Optional[float] = None
    ) -> Command:
        results, unscheduled = self.simulate(candidates, deadline=deadline)
        if results is None or unscheduled:
            return Command(reason=self.reason)
        if len(results.claims) == 0:
            return Command(candidates=candidates, reason=self.reason, results=results)
        if len(results.claims) != 1:
            return Command(reason=self.reason)

        claim = results.claims[0]
        candidate_price = sum(c.price for c in candidates)
        all_spot = all(
            (c.state_node.node or c.state_node.node_claim).metadata.labels.get(
                l.CAPACITY_TYPE_LABEL_KEY
            )
            == l.CAPACITY_TYPE_SPOT
            for c in candidates
        )
        ct_req = claim.requirements.get(l.CAPACITY_TYPE_LABEL_KEY)
        if all_spot and ct_req.has(l.CAPACITY_TYPE_SPOT):
            return self._spot_to_spot(candidates, claim, results, candidate_price)

        if not self._filter_by_price(claim, candidate_price):
            return Command(reason=self.reason)
        # OD -> [OD, spot]: after price filtering, force spot so the launch
        # doesn't pick an on-demand offering pricier than a viable spot one
        # (consolidation.go:240-243)
        if ct_req.has(l.CAPACITY_TYPE_SPOT) and ct_req.has(l.CAPACITY_TYPE_ON_DEMAND):
            from karpenter_tpu.scheduling import Operator, Requirement

            claim.requirements.add(
                Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_SPOT)
            )
        return Command(
            candidates=candidates, replacements=[claim], reason=self.reason, results=results
        )

    def _filter_by_price(self, claim: SimClaim, candidate_price: float) -> bool:
        """RemoveInstanceTypeOptionsByPriceAndMinValues (nodeclaim.go:411):
        keep instance types with a compatible offering cheaper than the
        candidates; False if none remain."""
        claim.instance_types = [
            it
            for it in claim.instance_types
            if it.cheapest_offering_price(claim.requirements) < candidate_price
        ]
        return bool(claim.instance_types)

    def _spot_to_spot(
        self,
        candidates: list[Candidate],
        claim: SimClaim,
        results: SchedulingResult,
        candidate_price: float,
    ) -> Command:
        """consolidation.go:256-343: gated by the feature flag; requires >=15
        cheaper types and caps launch flexibility at 15 to prevent churn."""
        if not self.spot_to_spot_enabled:
            return Command(reason=self.reason)
        from karpenter_tpu.cloudprovider.instancetype import order_by_price
        from karpenter_tpu.scheduling import Operator, Requirement

        claim.requirements.add(
            Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_SPOT)
        )
        if not self._filter_by_price(claim, candidate_price):
            return Command(reason=self.reason)
        ordered = order_by_price(claim.instance_types, claim.requirements)
        if len(candidates) == 1 and len(ordered) < MIN_INSTANCE_TYPES_FOR_SPOT_TO_SPOT:
            return Command(reason=self.reason)
        claim.instance_types = ordered[:MAX_SPOT_TO_SPOT_LAUNCH_FLEXIBILITY]
        return Command(
            candidates=candidates, replacements=[claim], reason=self.reason, results=results
        )


class SingleNodeConsolidation(_ConsolidationBase):
    """Per-candidate simulation, cheapest-savings first
    (singlenodeconsolidation.go:33-146). With the batched prefilter, every
    candidate's what-if runs as one device dispatch and only batch-feasible
    candidates pay a sequential confirmation. The walk is bounded by the
    3-minute method deadline (singlenodeconsolidation.go:33,60-68):
    candidates not reached before it expires wait for the next pass."""

    def compute(self, candidates: list[Candidate], budgets: dict[str, int]) -> Command:
        deadline = self.clock.now() + SINGLE_NODE_CONSOLIDATION_TIMEOUT_SECONDS
        # the walk is over atomic units: ordinary nodes one at a time,
        # gang slices as whole claim groups (all-or-none eviction);
        # unit order comes from the active placement objective
        ordered = [
            c
            for u in _order_units(atomic_units(self.eligible(candidates)))
            for c in u
        ]
        units = atomic_units(_within_budget(ordered, budgets))
        if len(units) > 1 and self.simulate_batch is not None:
            signals = self.simulate_batch([list(u) for u in units])
            if signals is not None:
                # feasibility is a sound over-approximation (the batch is
                # fully relaxed), so ok=False candidates are truly dead.
                # n_new is a packing heuristic — first-fit is non-monotone
                # under constraint removal — so it only ORDERS the
                # sequential confirms, never drops a feasible candidate.
                feasible = [
                    (u, n_new) for u, (ok, n_new) in zip(units, signals) if ok
                ]
                units = [u for u, n in feasible if n <= 1] + [
                    u for u, n in feasible if n > 1
                ]
        for unit in units:
            if self.clock.now() >= deadline:
                from karpenter_tpu.utils.metrics import CONSOLIDATION_TIMEOUTS

                CONSOLIDATION_TIMEOUTS.inc(method="single-node")
                break
            cmd = self.compute_consolidation(list(unit), deadline)
            if not cmd.is_empty:
                return cmd
        return Command(reason=self.reason)


class MultiNodeConsolidation(_ConsolidationBase):
    """Binary search over the savings-sorted candidate prefix
    (multinodeconsolidation.go:52-191)."""

    def compute(self, candidates: list[Candidate], budgets: dict[str, int]) -> Command:
        deadline = self.clock.now() + MULTI_NODE_CONSOLIDATION_TIMEOUT_SECONDS
        # prefixes are over atomic units so a slice's hosts always enter a
        # prefix together; the batch cap counts NODES, aligned down to a
        # unit boundary; unit order comes from the active placement
        # objective (same scores provisioning optimizes)
        ordered = [
            c
            for u in _order_units(atomic_units(self.eligible(candidates)))
            for c in u
        ]
        units: list[list[Candidate]] = []
        total = 0
        for u in atomic_units(_within_budget(ordered, budgets)):
            if total + len(u) > MAX_MULTI_NODE_BATCH:
                break
            units.append(u)
            total += len(u)
        if total < 2:
            return Command(reason=self.reason)

        def flatten(n: int) -> list[Candidate]:
            return [c for u in units[:n] for c in u]

        # memoized per prefix length: the confirm walk and the binary-search
        # fallback share results, bounding total sequential simulates to
        # confirm_budget + log N with no repeats
        prefix_memo: dict[int, Command] = {}

        def compute_prefix(n: int) -> Command:
            if n not in prefix_memo:
                prefix_memo[n] = self.compute_consolidation(flatten(n), deadline)
            return prefix_memo[n]

        def timed_out() -> bool:
            # multinodeconsolidation.go:142-153: on deadline, return the
            # last valid command instead of discarding the pass's work
            if self.clock.now() >= deadline:
                from karpenter_tpu.utils.metrics import CONSOLIDATION_TIMEOUTS

                CONSOLIDATION_TIMEOUTS.inc(method="multi-node")
                return True
            return False

        if self.simulate_batch is not None:
            signals = self.simulate_batch([flatten(n) for n in range(1, len(units) + 1)])
            if signals is not None:
                # every prefix evaluated in ONE device dispatch; confirm the
                # largest batch-feasible prefixes sequentially (price rules
                # and exact preference semantics run there), bounded to the
                # sequential binary search's O(log N) simulate budget.
                # Feasibility (ok) soundly over-approximates — ok=False
                # prefixes are sequentially infeasible too. n_new<=1 is only
                # a likely-single-replacement ORDERING hint (first-fit is
                # non-monotone under relaxation), so feasible prefixes it
                # deprioritizes still get tried, and if the confirm budget
                # can't cover every feasible prefix we fall back to the
                # exact binary search rather than silently skip.
                feasible = [
                    (n, n_new)
                    for n, (ok, n_new) in zip(range(1, len(units) + 1), signals)
                    if ok
                ]
                ordered = sorted((n for n, nn in feasible if nn <= 1), reverse=True) + sorted(
                    (n for n, nn in feasible if nn > 1), reverse=True
                )
                confirm_budget = max(2, len(units).bit_length())
                for n in ordered[:confirm_budget]:
                    if timed_out():
                        return Command(reason=self.reason)
                    cmd = compute_prefix(n)
                    if not cmd.is_empty and self._replacement_improves(cmd, flatten(n)):
                        return cmd
                if len(ordered) <= confirm_budget:
                    # every batch-feasible prefix was confirmed infeasible
                    # sequentially; nothing was skipped
                    return Command(reason=self.reason)
                # untried feasible prefixes remain — run the exact search
        # binary search on the prefix length: find the largest N where
        # consolidating candidates[0..N) simulates successfully
        lo, hi = 1, len(units)
        best = Command(reason=self.reason)
        while lo <= hi:
            if timed_out():
                return best  # last valid command
            mid = (lo + hi) // 2
            cmd = compute_prefix(mid)
            if not cmd.is_empty and self._replacement_improves(cmd, flatten(mid)):
                best = cmd
                lo = mid + 1
            else:
                hi = mid - 1
        return best

    def _replacement_improves(self, cmd: Command, candidates: list[Candidate]) -> bool:
        """Reject replacing N nodes with the same instance type as one of
        them at no saving (multinodeconsolidation.go:209-246)."""
        if not cmd.replacements:
            return True
        claim = cmd.replacements[0]
        names = {it.name for it in claim.instance_types}
        if len(candidates) == 1:
            return True
        return not all(
            (c.instance_type is not None and c.instance_type.name in names and len(names) == 1)
            for c in candidates
        )
