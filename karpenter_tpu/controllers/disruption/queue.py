"""Disruption orchestration queue.

Counterpart of reference disruption/orchestration/queue.go:313-392: taint
candidates -> create replacement NodeClaims -> MarkForDeletion (strictly
after replacements, the double-launch guard, queue.go:342-349) -> await
replacement initialization -> delete candidates; roll back on failure.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.cloudprovider.errors import TransientError
from karpenter_tpu.controllers.disruption.methods import Command
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import COND_INITIALIZED
from karpenter_tpu.models.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock

REPLACEMENT_TIMEOUT_SECONDS = 10 * 60.0
# Transient API errors while advancing an in-flight command retry across
# process() passes, bounded: past the budget the command rolls back (the
# candidates return to service; the next disruption poll recomputes)
MAX_CHECK_RETRIES = 8


@dataclass
class _InFlight:
    command: Command
    replacement_names: list[str]
    started_at: float
    candidate_provider_ids: list[str] = field(default_factory=list)
    retries: int = 0
    abandoning: bool = False  # retries exhausted; only rollback remains


class OrchestrationQueue:
    def __init__(self, store: ObjectStore, cluster: Cluster, provisioner, clock: Clock):
        self.store = store
        self.cluster = cluster
        self.provisioner = provisioner
        self.clock = clock
        self.in_flight: list[_InFlight] = []

    # -- StartCommand (queue.go:313-392) ------------------------------------

    def start(self, command: Command) -> None:
        """Begin a command; a transient API error mid-start aborts it
        cleanly (partial taints/replacements undone) instead of leaving
        half a command in flight — the next disruption poll recomputes
        from live state, which is the requeue."""
        replacement_names: list[str] = []
        try:
            self._start(command, replacement_names)
        except TransientError:
            from karpenter_tpu.utils import metrics

            metrics.TRANSIENT_RETRIES.inc(controller="disruption.queue")
            metrics.VOLUNTARY_DISRUPTION_DECISIONS.inc(
                decision="aborted", reason=command.reason
            )
            self._abort_start(command, replacement_names)

    def _start(self, command: Command, replacement_names: list[str]) -> None:
        # 1. taint candidates so nothing new schedules there
        for c in command.candidates:
            node = c.state_node.node
            if node is not None and not any(
                t.match(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints
            ):
                node.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)
                self.store.update(ObjectStore.NODES, node)
        # 2. create replacement NodeClaims, nominating their pods so the
        # provisioner doesn't double-provision for them (provisioner.go
        # create_node_claims parity)
        from karpenter_tpu.utils import metrics

        for sim in command.replacements:
            claim = self.provisioner._to_node_claim(sim)
            self.store.create(ObjectStore.NODECLAIMS, claim)
            metrics.NODECLAIMS_CREATED.inc(
                reason=command.reason,
                nodepool=sim.template.nodepool_name,
                min_values_relaxed="true" if sim.min_values_relaxed else "false",
            )
            self.cluster.update_nodeclaim(claim)
            for pod in sim.pods:
                self.cluster.nominate_pod(pod.uid, claim.name)
            replacement_names.append(claim.name)
        # 3. mark for deletion AFTER replacements exist (double-launch guard)
        pids = [c.provider_id for c in command.candidates]
        self.cluster.mark_for_deletion(*pids)
        self.in_flight.append(
            _InFlight(
                command=command,
                replacement_names=replacement_names,
                started_at=self.clock.now(),
                candidate_provider_ids=pids,
            )
        )

    def _abort_start(self, command: Command, replacement_names: list[str]) -> None:
        """Best-effort unwind of a partially-started command: drop any
        replacements already created and untaint the candidates. Each
        step absorbs further transient errors — an orphan that slips
        through is reclaimed by liveness/GC, and pod nominations expire
        on their own TTL."""
        for name in replacement_names:
            try:
                claim = self.store.get(ObjectStore.NODECLAIMS, name)
                if claim is not None:
                    claim.metadata.finalizers = []
                    self.store.delete(ObjectStore.NODECLAIMS, name)
            except TransientError:
                pass
        for c in command.candidates:
            node = c.state_node.node
            if node is None:
                continue
            live = self.store.get(ObjectStore.NODES, node.name)
            if live is None:
                continue
            before = len(live.spec.taints)
            live.spec.taints = [
                t for t in live.spec.taints if not t.match(DISRUPTED_NO_SCHEDULE_TAINT)
            ]
            if len(live.spec.taints) != before:
                try:
                    self.store.update(ObjectStore.NODES, live)
                except TransientError:
                    live.spec.taints.append(DISRUPTED_NO_SCHEDULE_TAINT)

    # -- waitOrTerminate (queue.go:186-257) -----------------------------------

    def process(self) -> int:
        """Advance in-flight commands; returns completed count."""
        if not self.in_flight:
            return 0
        from karpenter_tpu.tracing.tracer import TRACER

        with TRACER.span("disruption.queue", in_flight=len(self.in_flight)):
            return self._process()

    def _process(self) -> int:
        done = 0
        remaining = []
        for item in self.in_flight:
            try:
                if item.abandoning:
                    # retry budget already spent: only the rollback
                    # remains, retried until it lands
                    self._rollback(item)
                    continue
                status = self._check(item)
            except TransientError:
                from karpenter_tpu.utils import metrics

                metrics.TRANSIENT_RETRIES.inc(controller="disruption.queue")
                item.retries += 1
                if item.retries > MAX_CHECK_RETRIES:
                    item.abandoning = True
                remaining.append(item)  # requeue: next process() retries
                continue
            if status == "wait":
                remaining.append(item)
            elif status == "done":
                done += 1
            # "rolled-back" items are dropped
        self.in_flight = remaining
        return done

    def _check(self, item: _InFlight) -> str:
        claims = [self.store.get(ObjectStore.NODECLAIMS, n) for n in item.replacement_names]
        if any(c is None for c in claims):
            self._rollback(item)  # a replacement failed to launch
            return "rolled-back"
        if not all(c.conditions.is_true(COND_INITIALIZED) for c in claims):
            if self.clock.now() - item.started_at > REPLACEMENT_TIMEOUT_SECONDS:
                self._rollback(item)
                return "rolled-back"
            return "wait"
        # replacements ready: delete the candidates (graceful; the
        # termination flow drains and the lifecycle finalizer fires).
        # Disruption metrics count HERE — an aborted command must not be
        # recorded as a disruption (queue.go:247-248)
        from karpenter_tpu.utils import metrics

        for c in item.command.candidates:
            claim = c.state_node.node_claim
            if claim is not None and self.store.get(ObjectStore.NODECLAIMS, claim.name) is not None:
                metrics.NODECLAIMS_DISRUPTED.inc(
                    reason=item.command.reason, nodepool=c.nodepool.name
                )
                metrics.PODS_DISRUPTION_INITIATED.inc(
                    float(len(c.reschedulable_pods)), nodepool=c.nodepool.name
                )
                claim.metadata.annotations[l.NODECLAIM_TERMINATION_TIMESTAMP_ANNOTATION_KEY] = str(
                    self.clock.now()
                )
                claim.metadata.annotations["karpenter.sh/termination-reason"] = (
                    item.command.reason.lower()
                )
                self.store.delete(ObjectStore.NODECLAIMS, claim.name)
        return "done"

    def _rollback(self, item: _InFlight) -> None:
        """UnmarkForDeletion + untaint so the nodes return to service
        (queue.go:416-427)."""
        self.cluster.unmark_for_deletion(*item.candidate_provider_ids)
        for c in item.command.candidates:
            node = c.state_node.node
            if node is None:
                continue
            live = self.store.get(ObjectStore.NODES, node.name)
            if live is None:
                continue
            before = len(live.spec.taints)
            live.spec.taints = [
                t for t in live.spec.taints if not t.match(DISRUPTED_NO_SCHEDULE_TAINT)
            ]
            if len(live.spec.taints) != before:
                self.store.update(ObjectStore.NODES, live)
