"""Disruption candidates and budgets.

Counterpart of reference disruption/types.go:75-160 (Candidate construction
+ disruptability validation) and helpers.go:262-313 (budget mapping).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.cloudprovider.instancetype import InstanceType
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import COND_INITIALIZED, NodeClaim
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.state.cluster import Cluster, StateNode
from karpenter_tpu.utils.clock import Clock

POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"


@dataclass
class Candidate:
    """A node eligible for disruption (types.go:75-92)."""

    state_node: StateNode
    nodepool: NodePool
    instance_type: Optional[InstanceType]
    price: float
    reschedulable_pods: list[Pod] = field(default_factory=list)
    disruption_cost: float = 1.0

    @property
    def name(self) -> str:
        return self.state_node.name

    @property
    def provider_id(self) -> str:
        return self.state_node.provider_id

    @property
    def savings_ratio(self) -> float:
        """Sort key: cheaper-to-disrupt-per-dollar first (types.go:145)."""
        return self.price / self.disruption_cost if self.disruption_cost else self.price

    @property
    def owned_by_static(self) -> bool:
        """Static pools are disrupted only by StaticDrift's
        replace-then-delete (types.go:147, staticdrift.go:51)."""
        return self.nodepool.is_static


def _pod_eviction_cost(pod: Pod) -> float:
    cost = 1.0
    raw = pod.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost += float(raw) / 1000.0
        except ValueError:
            pass
    return max(cost, 0.0)


def is_disruptable(sn: StateNode, clock: Clock) -> Optional[str]:
    """None if the node may be disrupted, else the blocking reason
    (types.go:160 construction validation)."""
    if sn.node is None or sn.node_claim is None:
        return "not managed"
    if sn.marked_for_deletion or sn.node.metadata.deleting:
        return "already deleting"
    if not sn.node_claim.conditions.is_true(COND_INITIALIZED):
        return "not initialized"
    if sn.is_nominated(clock.now()):
        return "nominated for pending pods"
    for pod in sn.pods.values():
        if pod.metadata.annotations.get(l.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true":
            return f"pod {pod.name} has do-not-disrupt"
    return None


def build_candidates(
    cluster: Cluster,
    pools_by_name: dict[str, NodePool],
    instance_types_by_name: dict[str, InstanceType],
    clock: Clock,
    pdb_blocked: frozenset[str] = frozenset(),
) -> list[Candidate]:
    """All disruptable nodes as candidates, deterministic name order.

    pdb_blocked: uids of pods whose eviction would violate a
    PodDisruptionBudget — their nodes are excluded (types.go:160).
    """
    out = []
    nominated_targets = cluster.nomination_targets()
    for sn in sorted(cluster.nodes(), key=lambda s: s.name):
        if is_disruptable(sn, clock) is not None:
            continue
        if pdb_blocked and any(uid in pdb_blocked for uid in sn.pods):
            continue
        # capacity that pending pods are nominated onto (a fresh replacement
        # node, or one awaiting binds) must not be disrupted from under them
        if sn.name in nominated_targets or (
            sn.node_claim is not None and sn.node_claim.name in nominated_targets
        ):
            continue
        pool = pools_by_name.get(sn.nodepool_name or "")
        if pool is None:
            continue
        it_name = (sn.node or sn.node_claim).metadata.labels.get(l.LABEL_INSTANCE_TYPE, "")
        it = instance_types_by_name.get(it_name)
        zone = (sn.node or sn.node_claim).metadata.labels.get(l.LABEL_TOPOLOGY_ZONE, "")
        ct = (sn.node or sn.node_claim).metadata.labels.get(l.CAPACITY_TYPE_LABEL_KEY, "")
        price = it.offering_price(zone, ct) if it else None
        if price is None:
            price = 0.0
        reschedulable = [p for p in sn.pods.values() if not p.is_terminal()]
        cost = 1.0 + sum(_pod_eviction_cost(p) for p in reschedulable)
        out.append(
            Candidate(
                state_node=sn,
                nodepool=pool,
                instance_type=it,
                price=price,
                reschedulable_pods=reschedulable,
                disruption_cost=cost,
            )
        )
    return out


def build_disruption_budgets(
    pools_by_name: dict[str, NodePool],
    cluster: Cluster,
    reason: str,
    clock: Clock,
) -> dict[str, int]:
    """pool -> allowed simultaneous disruptions for the reason, net of nodes
    already disrupting (helpers.go:262-313)."""
    out = {}
    now = clock.now()
    for name, pool in pools_by_name.items():
        total = 0
        disrupting = 0
        for sn in cluster.nodes():
            if sn.nodepool_name != name:
                continue
            total += 1
            if sn.marked_for_deletion or sn.is_disrupted():
                disrupting += 1
        allowed = pool.allowed_disruptions(reason, total, now)
        out[name] = max(allowed - disrupting, 0)
    return out
