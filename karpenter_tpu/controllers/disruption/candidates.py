"""Disruption candidates and budgets.

Counterpart of reference disruption/types.go:75-160 (Candidate construction
+ disruptability validation) and helpers.go:262-313 (budget mapping).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.cloudprovider.instancetype import InstanceType
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import COND_INITIALIZED, NodeClaim
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.state.cluster import Cluster, StateNode
from karpenter_tpu.utils.clock import Clock

POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"


def gang_key_of_node(sn: StateNode) -> Optional[str]:
    """The gang key stamped on a slice host's NodeClaim at launch
    (gang.GANG_CLAIM_ANNOTATION), or None for ordinary nodes."""
    from karpenter_tpu.gang import GANG_CLAIM_ANNOTATION

    for obj in (sn.node_claim, sn.node):
        if obj is not None:
            key = obj.metadata.annotations.get(GANG_CLAIM_ANNOTATION)
            if key:
                return key
    return None


@dataclass
class Candidate:
    """A node eligible for disruption (types.go:75-92)."""

    state_node: StateNode
    nodepool: NodePool
    instance_type: Optional[InstanceType]
    price: float
    # False when the catalog had no offering price for the node's
    # (zone, capacity-type): price is 0.0 for the legacy ratio math, but
    # cost-ranked objective ordering EXCLUDES the candidate — a missing
    # price must never read as "cheapest" (ktpu_pricing_missing_total)
    price_known: bool = True
    reschedulable_pods: list[Pod] = field(default_factory=list)
    disruption_cost: float = 1.0
    # gang key when this node is one host of a multi-host slice: the
    # slice's claim group is disrupted atomically (all hosts or none)
    gang_key: Optional[str] = None

    @property
    def name(self) -> str:
        return self.state_node.name

    @property
    def provider_id(self) -> str:
        return self.state_node.provider_id

    @property
    def savings_ratio(self) -> float:
        """Sort key: cheaper-to-disrupt-per-dollar first (types.go:145)."""
        return self.price / self.disruption_cost if self.disruption_cost else self.price

    @property
    def owned_by_static(self) -> bool:
        """Static pools are disrupted only by StaticDrift's
        replace-then-delete (types.go:147, staticdrift.go:51)."""
        return self.nodepool.is_static


def _pod_eviction_cost(pod: Pod) -> float:
    cost = 1.0
    raw = pod.metadata.annotations.get(POD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost += float(raw) / 1000.0
        except ValueError:
            pass
    return max(cost, 0.0)


def is_disruptable(sn: StateNode, clock: Clock) -> Optional[str]:
    """None if the node may be disrupted, else the blocking reason
    (types.go:160 construction validation)."""
    if sn.node is None or sn.node_claim is None:
        return "not managed"
    if sn.marked_for_deletion or sn.node.metadata.deleting:
        return "already deleting"
    if not sn.node_claim.conditions.is_true(COND_INITIALIZED):
        return "not initialized"
    if sn.is_nominated(clock.now()):
        return "nominated for pending pods"
    for pod in sn.pods.values():
        if pod.metadata.annotations.get(l.DO_NOT_DISRUPT_ANNOTATION_KEY) == "true":
            return f"pod {pod.name} has do-not-disrupt"
    return None


def build_candidates(
    cluster: Cluster,
    pools_by_name: dict[str, NodePool],
    instance_types_by_name: dict[str, InstanceType],
    clock: Clock,
    pdb_blocked: frozenset[str] = frozenset(),
) -> list[Candidate]:
    """All disruptable nodes as candidates, deterministic name order.

    pdb_blocked: uids of pods whose eviction would violate a
    PodDisruptionBudget — their nodes are excluded (types.go:160).
    """
    out = []
    nominated_targets = cluster.nomination_targets()
    for sn in sorted(cluster.nodes(), key=lambda s: s.name):
        if is_disruptable(sn, clock) is not None:
            continue
        if pdb_blocked and any(uid in pdb_blocked for uid in sn.pods):
            continue
        # capacity that pending pods are nominated onto (a fresh replacement
        # node, or one awaiting binds) must not be disrupted from under them
        if sn.name in nominated_targets or (
            sn.node_claim is not None and sn.node_claim.name in nominated_targets
        ):
            continue
        pool = pools_by_name.get(sn.nodepool_name or "")
        if pool is None:
            continue
        it_name = (sn.node or sn.node_claim).metadata.labels.get(l.LABEL_INSTANCE_TYPE, "")
        it = instance_types_by_name.get(it_name)
        zone = (sn.node or sn.node_claim).metadata.labels.get(l.LABEL_TOPOLOGY_ZONE, "")
        ct = (sn.node or sn.node_claim).metadata.labels.get(l.CAPACITY_TYPE_LABEL_KEY, "")
        price = it.offering_price(zone, ct) if it else None
        price_known = price is not None
        if price is None:
            # keep the legacy 0.0 for the savings-ratio math, but COUNT
            # the gap and mark the candidate so cost-ranked ordering can
            # exclude it (a silent 0.0 made missing prices the cheapest)
            from karpenter_tpu.utils.metrics import PRICING_MISSING

            PRICING_MISSING.inc()
            price = 0.0
        reschedulable = [p for p in sn.pods.values() if not p.is_terminal()]
        cost = 1.0 + sum(_pod_eviction_cost(p) for p in reschedulable)
        out.append(
            Candidate(
                state_node=sn,
                nodepool=pool,
                instance_type=it,
                price=price,
                price_known=price_known,
                reschedulable_pods=reschedulable,
                disruption_cost=cost,
                gang_key=gang_key_of_node(sn),
            )
        )
    # a multi-host slice is disrupted atomically: a gang enters the
    # candidate set only when EVERY live host of the slice is itself a
    # candidate — one blocked host (nominated, PDB, do-not-disrupt,
    # deleting) withdraws the whole gang
    return drop_partial_gangs(out, cluster)


def drop_partial_gangs(
    candidates: list[Candidate], cluster: Cluster
) -> list[Candidate]:
    """Remove gang candidates whose slice is only partially represented:
    disruption never evicts a strict subset of a gang's claims, so unless
    every live host of the gang survived candidate filtering, none do."""
    pops: dict[str, int] = {}
    for sn in cluster.nodes():
        key = gang_key_of_node(sn)
        if key:
            pops[key] = pops.get(key, 0) + 1
    have: dict[str, int] = {}
    for c in candidates:
        if c.gang_key:
            have[c.gang_key] = have.get(c.gang_key, 0) + 1
    return [
        c
        for c in candidates
        if not c.gang_key or have[c.gang_key] >= pops.get(c.gang_key, 0)
    ]


def atomic_units(candidates: list[Candidate]) -> list[list[Candidate]]:
    """Group candidates into atomic disruption units, order-preserving:
    one unit per ordinary node, one unit per gang (every host of the
    slice, grouped at the gang's first appearance). Disruption methods
    select whole units, so a command can never carry a strict subset of a
    slice's hosts."""
    units: list[list[Candidate]] = []
    gang_unit: dict[str, list[Candidate]] = {}
    for c in candidates:
        if c.gang_key is None:
            units.append([c])
            continue
        u = gang_unit.get(c.gang_key)
        if u is None:
            u = gang_unit[c.gang_key] = [c]
            units.append(u)
        else:
            u.append(c)
    return units


def partial_gang_violation(
    candidates: list[Candidate], cluster: Cluster
) -> Optional[str]:
    """The no-partial-eviction tripwire: the gang key of any live slice a
    command would evict a strict subset of, else None. Impossible by
    construction (build_candidates + atomic unit selection), checked
    anyway before every command executes."""
    chosen: dict[str, int] = {}
    for c in candidates:
        if c.gang_key:
            chosen[c.gang_key] = chosen.get(c.gang_key, 0) + 1
    if not chosen:
        return None
    pops: dict[str, int] = {}
    for sn in cluster.nodes():
        key = gang_key_of_node(sn)
        if key in chosen:
            pops[key] = pops.get(key, 0) + 1
    for key, n in chosen.items():
        if n < pops.get(key, 0):
            return key
    return None


def build_disruption_budgets(
    pools_by_name: dict[str, NodePool],
    cluster: Cluster,
    reason: str,
    clock: Clock,
) -> dict[str, int]:
    """pool -> allowed simultaneous disruptions for the reason, net of nodes
    already disrupting (helpers.go:262-313)."""
    out = {}
    now = clock.now()
    for name, pool in pools_by_name.items():
        total = 0
        disrupting = 0
        for sn in cluster.nodes():
            if sn.nodepool_name != name:
                continue
            total += 1
            if sn.marked_for_deletion or sn.is_disrupted():
                disrupting += 1
        allowed = pool.allowed_disruptions(reason, total, now)
        out[name] = max(allowed - disrupting, 0)
    return out
