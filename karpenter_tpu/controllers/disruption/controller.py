"""The disruption controller: the 10s polling loop.

Counterpart of reference disruption/controller.go:101-196: state-sync gate,
stale-taint cleanup, then the method cascade (first success wins) with a
validation delay before execution (consolidation.go:45, validation.go).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from karpenter_tpu.controllers.disruption.candidates import (
    Candidate,
    build_candidates,
    build_disruption_budgets,
)
from karpenter_tpu.controllers.disruption.methods import (
    StaticDrift,
    Command,
    Drift,
    Emptiness,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_tpu.controllers.disruption.queue import OrchestrationQueue
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import REASON_EMPTY
from karpenter_tpu.models.taints import DISRUPTED_NO_SCHEDULE_TAINT
from karpenter_tpu.state.store import ObjectStore

POLL_PERIOD_SECONDS = 10.0  # controller.go:71
VALIDATION_DELAY_SECONDS = 15.0  # consolidation.go:45


@dataclass
class _PendingValidation:
    command: Command
    ready_at: float


class DisruptionController:
    def __init__(self, store: ObjectStore, cluster, provisioner, cloud, clock,
                 spot_to_spot_enabled: bool = False, cost_ledger=None):
        self.store = store
        self.cluster = cluster
        self.provisioner = provisioner
        self.cloud = cloud
        self.clock = clock
        self.cost_ledger = cost_ledger
        self.queue = OrchestrationQueue(store, cluster, provisioner, clock)
        self._pending: Optional[_PendingValidation] = None
        self.methods = [
            Emptiness(clock, cluster, store),
            StaticDrift(store, cloud),
            Drift(self._simulate),
            MultiNodeConsolidation(
                self._simulate, clock, spot_to_spot_enabled, simulate_batch=self._simulate_batch
            ),
            SingleNodeConsolidation(
                self._simulate, clock, spot_to_spot_enabled, simulate_batch=self._simulate_batch
            ),
        ]

    # -- simulation hook ------------------------------------------------------

    def _simulate(self, candidates: list[Candidate], deadline=None):
        """SimulateScheduling (helpers.go:53-154): schedule pending pods +
        candidates' pods against the cluster minus the candidates. Returns
        (results, unscheduled candidate-pod uids). deadline comes from the
        calling method's timeout (1m multi-node / 3m single-node)."""
        from karpenter_tpu.tracing.tracer import TRACER

        excluded = {c.name for c in candidates}
        extra = [p for c in candidates for p in c.reschedulable_pods]
        with TRACER.span(
            "disruption.simulate", candidates=len(candidates), displaced=len(extra)
        ):
            result = self.provisioner.simulate(excluded, extra, deadline=deadline)
        if result is None:
            return None, set()
        extra_uids = {p.uid for p in extra}
        unscheduled = {p.uid for p, _ in result.unschedulable} & extra_uids
        return result, unscheduled

    def _simulate_batch(self, scenarios: list[list[Candidate]]):
        """Batched what-if prefilter: one device dispatch for all candidate
        sets (see Provisioner.simulate_batch); None when unsupported."""
        from karpenter_tpu.tracing.tracer import TRACER

        batch = getattr(self.provisioner, "simulate_batch", None)
        if batch is None:
            return None
        with TRACER.span("disruption.whatif_batch", scenarios=len(scenarios)):
            return batch(scenarios)

    # -- the loop (controller.go:128-196) --------------------------------------

    def reconcile(self) -> Optional[Command]:
        from karpenter_tpu.tracing.tracer import TRACER

        if not self.cluster.synced():
            return None
        with TRACER.span("disruption.reconcile"):
            return self._reconcile()

    def _reconcile(self) -> Optional[Command]:
        from karpenter_tpu.tracing.tracer import TRACER
        from karpenter_tpu.utils import metrics

        self._cleanup_stale_taints()
        self.queue.process()

        # a command awaiting validation takes precedence
        if self._pending is not None:
            if self.clock.now() < self._pending.ready_at:
                return None
            command = self._pending.command
            self._pending = None
            with TRACER.span(
                "disruption.validate", nodes=len(command.candidates)
            ):
                valid = self._validate(command)
            if valid:
                from karpenter_tpu.utils.logging import get_logger

                get_logger().with_values(controller="disruption").info(
                    "disrupting nodes",
                    reason=command.reason,
                    nodes=[c.name for c in command.candidates],
                    replacements=len(command.replacements),
                )
                metrics.VOLUNTARY_DISRUPTION_DECISIONS.inc(
                    decision="disrupt", reason=command.reason
                )
                self.queue.start(command)
                return command
            metrics.VOLUNTARY_DISRUPTION_DECISIONS.inc(
                decision="invalidated", reason=command.reason
            )
            return None

        from karpenter_tpu.cloudprovider.errors import instance_types_or_none

        pools = {p.name: p for p in self.store.nodepools()}
        its = {
            it.name: it
            for p in pools.values()
            for it in instance_types_or_none(self.cloud, p) or ()
        }
        from karpenter_tpu.models.pdb import blocked_pod_uids

        blocked = frozenset(
            blocked_pod_uids(self.store.list(ObjectStore.PDBS), self.store.pods())
        )
        with TRACER.span("disruption.candidates") as csp:
            candidates = build_candidates(self.cluster, pools, its, self.clock, blocked)
            csp.set(candidates=len(candidates))
        if not candidates:
            return None

        for method in self.methods:
            budgets = build_disruption_budgets(pools, self.cluster, method.reason, self.clock)
            method_name = type(method).__name__
            metrics.DISRUPTION_ELIGIBLE_NODES.set(float(len(candidates)), method=method_name)
            metrics.VOLUNTARY_DISRUPTION_ELIGIBLE.set(
                float(len(candidates)), reason=method.reason
            )
            with TRACER.span(f"disruption.method.{method_name}"):
                with metrics.DISRUPTION_EVAL_DURATION.time(method=method_name):
                    command = method.compute(candidates, budgets)
            if command.is_empty:
                continue
            # Balanced scoring applies to consolidation only — Drift and
            # Emptiness are never scored (evaluator invoked only from
            # singlenodeconsolidation.go:102 / multinodeconsolidation.go:168)
            if isinstance(
                method, (MultiNodeConsolidation, SingleNodeConsolidation)
            ) and not self._balanced_approves(command, candidates):
                metrics.VOLUNTARY_DISRUPTION_DECISIONS.inc(
                    decision="balanced-rejected", reason=command.reason
                )
                continue
            # every method — including Emptiness — waits out the validation
            # delay (emptiness.go:101 validator.Validate): a pod may bind to
            # an "empty" node between candidate computation and execution
            self._pending = _PendingValidation(
                command=command, ready_at=self.clock.now() + VALIDATION_DELAY_SECONDS
            )
            return None
        return None

    def _balanced_approves(self, command: Command, all_candidates: list[Candidate]) -> bool:
        """ConsolidationPolicy: Balanced (balanced.go:47-130): every
        Balanced pool touched by the command must approve — a move passes
        iff (savings / poolCost) / (disruption / poolDisruptionCost)
        >= 1/k with k=2 (nodepool.go:171). Pools with other policies
        always approve."""
        from karpenter_tpu.models.nodepool import BALANCED_K, CONSOLIDATION_BALANCED

        touched = {c.nodepool.name: c.nodepool for c in command.candidates}
        balanced = {
            n: p
            for n, p in touched.items()
            if p.spec.disruption.consolidation_policy == CONSOLIDATION_BALANCED
        }
        if not balanced:
            return True
        replacement_price = sum(
            sim.cheapest_launch()[1] for sim in command.replacements
        )
        total_cmd_price = sum(c.price for c in command.candidates)
        total_savings = total_cmd_price - replacement_price
        for name in balanced:
            pool_cmd = [c for c in command.candidates if c.nodepool.name == name]
            pool_price = sum(c.price for c in pool_cmd)
            # attribute net savings proportionally across pools
            # (balanced.go:149-156) — charging each pool the full
            # replacement cost would double-count it
            savings = (
                total_savings * (pool_price / total_cmd_price) if total_cmd_price > 0 else 0.0
            )
            disruption = sum(c.disruption_cost for c in pool_cmd)
            pool_cost = self.cost_ledger.pool_cost(name) if self.cost_ledger is not None else 0.0
            if pool_cost <= 0:
                # ledger empty (restart / unknown prices): fall back to the
                # candidate price sum (balanced.go:94-97)
                pool_cost = sum(c.price for c in all_candidates if c.nodepool.name == name)
            pool_disruption = self._pool_disruption_total(name)
            if pool_cost <= 0 or pool_disruption <= 0 or savings <= 0:
                return False
            ratio = (savings / pool_cost) / (disruption / pool_disruption)
            if ratio < 1.0 / BALANCED_K:
                return False
        return True

    def _pool_disruption_total(self, pool_name: str) -> float:
        """Disruption-cost total over ALL the pool's nodes — non-candidates
        included (balanced.go computeNodePoolTotals)."""
        from karpenter_tpu.controllers.disruption.candidates import _pod_eviction_cost

        total = 0.0
        for sn in self.cluster.nodes():
            if sn.nodepool_name != pool_name:
                continue
            total += 1.0 + sum(
                _pod_eviction_cost(p) for p in sn.pods.values() if not p.is_terminal()
            )
        return total

    def _validate(self, command: Command) -> bool:
        """Re-verify after the delay: candidates still disruptable, not
        newly PDB-blocked, and the pods still have somewhere to go
        (validation.go:258)."""
        from karpenter_tpu.controllers.disruption.candidates import (
            is_disruptable,
            partial_gang_violation,
        )
        from karpenter_tpu.models.pdb import blocked_pod_uids

        # the no-partial-eviction tripwire: impossible by construction
        # (atomic unit selection), but a command that would evict a strict
        # subset of a live slice's hosts is refused outright
        viol = partial_gang_violation(command.candidates, self.cluster)
        if viol is not None:
            from karpenter_tpu.utils.logging import get_logger

            get_logger().with_values(controller="disruption").error(
                "command would evict a strict subset of a gang's claims",
                gang=viol,
                reason=command.reason,
            )
            return False
        blocked = blocked_pod_uids(self.store.list(ObjectStore.PDBS), self.store.pods())
        for c in command.candidates:
            sn = self.cluster.node_by_name(c.name)
            if sn is None:
                return False  # node vanished during the window
            if is_disruptable(sn, self.clock) is not None:
                return False
            if any(uid in blocked for uid in sn.pods):
                return False
            fresh = [p for p in sn.pods.values() if not p.is_terminal()]
            if command.reason == REASON_EMPTY and fresh:
                # emptiness: a pod bound during the delay (emptiness.go:101)
                return False
            # re-simulate against the CURRENT pod set — a pod that bound
            # during the delay must be rescheduled too, not evicted blind
            # (validation.go re-builds candidates from live state)
            c.state_node = sn
            c.reschedulable_pods = fresh
        if all(c.owned_by_static for c in command.candidates):
            # static replace-then-delete: the replacement is a template
            # clone, not a pod placement — no re-simulation applies
            # (queue.go:286 static special case)
            return True
        if command.replacements or any(c.reschedulable_pods for c in command.candidates):
            results, unscheduled = self._simulate(command.candidates)
            if results is None or unscheduled:
                return False
            # the world may have changed during the delay: the command is
            # only valid if the displaced pods still fit without MORE new
            # capacity than the command already launches (validation.go)
            if len(results.claims) > len(command.replacements):
                return False
        return True

    def _cleanup_stale_taints(self) -> None:
        """Remove disrupted taints from nodes with no in-flight command —
        crash recovery (controller.go:147-164)."""
        active = {
            c.provider_id
            for item in self.queue.in_flight
            for c in item.command.candidates
        }
        if self._pending is not None:
            active |= {c.provider_id for c in self._pending.command.candidates}
        for node in self.store.nodes():
            if not any(t.match(DISRUPTED_NO_SCHEDULE_TAINT) for t in node.spec.taints):
                continue
            sn = self.cluster.node_by_provider_id(node.spec.provider_id)
            if sn is not None and (sn.marked_for_deletion or node.spec.provider_id in active):
                continue
            node.spec.taints = [
                t for t in node.spec.taints if not t.match(DISRUPTED_NO_SCHEDULE_TAINT)
            ]
            self.store.update(ObjectStore.NODES, node)
