"""Disruption: turning an overprovisioned cluster into a cheaper one.

Counterpart of reference pkg/controllers/disruption. A polling controller
evaluates methods in priority order — first success wins
(controller.go:101-115):

  Emptiness -> Drift -> MultiNodeConsolidation -> SingleNodeConsolidation

Consolidation what-ifs run full scheduling simulations against the cluster
minus the candidates (helpers.go:53-154); on TPU these reuse the same
solver the provisioner runs.
"""

from karpenter_tpu.controllers.disruption.candidates import (  # noqa: F401
    Candidate,
    build_candidates,
    build_disruption_budgets,
)
from karpenter_tpu.controllers.disruption.controller import DisruptionController  # noqa: F401
from karpenter_tpu.controllers.disruption.methods import (  # noqa: F401
    Command,
    Drift,
    Emptiness,
    MultiNodeConsolidation,
    SingleNodeConsolidation,
)
from karpenter_tpu.controllers.disruption.queue import OrchestrationQueue  # noqa: F401
