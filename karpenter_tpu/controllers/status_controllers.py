"""Small status reconcilers: claim consistency + NodePool status.

Counterparts of reference pkg/controllers/nodeclaim/consistency
(ConsistentStateFound on claim/node capacity mismatch) and
pkg/controllers/nodepool/{counter,readiness,hash} (usage into
status.resources, Ready condition, drift-hash annotation).
"""

from __future__ import annotations

from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import COND_CONSISTENT_STATE_FOUND, COND_REGISTERED
from karpenter_tpu.models.nodepool import (
    CONDITION_NODECLASS_READY,
    CONDITION_READY,
    CONDITION_VALIDATION_SUCCEEDED,
    NODEPOOL_HASH_VERSION,
)
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock

CAPACITY_TOLERANCE = 0.10  # relative mismatch that flags inconsistency


def node_class_label_key(ref: dict) -> str:
    """group + lowercase kind, the label hydration backfills
    (labels.go:173-175 NodeClassLabelKey)."""
    return f"{ref.get('group', '')}/{str(ref.get('kind', '')).lower()}"


class HydrationController:
    """Upgrade backfill (nodeclaim/hydration + node/hydration): stamps the
    nodeclass label derived from spec.nodeClassRef onto pre-existing
    NodeClaims and their Nodes so newer-version selectors keep matching."""

    def __init__(self, store: ObjectStore):
        self.store = store

    def reconcile(self) -> int:
        hydrated = 0
        for claim in self.store.nodeclaims():
            ref = claim.spec.node_class_ref
            if not ref or not ref.get("kind"):
                continue
            key = node_class_label_key(ref)
            value = ref.get("name", "")
            if claim.metadata.labels.get(key) != value:
                claim.metadata.labels[key] = value
                self.store.update(ObjectStore.NODECLAIMS, claim)
                hydrated += 1
            node = (
                self.store.node_by_provider_id(claim.status.provider_id)
                if claim.status.provider_id
                else None
            )
            if node is not None and node.metadata.labels.get(key) != value:
                node.metadata.labels[key] = value
                self.store.update(ObjectStore.NODES, node)
                hydrated += 1
        return hydrated


class ConsistencyController:
    """Detects claim<->node capacity drift (consistency/controller.go)."""

    def __init__(self, store: ObjectStore, clock: Clock):
        self.store = store
        self.clock = clock

    def reconcile(self) -> int:
        flagged = 0
        for claim in self.store.nodeclaims():
            if not claim.conditions.is_true(COND_REGISTERED):
                continue
            node = self.store.node_by_provider_id(claim.status.provider_id)
            if node is None:
                continue
            consistent = True
            for resource, expected in claim.status.capacity.items():
                actual = node.status.capacity.get(resource, 0.0)
                if expected <= 0:
                    continue
                if abs(actual - expected) / expected > CAPACITY_TOLERANCE:
                    consistent = False
                    break
            if consistent:
                claim.conditions.set_true(
                    COND_CONSISTENT_STATE_FOUND, "Consistent", now=self.clock.now()
                )
            else:
                claim.conditions.set_false(
                    COND_CONSISTENT_STATE_FOUND, "CapacityMismatch", now=self.clock.now()
                )
                flagged += 1
        return flagged


class NodePoolValidationController:
    """Runtime validation the CRD schema can't express
    (pkg/controllers/nodepool/validation/controller.go:61-84): flips
    ValidationSucceeded per pool; a False gates the pool out of
    provisioning via the Ready root condition."""

    def __init__(self, store: ObjectStore, clock: Clock):
        self.store = store
        self.clock = clock

    def reconcile(self) -> int:
        from karpenter_tpu.models.validation import validate_nodepool

        flagged = 0
        for pool in self.store.nodepools():
            errs = validate_nodepool(pool)
            if errs:
                pool.conditions.set_false(
                    CONDITION_VALIDATION_SUCCEEDED,
                    "NodePoolValidationFailed",
                    "; ".join(errs[:5]),
                    now=self.clock.now(),
                )
                flagged += 1
            else:
                pool.conditions.set_true(
                    CONDITION_VALIDATION_SUCCEEDED, now=self.clock.now()
                )
        return flagged


class NodePoolStatusController:
    """Usage into status.resources + Ready condition + hash annotation
    (nodepool/{counter,readiness,hash})."""

    def __init__(self, store: ObjectStore, cluster: Cluster, clock: Clock):
        self.store = store
        self.cluster = cluster
        self.clock = clock

    def reconcile(self) -> None:
        for pool in self.store.nodepools():
            usage = self.cluster.nodepool_usage(pool.name)
            pool.status.resources = usage
            pool.status.node_count = int(usage.get("nodes", 0))
            # the harness has no NodeClass objects: class readiness is
            # vacuously true; Ready is the root condition over class
            # readiness AND runtime validation (operatorpkg status roots)
            pool.conditions.set_true(CONDITION_NODECLASS_READY, "NoNodeClass", now=self.clock.now())
            if pool.conditions.is_false(CONDITION_VALIDATION_SUCCEEDED):
                pool.conditions.set_false(
                    CONDITION_READY, "NodePoolValidationFailed", now=self.clock.now()
                )
            else:
                pool.conditions.set_true(CONDITION_READY, "Ready", now=self.clock.now())
            pool.metadata.annotations[l.NODEPOOL_HASH_ANNOTATION_KEY] = pool.static_hash()
            pool.metadata.annotations[l.NODEPOOL_HASH_VERSION_ANNOTATION_KEY] = (
                NODEPOOL_HASH_VERSION
            )
