"""Static-capacity NodePools: replica-count reconcilers.

Counterpart of reference pkg/controllers/static/{provisioning,
deprovisioning} (provisioning/controller.go:75-124,
deprovisioning/controller.go:84-270): pools with spec.replicas hold
exactly that many nodes — scale up creates claims from the pool template,
scale down removes empty-then-youngest claims first.
"""

from __future__ import annotations

from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import build_template
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import NodeClaim, NodeClaimSpec
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.objects import ObjectMeta, new_uid
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock


class StaticCapacityController:
    def __init__(self, store: ObjectStore, cluster: Cluster, cloud: CloudProvider, clock: Clock):
        self.store = store
        self.cluster = cluster
        self.cloud = cloud
        self.clock = clock

    def reconcile(self) -> int:
        """Returns net claims created (negative = removed)."""
        delta = 0
        for pool in self.store.nodepools():
            if not pool.is_static:
                continue
            claims = [
                c
                for c in self.store.nodeclaims()
                if c.nodepool_name == pool.name
                and not c.metadata.deleting
                and not self._pending_disruption(c)
            ]
            want = pool.spec.replicas or 0
            if len(claims) < want:
                delta += self._scale_up(pool, want - len(claims))
            elif len(claims) > want:
                delta -= self._scale_down(claims, len(claims) - want)
        return delta

    def _pending_disruption(self, claim: NodeClaim) -> bool:
        """A StaticDrift candidate awaiting replace-then-delete still holds
        a replica slot; counting it would make this controller delete the
        fresh replacement (the reference tracks this via NodePoolState's
        nodesPendingDisruption, staticdrift.go:72-77)."""
        sn = self.cluster.node_by_provider_id(claim.status.provider_id or "")
        return sn is not None and sn.marked_for_deletion

    def _scale_up(self, pool: NodePool, count: int) -> int:
        from karpenter_tpu.cloudprovider.errors import instance_types_or_none

        pool_its = instance_types_or_none(self.cloud, pool)
        if pool_its is None:
            return 0  # unevaluated pool: retry after the overlay reconcile
        template = build_template(pool, pool_its)
        created = 0
        for _ in range(count):
            requirements = []
            for r in template.requirements.values():
                entry = {"key": r.key, "operator": r.operator().value}
                if r.values:
                    entry["values"] = sorted(r.values)
                requirements.append(entry)
            claim = NodeClaim(
                metadata=ObjectMeta(
                    name=f"{pool.name}-{new_uid('static')}",
                    labels={**template.labels, l.NODEPOOL_LABEL_KEY: pool.name},
                    annotations={l.NODEPOOL_HASH_ANNOTATION_KEY: template.nodepool_hash},
                ),
                spec=NodeClaimSpec(
                    taints=list(template.taints),
                    startup_taints=list(template.startup_taints),
                    requirements=requirements,
                    expire_after_seconds=template.expire_after_seconds,
                ),
            )
            self.store.create(ObjectStore.NODECLAIMS, claim)
            self.cluster.update_nodeclaim(claim)
            created += 1
        return created

    def _scale_down(self, claims: list[NodeClaim], count: int) -> int:
        """Empty nodes first, then youngest (deprovisioning
        controller.go:84-270)."""

        def sort_key(claim: NodeClaim):
            sn = self.cluster.node_by_provider_id(claim.status.provider_id or "")
            pods = len(sn.pods) if sn is not None else 0
            return (pods, -claim.metadata.creation_timestamp)

        removed = 0
        for claim in sorted(claims, key=sort_key)[:count]:
            self.store.delete(ObjectStore.NODECLAIMS, claim.name)
            removed += 1
        return removed
