"""NodeClaim disruption-condition controller: marks Drifted.

Counterpart of reference pkg/controllers/nodeclaim/disruption
(controller.go:77-113, drift.go:86-181): a claim drifts when the provider
reports drift, or when its NodePool's static-field hash no longer matches
the hash annotation stamped at creation, or when its requirements no
longer satisfy the pool's requirements.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import COND_DRIFTED, NodeClaim
from karpenter_tpu.scheduling.requirements import node_selector_requirement
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock


class NodeClaimDisruptionController:
    def __init__(self, store: ObjectStore, cloud: CloudProvider, clock: Clock):
        self.store = store
        self.cloud = cloud
        self.clock = clock

    def drift_reason(self, claim: NodeClaim) -> Optional[str]:
        pool = self.store.get(ObjectStore.NODEPOOLS, claim.nodepool_name or "")
        if pool is None:
            return None
        # provider-side drift (CloudProvider.IsDrifted)
        reason = self.cloud.is_drifted(claim)
        if reason:
            return reason
        # static-field hash drift (drift.go:154-168)
        stamped = claim.metadata.annotations.get(l.NODEPOOL_HASH_ANNOTATION_KEY)
        if stamped is not None and stamped != pool.static_hash():
            return "NodePoolDrifted"
        # requirement drift (drift.go:170-181): the claim's labels must
        # still satisfy every pool requirement — a requirement on a key the
        # claim has no label for is also drift
        for r in pool.spec.template.spec.requirements:
            req = node_selector_requirement(r["key"], r["operator"], r.get("values", ()))
            label = claim.metadata.labels.get(req.key)
            if label is None:
                if not req.is_lenient():
                    return "RequirementsDrifted"
                continue
            if not req.has(label):
                return "RequirementsDrifted"
        return None

    def reconcile(self, claim: NodeClaim) -> bool:
        reason = self.drift_reason(claim)
        if reason:
            changed = claim.conditions.set_true(COND_DRIFTED, reason, now=self.clock.now())
        else:
            changed = claim.conditions.set_false(COND_DRIFTED, "NotDrifted", now=self.clock.now())
        if changed and self.store.get(ObjectStore.NODECLAIMS, claim.name) is not None:
            self.store.update(ObjectStore.NODECLAIMS, claim)
        return changed
