"""Provisioning: pending pods -> NodeClaims.

Counterpart of reference pkg/controllers/provisioning. The scheduler here
has two interchangeable engines driven by the same template/claim model:

  host_scheduler.py  exact-semantics Python packer — the oracle the device
                     engine is differentially tested against, and the
                     fallback for exotic features not yet tensorized
  scheduler.py       the TPU engine: encode -> ops.solver -> decode
"""

from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import (  # noqa: F401
    ClaimTemplate,
    build_templates,
)
from karpenter_tpu.controllers.provisioning.host_scheduler import (  # noqa: F401
    HostScheduler,
    SchedulingResult,
    SimClaim,
)
from karpenter_tpu.controllers.provisioning.scheduler import (  # noqa: F401
    ResidentSession,
    TPUScheduler,
)
