"""The TPU scheduling engine: encode -> ops.solver -> decode.

Produces bit-identical packings to the HostScheduler oracle (differentially
tested in tests/test_solver.py): same FFD order, same fewest-pods-first
claim selection, same weight-ordered template fallback, same triple-mask
instance-type filtering — but evaluated as dense tensor ops in one
`lax.scan` on the accelerator instead of per-pod goroutine fan-outs.

Shape discipline: the label vocabulary can grow across solve() calls (new
pods may introduce new keys/values). Static problem tensors are re-encoded
whenever the vocab changes, with key/value axes padded to powers of two so
XLA's compile cache keeps hitting; problem tensors are jit arguments, not
closure constants, so re-encoding alone never recompiles.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from karpenter_tpu.cloudprovider.instancetype import InstanceType
from karpenter_tpu.controllers.provisioning.host_scheduler import (
    ExistingSimNode,
    HostScheduler,
    SchedulingResult,
    SimClaim,
    ffd_sort,
    hostname_placeholder,
    normalize_volume_reqs,
)
from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import ClaimTemplate
from karpenter_tpu.guard import (
    QUARANTINE,
    DispatchStallError,
    run_guarded,
)
from karpenter_tpu.guard import audit as guard_audit
from karpenter_tpu.guard import config as guard_config
from karpenter_tpu.controllers.provisioning.topology import Topology, build_universe_domains
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.obs import waterfall as _wfl
from karpenter_tpu.ops import solver as ops_solver
from karpenter_tpu.ops import topology as topo_ops
from karpenter_tpu.ops.encode import PadBucketCache, ProblemEncoder, encode_requirements
from karpenter_tpu.scheduling import Operator, Requirement, Requirements
from karpenter_tpu.scheduling.taints import tolerates_all
from karpenter_tpu.utils import resources as res


class DivergenceError(RuntimeError):
    """Device decode disagreed with the host algebra; the solve falls back
    to the host oracle (never aborts provisioning)."""


class _GangHostRoute(RuntimeError):
    """A gang solve hit a constraint family the device gang kernel does
    not cover (reservations, enforced minValues, multi-key/wide vg
    groups, hostname affinity, or a tripped "gang" quarantine — finite
    budgets and single-key gang topology now run on device, ISSUE 20);
    the solve degrades to the host oracle, which implements the
    identical all-or-nothing semantics."""


# NO_ROOM is a device-shape artifact with no reference analog: the Go
# scheduler always opens another node (scheduler.go:582-612). solve()
# recovers by doubling the claim-slot capacity and re-solving, so this
# reason only ever surfaces if recovery is impossible (it never is — the
# cap grows to one slot per pod).
NO_ROOM_REASON = "claim-slot capacity exhausted; raise max_claims"
NO_CLAIM_REASON = "no compatible in-flight claim or template"


def _next_pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


def _gather_pod_chunk(
    reqs_k, strict_k, requests_k, tol_k, it_allow_k, exist_ok_k, ports_k,
    conf_k, vols_k, pod_topo_k, kid, n_valid,
):
    """One fused device dispatch for a per-pod chunk's kind->pod gathers.

    Un-jitted, each chunk paid ~45 eager op dispatches (take_set +
    take_pod_topology + 6 indexings); jitted, the whole materialization is
    one cached executable per (chunk, tensor) shape class."""
    from karpenter_tpu.ops.kernels import take_set

    pt = ops_solver.PodTensors(
        reqs=take_set(reqs_k, kid),
        strict_reqs=take_set(strict_k, kid),
        requests=requests_k[kid],
        valid=jnp.arange(kid.shape[0]) < n_valid,
    )
    ptopo = topo_ops.take_pod_topology(pod_topo_k, kid)
    return (
        pt, tol_k[kid], it_allow_k[kid], exist_ok_k[kid], ports_k[kid],
        conf_k[kid], vols_k[kid], ptopo,
    )


def _gather_fill_xs(
    reqs_k, requests_k, tol_k, it_allow_k, exist_ok_k, ports_k, conf_k,
    vols_k, pod_topo_k, kid, counts,
):
    """Fused gather building FillXs for a batchable segment run."""
    from karpenter_tpu.ops.kernels import take_set

    ptopo = topo_ops.take_pod_topology(pod_topo_k, kid)
    return ops_solver.FillXs(
        reqs=take_set(reqs_k, kid),
        requests=requests_k[kid],
        tmpl_ok=tol_k[kid],
        it_allow=it_allow_k[kid],
        exist_ok=exist_ok_k[kid],
        ports=ports_k[kid],
        port_conf=conf_k[kid],
        vols=vols_k[kid],
        count=counts,
        hg_applies=ptopo.hg_applies,
        hg_records=ptopo.hg_records,
        hg_self=ptopo.hg_self,
    )


def _gather_kind_xs(
    reqs_k, strict_k, requests_k, tol_k, it_allow_k, exist_ok_k, ports_k,
    conf_k, vols_k, pod_topo_k, kid, counts,
):
    """Fused gather building KindXs for a kind-scan segment run."""
    from karpenter_tpu.ops.kernels import take_set

    ptopo = topo_ops.take_pod_topology(pod_topo_k, kid)
    return ops_solver.KindXs(
        reqs=take_set(reqs_k, kid),
        strict_mask=strict_k.mask[kid],
        requests=requests_k[kid],
        tmpl_ok=tol_k[kid],
        it_allow=it_allow_k[kid],
        exist_ok=exist_ok_k[kid],
        ports=ports_k[kid],
        port_conf=conf_k[kid],
        vols=vols_k[kid],
        count=counts,
        vg_applies=ptopo.vg_applies,
        vg_records=ptopo.vg_records,
        vg_self=ptopo.vg_self,
        hg_applies=ptopo.hg_applies,
        hg_records=ptopo.hg_records,
        hg_self=ptopo.hg_self,
    )


_gather_pod_chunk_raw = _gather_pod_chunk
_gather_pod_chunk = jax.jit(_gather_pod_chunk_raw)
# batched over [DP] rows of (kind ids, valid counts): one dispatch gathers
# every dp row's per-pod chunk for the speculative perpod fan-out
_gather_pod_chunk_dp = jax.jit(
    jax.vmap(_gather_pod_chunk_raw, in_axes=(None,) * 10 + (0, 0))
)
# the raw (un-jitted) gather also feeds the dp-batched variant below
_gather_fill_xs_raw = _gather_fill_xs
_gather_fill_xs = jax.jit(_gather_fill_xs_raw)
# batched over [DP] rows of (kind ids, counts): one dispatch gathers every
# dp row's chunk-group FillXs (leading axis = the mesh's dp axis)
_gather_fill_xs_dp = jax.jit(
    jax.vmap(_gather_fill_xs_raw, in_axes=(None,) * 9 + (0, 0))
)
_gather_kind_xs_raw = _gather_kind_xs
_gather_kind_xs = jax.jit(_gather_kind_xs_raw)
# batched over [DP] rows of (kind ids, counts): one dispatch gathers every
# dp row's chunk-group KindXs for the speculative kscan fan-out
_gather_kind_xs_dp = jax.jit(
    jax.vmap(_gather_kind_xs_raw, in_axes=(None,) * 10 + (0, 0))
)

# speculative dp families (metrics labels + shard stats keys): the three
# fill-shaped labels split by what shared state the verdict had to prove
# disjoint — plain capacity (fill), existing-node debits (existing),
# hostname-group counts (topo_fill) — plus the kscan and per-pod engines
_SHARD_FAMILIES = ("fill", "existing", "topo_fill", "kscan", "perpod")


def _slim_outputs(specs: tuple, flat) -> tuple[list, list]:
    """Shared output slimming for the jitted fetch preps: slices every
    output to its live rows and narrows fill grids to int16. Returns the
    processed list plus the per-grid fill maxes (overflow guard)."""
    proc: list = []
    maxes: list = []
    i = 0
    for spec in specs:
        if spec[0] == "pods":
            proc.append(flat[i])
            i += 1
        elif spec[0] == "kscan":
            proc.append(flat[i][: spec[1]])
            proc.append(flat[i + 1][: spec[1]])  # per-segment grid_reused
            i += 2
        elif spec[0] == "gang":
            B = spec[1]
            proc.extend(a[:B] for a in flat[i : i + 5])
            i += 5
        else:
            B = spec[1]
            fc, fe, os_, no_, st_, sm = flat[i : i + 6]
            i += 6
            maxes.append(jnp.max(fc))
            if fe.size:
                maxes.append(jnp.max(fe))
            proc.extend(
                [
                    fc[:B].astype(jnp.int16),
                    fe[:B].astype(jnp.int16),
                    os_[:B],
                    no_[:B],
                    st_[:B],
                    sm,  # the dispatch's window -> global slot map
                ]
            )
    return proc, maxes


# scalar/column head every decode fetch starts with (before tk rows)
_STATE_HEAD = ("template", "its", "used", "held", "n_open", "w_open", "w_hw", "spills")


def _state_reads(state, tk: tuple) -> list:
    """The final-state reads every decode needs: GLOBAL-slot claim
    finalization columns (hot window merged over the frozen bank), the
    n_open/window sync scalars, and (when vg topology narrowed anything)
    the topo-key requirement rows, pre-gathered on device."""
    g = ops_solver.global_claims(state, tk)
    proc = [
        g["template"], g["its"], g["used"], g["held"],
        state.n_open, state.w_open, state.w_hw, state.spills,
    ]
    if tk:
        kid = list(tk)
        proc.extend(
            [
                g["tk_mask"],
                g["tk_inf"],
                g["tk_def"],
                state.exist_reqs.mask[:, kid, :],
                state.exist_reqs.inf[:, kid],
                state.exist_reqs.defined[:, kid],
            ]
        )
    return proc


def _make_fetch_prep(specs: tuple, tk: tuple):
    """Build the jitted decode-fetch prep for one output-structure
    signature: slices every output to its live rows, narrows fill grids to
    int16, gathers the topology-key requirement rows, and emits ONE flat
    list (state reads first, outputs in order, fill_max, topo masks).
    The caller caches the jitted function per (specs, tk, pad signature)
    so repeated solves with the same shape reuse one executable."""

    n_head = len(_STATE_HEAD)

    def _prep(state, flat):
        reads = _state_reads(state, tk)
        proc = reads[:n_head]
        out, maxes = _slim_outputs(specs, flat)
        proc.extend(out)
        if maxes:
            proc.append(jnp.max(jnp.stack(maxes)))
        proc.extend(reads[n_head:])
        return proc

    return _prep


def _make_group_prep(specs: tuple):
    """Jitted fetch prep for ONE pipeline chunk group: the group's outputs
    (slimmed exactly like the monolithic prep) plus the post-group
    template snapshot (claims opened by this group already carry their
    final template) and the group's own fill-overflow max."""

    def _prep(tmpl, flat):
        proc = [tmpl]
        out, maxes = _slim_outputs(specs, flat)
        proc.extend(out)
        if maxes:
            proc.append(jnp.max(jnp.stack(maxes)))
        return proc

    return _prep


def _make_final_prep(tk: tuple):
    """Jitted fetch prep for the pipelined decode's final state fetch."""

    def _prep(state):
        return _state_reads(state, tk)

    return _prep


def _make_group_final_prep(specs: tuple, tk: tuple):
    """Jitted fetch prep for the LAST pipeline chunk group: the group's
    outputs AND the final-state reads ride ONE transfer, so the pipelined
    decode pays no trailing state-fetch round trip (the ROADMAP's
    "ride the final state fetch on the last chunk group" lever)."""

    def _prep(tmpl, flat, state):
        proc = [tmpl]
        out, maxes = _slim_outputs(specs, flat)
        proc.extend(out)
        if maxes:
            proc.append(jnp.max(jnp.stack(maxes)))
        proc.extend(_state_reads(state, tk))
        return proc

    return _prep


def _partition_ranges(weights: Sequence, n_groups: int) -> list[tuple[int, int]]:
    """Split [0, len(weights)) into <= n_groups contiguous ranges with
    roughly balanced total weight (the pipelined decode's chunk groups)."""
    n = len(weights)
    n_groups = max(min(n_groups, n), 1)
    total = float(sum(weights)) or 1.0
    out: list[tuple[int, int]] = []
    lo = 0
    acc = 0.0
    for i, w in enumerate(weights):
        acc += w
        if len(out) < n_groups - 1 and acc >= total * (len(out) + 1) / n_groups:
            out.append((lo, i + 1))
            lo = i + 1
    if lo < n:
        out.append((lo, n))
    return out


def _merge_scaled(base: dict, req: dict, c: int) -> dict:
    """base + c*req per resource, in the fill kernel's one-multiply-add f32
    convention (see ops/solver.py batch placement comment) so host decode
    stays bit-identical with the device carry."""
    out = dict(base)
    cf = np.float32(c)
    for k, v in req.items():
        out[k] = float(np.float32(np.float32(out.get(k, 0.0)) + cf * np.float32(v)))
    return out


def _decode_fill_segments(ctx, segs, f) -> None:
    """Vectorized fill decode: expand every segment's per-slot counts to a
    per-pod slot stream via ONE global np.repeat over (value, count) pairs
    collected in pure Python from the COO fetch, then apply grouped —
    identical pod/claim/merge ORDER to the per-pod replay it replaces
    (tier 1 in node-index order, tier 2 in water-fill interleave order,
    tier 3 in slot order, leftovers last; f32 usage merges one
    multiply-add per (segment, node)). Multi-slot tier-2 interleaves are
    rare, so they land as small permutation fixups on the repeated stream.

    Fill grids address WINDOW rows; `slot_map` (the dispatch's slot_of
    snapshot) translates them to global claim ids — the tier-2/tier-3
    split stays in window coordinates (open_start is the segment's
    w_open), while every emitted slot is global.

    `ctx` carries the decode bookkeeping (the full decode's closure state,
    or a ResidentSession's persistent cross-round bookkeeping — both paths
    share this function so delta rounds replay the exact same order
    semantics): E, existing_nodes, pods_sorted, ensure_claim,
    slot_to_claim, claim_kinds, claim_pod_counts, NC1, assignments,
    existing_assignments, unschedulable, node_kinds, kind_ports,
    kind_total."""
    E = ctx.E
    pods_sorted = ctx.pods_sorted
    lo0 = segs[0][0]
    vals: list[int] = []  # E-space slot ids / negative sentinels
    cnts: list[int] = []
    # (stream_pos, slots, counts, p0s) for multi-slot tier-2 runs
    fixups: list = []
    # (kind, e_slots, e_counts) per segment, in segment order
    exist_merges: list = []
    # (slot, kind, count) per touched claim, in segment order
    claim_events: list = []
    fill_c = f["fill_c"]
    fill_e = f["fill_e"]
    open_start = f["open_start"]
    n_opened = f["n_opened"]
    status = f["status"]
    slot_map = np.asarray(f["slot_map"], dtype=np.int64)
    pc = ctx.claim_pod_counts
    # ONE nonzero scan over the whole [B, W] grid; per-segment
    # (window row, count) pairs come from the row-pointer slices
    js, ss = np.nonzero(fill_c)
    cc = fill_c[js, ss].tolist()
    ss_l = ss.tolist()
    gs_l = slot_map[ss].tolist() if ss.size else []
    row_ptr = np.searchsorted(js, np.arange(len(segs) + 1))
    for j, (lo, hi, kind) in enumerate(segs):
        count = hi - lo
        if count == 0:
            continue
        placed = 0
        # tier 1: existing nodes in index order
        if E:
            e_idx = np.flatnonzero(fill_e[j])
            if e_idx.size:
                el = e_idx.tolist()
                cl = fill_e[j][e_idx].tolist()
                vals += el
                cnts += cl
                placed += sum(cl)
                exist_merges.append((kind, el, cl))
        # touched window rows, ascending (np.nonzero row-major; window
        # order is open order, so global ids ascend too)
        a, b = int(row_ptr[j]), int(row_ptr[j + 1])
        pairs = list(zip(ss_l[a:b], gs_l[a:b], cc[a:b]))
        new_lo = int(open_start[j])
        new_hi = new_lo + int(n_opened[j])
        # tier 2: water-fill interleave over in-flight claims
        t2 = [(g_, c) for s, g_, c in pairs if not new_lo <= s < new_hi]
        if t2:
            if len(t2) > 1:
                fixups.append(
                    (
                        lo - lo0 + placed,
                        [g_ for g_, _ in t2],
                        [c for _, c in t2],
                        [int(pc[g_]) for g_, _ in t2],
                    )
                )
            for g_, c in t2:
                vals.append(E + g_)
                cnts.append(c)
                pc[g_] += c
                placed += c
                claim_events.append((g_, kind, c))
        # tier 3: new claims in slot order, each filled to capacity
        if new_hi > new_lo:
            for s, g_, c in pairs:
                if new_lo <= s < new_hi:
                    vals.append(E + g_)
                    cnts.append(c)
                    pc[g_] += c
                    placed += c
                    claim_events.append((g_, kind, c))
        # leftovers failed with a uniform reason
        left = count - placed
        if left > 0:
            vals.append(
                ops_solver.NO_ROOM
                if int(status[j]) == ops_solver.NO_ROOM
                else -1
            )
            cnts.append(left)
    stream = np.repeat(
        np.asarray(vals, dtype=np.int64),
        np.asarray(cnts, dtype=np.int64),
    )
    # tier-2 interleave fixups: rewrite the slot-grouped span in
    # fewest-pods-first (level, slot) order — same keys as the
    # sequential replay
    for pos, slots, counts, p0s in fixups:
        c2 = np.asarray(counts, dtype=np.int64)
        n2 = int(c2.sum())
        p0 = np.asarray(p0s, dtype=np.int64)
        t2a = np.asarray(slots, dtype=np.int64)
        ar = np.arange(n2, dtype=np.int64)
        cum0 = np.cumsum(c2) - c2
        levels = ar - np.repeat(cum0 - p0, c2)
        slots_rep = np.repeat(t2a, c2)
        order = np.argsort(levels * ctx.NC1 + slots_rep, kind="stable")
        stream[pos : pos + n2] = E + slots_rep[order]

    # ---- apply: claims ensured in ascending-slot order (== the
    # device's contiguous open order, so hostnames match the
    # sequential replay), pods grouped by slot in stream order
    cmask = stream >= E
    if cmask.any():
        ci = np.flatnonzero(cmask)
        cs = stream[ci] - E
        o = np.argsort(cs, kind="stable")
        cs_sorted = cs[o]
        ci_list = (ci[o] + lo0).tolist()
        bounds = np.flatnonzero(np.diff(cs_sorted)) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(cs_sorted)]))
        for a, b in zip(starts.tolist(), ends.tolist()):
            s = int(cs_sorted[a])
            claim = ctx.ensure_claim(s)
            batch = [pods_sorted[i] for i in ci_list[a:b]]
            claim.pods.extend(batch)
            for p in batch:
                ctx.assignments[p.metadata.uid] = s
    for s, kind, c in claim_events:
        claim = ctx.slot_to_claim[s]
        pk = ctx.kind_ports(kind)
        if pk:
            claim.host_ports.extend(pk * c)
        ck = ctx.claim_kinds[s]
        ck[kind] = ck.get(kind, 0) + c
    # ---- apply: existing nodes (index order per segment)
    emask = (stream >= 0) & (stream < E)
    if emask.any():
        ei = np.flatnonzero(emask)
        es = stream[ei]
        o = np.argsort(es, kind="stable")
        es_sorted = es[o]
        ei_sorted = ei[o]
        bounds = np.flatnonzero(np.diff(es_sorted)) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(es_sorted)]))
        ei_list = (ei_sorted + lo0).tolist()
        for a, b in zip(starts.tolist(), ends.tolist()):
            node = ctx.existing_nodes[int(es_sorted[a])]
            batch = [pods_sorted[i] for i in ei_list[a:b]]
            node.pods.extend(batch)
            for p in batch:
                ctx.existing_assignments[p.metadata.uid] = node.name
    for kind, e_idx, ce in exist_merges:
        req_d = ctx.kind_total(kind)
        pk = ctx.kind_ports(kind)
        for e, c in zip(e_idx, ce):
            node = ctx.existing_nodes[e]
            node.used = _merge_scaled(node.used, req_d, c)
            if pk:
                node.host_ports.extend(pk * c)
            nk = ctx.node_kinds.setdefault(e, {})
            nk[kind] = nk.get(kind, 0) + c
    # ---- apply: leftovers, in stream (= segment) order
    nmask = stream < 0
    if nmask.any():
        for i in np.flatnonzero(nmask).tolist():
            reason = (
                NO_ROOM_REASON
                if stream[i] == ops_solver.NO_ROOM
                else NO_CLAIM_REASON
            )
            ctx.unschedulable.append((pods_sorted[lo0 + i], reason))


class TPUScheduler:
    """One scheduler instance per template/catalog set; reusable across
    solve() batches (the vocab may grow between calls)."""

    # round-ledger plumbing: a ResidentSession suppresses the wrapped
    # scheduler's per-solve records (its internal full solves and audit
    # twins are sub-steps of ONE session round, which it records itself);
    # host_solve stamps the fallback reason for the round's record
    _ledger_suppress = False
    _last_fallback: Optional[str] = None

    def __init__(
        self,
        templates: list[ClaimTemplate],
        max_claims: Optional[int] = None,
        pod_pad: Optional[int] = None,
        reserved_mode: str = "fallback",
        reserved_capacity_enabled: bool = True,
        min_values_policy: str = "Strict",
        mesh=None,
        objective: Optional[str] = None,
    ):
        from karpenter_tpu.utils.accel import enable_persistent_compile_cache

        enable_persistent_compile_cache()  # restarts skip the cold compile
        # Multi-chip: a jax.sharding.Mesh with an "it" axis shards the
        # catalog (and every [.., T] mask) across devices; GSPMD inserts
        # the ICI collectives inside the same solve kernels the
        # single-device path compiles (SURVEY §2.9). None = single device.
        self.mesh = mesh
        self.reserved_mode = reserved_mode
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.min_values_policy = min_values_policy
        self.templates = templates
        self.existing_nodes: list[ExistingSimNode] = []
        self.budgets: dict[str, dict[str, float]] = {}
        # union catalog over all templates, stable order, deduped by name
        seen: dict[str, InstanceType] = {}
        for t in templates:
            for it in t.instance_types:
                seen.setdefault(it.name, it)
        self.catalog: list[InstanceType] = list(seen.values())
        self._it_index = {name: i for i, name in enumerate(seen)}
        self.max_claims = max_claims
        self._n_claims_override: Optional[int] = None
        self._tmpl_it_idx: dict = {}
        self._fetch_prep_cache: dict = {}
        # warm-start sizing of the claims axis: the device scan's per-step
        # cost is linear in n_claims, so steady-state solves shrink the
        # axis to a bucket above the last solve's observed need (NO_ROOM
        # recovery in solve_round escalates if the workload grows)
        self._last_n_open: Optional[int] = None
        self._last_n_claims: Optional[int] = None
        self._adaptive_claims = False  # only the solve() path warm-sizes
        self.pod_pad = pod_pad
        import os

        self.solve_chunk = int(os.environ.get("KTPU_SOLVE_CHUNK", "2048"))
        # active-window sizing for the claims axis: the scan's hot tensors
        # cover only `window` resident claims (capacity-dead claims are
        # evicted to the frozen bank between dispatches), so the per-step
        # cost tracks the LIVE claim count instead of cumulative opens.
        # 0 = adaptive: full axis cold, live high-water + margin warm.
        self.scan_window = int(os.environ.get("KTPU_SCAN_WINDOW", "0") or 0)
        # un-windowed solves at/above this size still run boundary
        # compaction so w_hw measures true residency for warm sizing
        self.compact_min_pods = int(
            os.environ.get("KTPU_COMPACT_MIN_PODS", "1024") or 0
        )
        self._window_override: Optional[int] = None
        self._last_w_hw: Optional[int] = None
        self._last_window: Optional[int] = None
        self._scan_stats: Optional[dict] = None
        # incremental encode cache: per-kind encoded rows keyed on the kind
        # content signature, valid while the vocab/pads/catalog stand still
        self.encode_cache_enabled = (
            os.environ.get("KTPU_ENCODE_CACHE", "1") not in ("0", "false")
        )
        self._encode_cache: dict = {}
        self._encode_cache_key: Optional[tuple] = None
        # software pipeline (encode/dispatch vs wire/decode overlap): split
        # large solves into ~K chunk groups; each group's outputs are
        # fetched and decoded while the device still runs later chunks.
        # K <= 1 disables; small solves stay on the single-fetch path
        # (pipelining adds one wire round trip per group, only worth it
        # when device compute per chunk can hide it).
        self.pipeline_chunks = int(os.environ.get("KTPU_PIPELINE_CHUNKS", "4"))
        self.pipeline_min_pods = int(os.environ.get("KTPU_PIPELINE_MIN_PODS", "4096"))
        # dp-sharded speculative fill (ISSUE 8): on a mesh with dp > 1,
        # pipelined fill chunk groups solve one-per-dp-row in a single
        # batched dispatch and merge exact-or-replay; bit-parity with the
        # single-device solve is structural (see ops/solver.py dp section)
        self.shard_dp = os.environ.get("KTPU_SHARD_DP", "1") not in ("0", "false")
        # dp-sharded speculative kscan (ISSUE 13): zonal-spread kinds join
        # the fan-out under the per-domain deadness predicate; KTPU_SHARD_KSCAN=0
        # opts kscan runs (only) back onto the sequential scan
        self.shard_kscan = os.environ.get("KTPU_SHARD_KSCAN", "1") not in (
            "0", "false"
        )
        # dp speculation for the stateful families (ISSUE 14):
        # KTPU_SHARD_EXISTING=0 re-imposes the `no real existing nodes`
        # eligibility gate on every dp family; KTPU_SHARD_PERPOD=0 opts
        # per-pod chunk runs (only) back onto the sequential scan
        self.shard_existing = os.environ.get(
            "KTPU_SHARD_EXISTING", "1"
        ) not in ("0", "false")
        self.shard_perpod = os.environ.get("KTPU_SHARD_PERPOD", "1") not in (
            "0", "false"
        )
        # pluggable placement objectives (objectives/): an explicit
        # NodePool policy (threaded by the provisioner) or KTPU_OBJECTIVE
        # selects a template-rank policy per solve; non-lexical fill
        # rounds fan KTPU_OBJECTIVE_K rank variants over the dp axis and
        # commit the best-scoring row off ONE verdict word per round
        self.objective = objective
        self._objective_ranks: dict = {}
        self._price_t = None
        self._price_t_np: Optional[np.ndarray] = None
        self._active_policy: str = "lexical"
        self._shard_stats: Optional[dict] = None
        # per-chunk streaming sink (gRPC SolveStream); None in-process
        self._chunk_sink = None
        # resident-session capture: when a ResidentSession wraps this
        # scheduler, full solves stash their post-solve device state +
        # decode bookkeeping here so delta rounds can resume from them
        self._capture = False
        self._captured: Optional[dict] = None
        # elementwise max over the r_min vectors boundary compaction used
        # this solve — the resident session's eviction-soundness floor
        # (an arrival below it could have fit an evicted claim)
        self._last_compact_rmin: Optional[np.ndarray] = None
        # tighter-than-pow2 pad buckets with executable-reuse amortization
        self._pad_cache = PadBucketCache()
        self._volume_reqs: dict = {}
        self._pod_vols: dict = {}
        self._reserved_in_use: dict[str, int] = {}

        self.encoder = ProblemEncoder()
        for t in templates:
            self.encoder.observe_requirements(t.requirements)
        for it in self.catalog:
            self.encoder.observe_instance_type(it)
        self._vocab_sig: Optional[tuple] = None

    def resident_session(self) -> "ResidentSession":
        """Wrap this scheduler in a ResidentSession: SolverState stays
        resident on device across solve() calls and steady-state rounds
        feed only the pod DELTA through the pipeline (ISSUE 7)."""
        return ResidentSession(self)

    # -- encoding ----------------------------------------------------------

    def universe_base(self) -> dict:
        """Cached template/catalog half of the topology domain universe
        (immutable per scheduler; the O(T x K) catalog scan runs once)."""
        if not hasattr(self, "_universe_base"):
            from karpenter_tpu.controllers.provisioning.topology import (
                template_universe_domains,
            )

            self._universe_base = template_universe_domains(self.templates)
        return self._universe_base

    def _sig(self) -> tuple:
        v = self.encoder.vocab
        return (v.n_keys, tuple(len(vals) for vals in v.values), self.encoder.n_resources)

    def _pads(self) -> tuple[int, int]:
        v = self.encoder.vocab
        return _next_pow2(max(v.n_keys, 1), 8), _next_pow2(max(v.max_values, 1), 8)

    def _encode_static(self) -> None:
        """(Re-)encode instance types + templates against the current vocab."""
        enc = self.encoder
        k_pad, v_pad = self._pads()
        itt = enc.encode_instance_types(self.catalog)
        # re-pad the requirement tensors to the bucketed K/V
        itt = itt._replace(
            reqs=encode_requirements(
                enc.vocab, [it.requirements for it in self.catalog], k_pad, v_pad, enc.skip_keys
            )
        )
        if self.mesh is not None:
            # shard the catalog over the mesh's "it" axis; padded types are
            # invalid/match-nothing so results stay bit-identical
            from karpenter_tpu.parallel.mesh import shard_instance_types

            itt = shard_instance_types(itt, self.mesh)
        self.it_tensors = itt
        self._T_pad = int(itt.alloc.shape[0])
        G = len(self.templates)
        tmpl_reqs = encode_requirements(
            enc.vocab, [t.requirements for t in self.templates], k_pad, v_pad, enc.skip_keys
        )
        its = np.zeros((G, self._T_pad), dtype=bool)
        daemon = np.zeros((G, enc.n_resources), dtype=np.float32)
        for g, t in enumerate(self.templates):
            for it in t.instance_types:
                its[g, self._it_index[it.name]] = True
            daemon[g] = enc.resources_vector(t.daemon_requests)
        # minValues floors from template requirements (the only carriers of
        # minValues — pods never set them); -1 keys the instance-type NAME.
        # The distinct min-keyed label names index a pre-gathered [T, J, V]
        # slab of each instance type's DEFINED finite values for that key
        # (undefined/complement keys contribute nothing — Values() parity).
        mv_keys_named: list[str] = []
        mv_lists = []
        for t in self.templates:
            entries = []
            for r in t.requirements.values():
                if r.min_values is None:
                    continue
                if r.key == l.LABEL_INSTANCE_TYPE:
                    entries.append((-1, r.min_values))
                else:
                    if r.key not in mv_keys_named:
                        mv_keys_named.append(r.key)
                    entries.append((mv_keys_named.index(r.key), r.min_values))
            mv_lists.append(entries)
        M = _next_pow2(max((len(e) for e in mv_lists), default=1), 1)
        mv_key = np.full((G, M), -2, dtype=np.int32)
        mv_min = np.zeros((G, M), dtype=np.int32)
        for g, entries in enumerate(mv_lists):
            for m, (k, v) in enumerate(entries):
                mv_key[g, m] = k
                mv_min[g, m] = v
        J = max(len(mv_keys_named), 1)
        mv_it_values = np.zeros((self._T_pad, J, v_pad), dtype=bool)
        for j, key_name in enumerate(mv_keys_named):
            kid = enc.vocab.key_to_id.get(key_name)
            if kid is None:
                continue
            for t_idx, it in enumerate(self.catalog):
                if not it.requirements.has(key_name):
                    continue
                # raw value set regardless of operator — Go's
                # Requirement.Values() (requirement.go:282-284) returns the
                # stored set even for NotIn, and the host oracle counts the
                # same way (satisfies_min_values)
                r = it.requirements.get(key_name)
                for v in r.values:
                    vid = enc.vocab.value_to_id[kid].get(v)
                    if vid is not None:
                        mv_it_values[t_idx, j, vid] = True
        self._mv_active = any(mv_lists)
        # shard the per-type template columns AT device_put time (instead
        # of replicating and re-constraining inside the kernels): the
        # [G, T] membership mask and the [T, J, V] minValues slab follow
        # the catalog's "it" sharding from birth
        from karpenter_tpu.ops.encode import place_sharded

        tmpl_its = place_sharded(its, self.mesh, None, "it")
        mv_slab = place_sharded(mv_it_values, self.mesh, "it")
        self.template_tensors = ops_solver.Templates(
            reqs=tmpl_reqs,
            its=tmpl_its,
            daemon_requests=jnp.asarray(daemon),
            valid=jnp.ones(G, dtype=bool),
            # per-solve budgets are patched in by solve()
            budget=jnp.full((G, enc.n_resources), np.inf, dtype=jnp.float32),
            nodes_budget=jnp.full(G, np.inf, dtype=jnp.float32),
            mv_key=jnp.asarray(mv_key),
            mv_min=jnp.asarray(mv_min),
            mv_it_values=mv_slab,
        )
        wk = enc.vocab.well_known_mask()
        self.well_known = jnp.asarray(
            np.pad(wk, (0, k_pad - len(wk)), constant_values=False)
        )
        # reserved-capacity vocabulary (reservationmanager.go:40-47);
        # capacities are re-read per solve — the provider mutates them as
        # reserved instances launch and terminate
        rid_kid, res_vid, rid_names = enc.reservation_ids()
        self._rid_kid, self._res_vid, self._rid_names = rid_kid, res_vid, rid_names
        self._res_active = (
            self.reserved_capacity_enabled
            and rid_kid >= 0
            and res_vid >= 0
            and bool(np.asarray(self.it_tensors.res_ofs).any())
        )
        # objective rank/price columns derive from the catalog encode —
        # drop them whenever the vocab (and so the tensors) rebuild
        self._objective_ranks = {}
        self._price_t = None
        self._price_t_np = None
        self._vocab_sig = self._sig()

    def _encode_budgets(self) -> tuple[jnp.ndarray, jnp.ndarray]:
        enc = self.encoder
        G = len(self.templates)
        budget = np.full((G, enc.n_resources), np.inf, dtype=np.float32)
        nodes_budget = np.full(G, np.inf, dtype=np.float32)
        for g, t in enumerate(self.templates):
            pool_budget = self.budgets.get(t.nodepool_name)
            if pool_budget is not None:
                for k, v in pool_budget.items():
                    if k == "nodes":
                        nodes_budget[g] = v
                    elif k in enc.resource_names:
                        budget[g, enc.resource_names.index(k)] = v
        return jnp.asarray(budget), jnp.asarray(nodes_budget)

    def _encode_existing(self, e_pad: int) -> ops_solver.ExistingNodes:
        enc = self.encoder
        k_pad, v_pad = self._pads()
        exist_reqs = encode_requirements(
            enc.vocab,
            [n.requirements for n in self.existing_nodes]
            + [Requirements()] * (e_pad - len(self.existing_nodes)),
            k_pad,
            v_pad,
            enc.skip_keys,
        )
        avail = np.zeros((e_pad, enc.n_resources), dtype=np.float32)
        for e, n in enumerate(self.existing_nodes):
            avail[e] = enc.resources_vector(n.available)
        return ops_solver.ExistingNodes(
            reqs=exist_reqs,
            avail=jnp.asarray(avail),
            valid=jnp.asarray(
                [True] * len(self.existing_nodes)
                + [False] * (e_pad - len(self.existing_nodes))
            ),
            # packed uint32 bitsets (kernels.pack_bool_np layout); re-filled
            # per solve, inert 1-lane defaults when CSI limits don't bind
            ports=jnp.zeros((e_pad, 1), dtype=jnp.uint32),
            vols=jnp.zeros((e_pad, 1), dtype=jnp.uint32),
            vol_limits=jnp.full((e_pad, 1), np.inf, dtype=jnp.float32),
            vol_driver=jnp.zeros((1, 1), dtype=jnp.uint32),
        )

    # -- solving -----------------------------------------------------------

    def solve(
        self,
        pods: Sequence[Pod],
        existing_nodes: Optional[list[ExistingSimNode]] = None,
        *args,
        **kwargs,
    ) -> SchedulingResult:
        """``_solve_impl`` plus one round-ledger record (obs/ledger.py):
        every solve — device, host fallback, or a raised error — leaves a
        flight-recorder entry unless a ResidentSession is recording the
        enclosing round itself (``_ledger_suppress``).

        The whole round runs under the observatory's fallback attribution
        scope: encode/dispatch/decode helpers jitted outside a
        named_kernel entry point (chunk gathers, fetch preps) attribute
        their compiles to `solve_round` instead of `anonymous`."""
        from karpenter_tpu.obs.observatory import kernel_scope

        if self._ledger_suppress:
            with kernel_scope("solve_round"):
                return self._solve_impl(pods, existing_nodes, *args, **kwargs)
        import time as _time

        from karpenter_tpu.obs import ledger as obs_ledger

        self._last_fallback = None
        pods = list(pods)
        n_pods = len(pods)
        # plain-solve problem capsule (ISSUE 17): only a spill-enabled
        # ledger pays for the pristine-input copy — the solve may mutate
        # existing nodes, and the capsule must record what went IN
        cap_existing = (
            [n.clone() for n in (existing_nodes or ())]
            if obs_ledger.spill_dir()
            else None
        )
        t0 = _time.perf_counter()
        try:
            with kernel_scope("solve_round"):
                result = self._solve_impl(
                    pods, existing_nodes, *args, **kwargs
                )
        except BaseException as err:
            obs_ledger.record_solve(
                self,
                pods=n_pods,
                wall_s=_time.perf_counter() - t0,
                reason=type(err).__name__,
                outcome="error",
                pod_list=pods if cap_existing is not None else None,
                existing_nodes=cap_existing,
            )
            raise
        obs_ledger.record_solve(
            self,
            pods=n_pods,
            wall_s=_time.perf_counter() - t0,
            pod_list=pods if cap_existing is not None else None,
            existing_nodes=cap_existing,
        )
        return result

    def _solve_impl(
        self,
        pods: Sequence[Pod],
        existing_nodes: Optional[list[ExistingSimNode]] = None,
        budgets: Optional[dict[str, dict[str, float]]] = None,
        topology: Optional[Topology] = None,
        topology_factory=None,
        volume_reqs: Optional[dict] = None,
        reserved_mode: Optional[str] = None,
        reserved_in_use: Optional[dict[str, int]] = None,
        dra_problem=None,
        pod_volumes: Optional[dict] = None,
        deadline: Optional[float] = None,
        now=None,
        bound_pods=None,  # data form of topology seeding; the in-process
        # engine uses topology_factory (the RPC client ships bound_pods)
        chunk_sink=None,  # pipelined-decode streaming: called with
        # ("reset", None) when a round (or fallback) restarts the tables
        # and ("chunk", delta) after each decoded chunk group
    ) -> SchedulingResult:
        """Solve with the preference relaxation ladder (preferences.go:38):
        each failing pod sheds ONE preference per round (shared loop in
        preferences.run_with_relaxation) and the whole problem re-solves.

        Fresh per-round state: existing nodes are cloned, and topology
        comes from topology_factory(pods) when given, else a pristine
        deepcopy of `topology` (group matching consults the pod's current
        spec, so shed constraints stop binding even on a stale topology),
        else a fresh build from the current pods.
        """
        import copy as _copy
        import time as _time

        from karpenter_tpu.controllers.provisioning import preferences as prefs

        norm_vol = normalize_volume_reqs(volume_reqs)
        now_fn = now if now is not None else _time.monotonic
        self._chunk_sink = chunk_sink
        # set by _encode when a solve dispatches the constraint-bearing
        # gang class on device (gang × topology / finite budgets) — the
        # guarded "gang" fast path, shadow-audited against the host oracle
        self._gang_device_class = False

        def host_twin() -> SchedulingResult:
            # the bare host-oracle solve on the identical problem: the
            # fallback rungs AND the "gang" shadow audit share it (the
            # audit must not count as a fallback or reset stream state)
            host = HostScheduler(
                self.templates,
                existing_nodes=[n.clone() for n in (existing_nodes or [])],
                budgets=budgets,
                topology=(
                    topology_factory(list(pods)) if topology_factory is not None else topology
                ),
                volume_reqs=norm_vol,
                reserved_mode=reserved_mode if reserved_mode is not None else self.reserved_mode,
                reserved_capacity_enabled=self.reserved_capacity_enabled,
                min_values_policy=self.min_values_policy,
                reserved_in_use=reserved_in_use,
                dra_problem=dra_problem,
                pod_volumes=pod_volumes,
                deadline=deadline,
                now=now_fn,
            )
            return host.solve(list(pods))

        def host_solve(reason: str) -> SchedulingResult:
            from karpenter_tpu.tracing.tracer import TRACER
            from karpenter_tpu.utils.metrics import SOLVER_FALLBACK, SOLVER_HOST_FALLBACKS

            # a host-oracle result has no device state to go resident on
            self._captured = None
            self._last_fallback = reason  # round-ledger: why we degraded
            if chunk_sink is not None:
                # any streamed chunks came from an abandoned device round;
                # the consumer must discard them before the full result
                chunk_sink(("reset", None))
            SOLVER_HOST_FALLBACKS.inc(reason=reason)
            SOLVER_FALLBACK.inc(reason=reason)
            cur = TRACER.current()
            if cur is not None:
                cur.set(host_fallback=reason)
            return host_twin()

        if dra_problem is not None and any(p.spec.resource_claims for p in pods):
            # DRA pods need the device-allocation DFS — deep, data-dependent
            # control flow with per-claim state that has no scan-friendly
            # shape. The host oracle is authoritative for these solves; the
            # device kernel keeps handling the claim-free hot path.
            return host_solve("dra")
        if any(len(alts) > 1 for alts in norm_vol.values()):
            # combinatorial volume-topology alternatives need the per-pod
            # try-each-alternative loop (nodeclaim.go:149-161); the device
            # kernel folds exactly one restriction per pod
            return host_solve("volume_alternatives")
        if norm_vol and existing_nodes:
            # the host checks volume requirements against existing nodes
            # with well-known-label leniency (existingnode.go:150); the
            # device folds them into the strict pod-reqs check. Identical
            # when every node defines the keys — route the rare
            # undefined-key case to the host to preserve parity
            vol_keys = {
                r.key for alts in norm_vol.values() for a in alts for r in a.values()
            }
            if any(
                not n.requirements.has(k) for n in existing_nodes for k in vol_keys
            ):
                return host_solve("volume_undefined_key")

        base_existing = list(existing_nodes or [])
        # problem context for guard divergence bundles: the shadow audits
        # fire deep inside the dispatch where pods are already encoded
        self._guard_problem = (list(pods), base_existing)
        # NO_ROOM escalation is per-solve: the next batch re-sizes from the
        # last observed need instead of inheriting a one-off doubling
        self._n_claims_override = None
        self._window_override = None
        self._volume_reqs = norm_vol
        # CSI attach limits ride the device scan (distinct-PVC popcounts
        # over a (driver, pvc) column vocabulary — volumeusage.go:201-208)
        self._pod_vols = pod_volumes or {}
        self._reserved_in_use = reserved_in_use or {}

        def solve_round(current: list[Pod]) -> SchedulingResult:
            # NO_ROOM recovery: the reference never fails a pod because the
            # solver ran out of claim slots (scheduler.go:582-612 always
            # opens another node) — double the slot capacity and re-solve
            # from scratch until every pod had a real chance at a slot.
            while True:
                # one waterfall per solve attempt: a NO_ROOM escalation
                # retry is a fresh round and gets fresh attribution
                with _wfl.round_waterfall():
                    with _wfl.span("topology"):
                        if topology_factory is not None:
                            topo = topology_factory(current)
                        elif topology is not None:
                            topo = _copy.deepcopy(topology)
                        else:
                            topo = None
                    from karpenter_tpu.tracing.tracer import TRACER

                    with TRACER.span("solve.round", pods=len(current)):
                        result = self._solve_once(
                            current, [n.clone() for n in base_existing], budgets, topo
                        )
                cap = _next_pow2(max(len(current), 1))
                used = self._last_n_claims or self.max_claims or cap
                leftover = sum(
                    1
                    for _, reason in result.unschedulable
                    if reason == NO_ROOM_REASON
                )
                spilled = (self._scan_stats or {}).get("spills", 0)
                if leftover and spilled:
                    # window-bound NO_ROOM: the claims axis had room but
                    # the active window was full — grow the window to the
                    # full axis and re-solve before escalating the axis
                    self._window_override = used
                    continue
                if used >= cap or not leftover:
                    return result
                # one-shot escalation: the failed solve already measured
                # claim density (placed pods per slot), so size the retry
                # from the leftover count instead of doubling repeatedly —
                # each retry is a full re-solve and possibly a cold compile
                placed = max(len(current) - leftover, 1)
                est = int(used * len(current) / placed * 1.25) + 32
                self._n_claims_override = min(
                    max(used * 2, -(-est // 256) * 256), cap
                )
                # the escalated retry runs un-windowed: a spill there
                # would just burn another full re-solve
                self._window_override = self._n_claims_override

        def should_stop() -> bool:
            # the device dispatch is atomic — the Solve deadline
            # (provisioner.go:415) is enforced between relaxation rounds
            return deadline is not None and now_fn() >= deadline

        prev_mode = self.reserved_mode
        if reserved_mode is not None:
            self.reserved_mode = reserved_mode
        try:
            result = prefs.run_with_relaxation(list(pods), solve_round, should_stop)
            if self._gang_device_class and (
                guard_config.lying("gang") or guard_config.should_audit("gang")
            ):
                result = self._audit_gang_solve(result, host_twin)
            return result
        except _GangHostRoute:
            # gangs + a constraint family the device gang kernel does not
            # cover (reservations, enforced minValues, multi-key vg
            # groups, hostname affinity, or a tripped "gang" quarantine):
            # the host oracle implements the identical all-or-nothing
            # semantics exactly
            return host_solve("gang_constraints")
        except DivergenceError:
            # the reference never aborts a Solve — a device/host decode
            # divergence re-solves the whole problem on the exact oracle
            # and records the event instead of failing provisioning
            return host_solve("divergence")
        except DispatchStallError as err:
            # the watchdog declared a solve section stalled — the device
            # dispatch (the collective-rendezvous deadlock class) or a
            # runaway host encode/decode: the stuck worker is leaked and
            # the stacks are already dumped — this solve completes on the
            # host oracle instead of hanging the provisioner, under a
            # per-section degradation rung
            return host_solve(f"watchdog_{err.section}")
        except Exception as err:  # noqa: BLE001 — the degradation ladder
            # device dispatch / decode blowing up (an XLA abort, a device
            # gone bad, an injected solver.dispatch fault) must not fail
            # the provisioning loop: the host oracle is authoritative for
            # the identical problem, so degrade THIS solve to it, logged
            # and counted. A host-oracle failure propagates — there is no
            # rung below the oracle.
            from karpenter_tpu.utils.logging import get_logger

            get_logger().with_values(controller="scheduler").warn(
                "device solve failed; degrading to host oracle",
                error=type(err).__name__,
                detail=str(err)[:200],
            )
            return host_solve("device_dispatch")
        finally:
            self.reserved_mode = prev_mode
            self._chunk_sink = None

    def _kind_sig(self, pod: Pod):
        """Canonical content signature for pod-kind dedup: the cached
        spec+labels+namespace signature (shared with ffd_sort, so identical
        pods are contiguous in the solve order) refined by the pod's
        volume-implied zone restriction. Two pods with equal signatures
        produce identical rows in every problem tensor, including topology
        ownership: groups are deduped by identity (`Topology._by_ident`),
        so content-identical declarers own the same group.
        """
        from karpenter_tpu.controllers.provisioning.host_scheduler import pod_content_sig

        alts = self._volume_reqs.get(pod.uid)
        vol_sig = (
            None
            if not alts
            else tuple(
                tuple(
                    (r.key, r.complement, tuple(sorted(r.values)), r.gte, r.lte)
                    for r in sorted(a.values(), key=lambda r: r.key)
                )
                for a in alts
            )
        )
        return (pod_content_sig(pod), vol_sig)

    def _pod_reqs(self, pod: Pod) -> Requirements:
        """Full pod requirements + PVC-implied zone restriction (volume
        topology folds into the NODE side via the combine, not into strict
        requirements, so TSC counting ignores it — volumetopology.go)."""
        reqs = Requirements.from_pod(pod)
        alts = self._volume_reqs.get(pod.uid)
        if alts:
            # the device path only runs single-alternative problems (multi
            # routes to the host oracle in solve())
            reqs.add(*alts[0].values())
        return reqs

    def _solve_once(
        self,
        pods: Sequence[Pod],
        existing_nodes: Optional[list[ExistingSimNode]] = None,
        budgets: Optional[dict[str, dict[str, float]]] = None,
        topology: Optional[Topology] = None,
    ) -> SchedulingResult:
        import time as _time

        from karpenter_tpu.tracing.tracer import TRACER

        if self._chunk_sink is not None:
            # a fresh round invalidates every chunk streamed so far
            self._chunk_sink(("reset", None))
        self._t_solve_start = _time.perf_counter()
        self._adaptive_claims = True
        self._scan_stats = None
        self._shard_stats = None
        self._last_compact_rmin = None
        pad_real0 = dict(self._pad_cache.real)
        pad_padded0 = dict(self._pad_cache.padded)
        try:
            with TRACER.span("solve.encode", pods=len(pods)), _wfl.span("encode"):
                # host encode under its own watchdog section (STATUS
                # known gap: encode/decode stalls were not deadlined)
                pods_sorted, enc = run_guarded(
                    lambda: self._encode(pods, existing_nodes, budgets, topology),
                    section="encode",
                )
        finally:
            self._adaptive_claims = False
        _t_encode_done = _time.perf_counter()
        with TRACER.span(
            "solve.dispatch", n_claims=enc["n_claims"]
        ), _wfl.span("dispatch"):
            state, outputs, tmpl_snaps = self._run_solve(enc)
        # device sync points: the single-fetch path pays exactly one wire
        # round trip (over a tunneled TPU each costs ~70ms); the pipelined
        # path pays one per chunk group + a final state fetch, with all
        # but the drain hidden behind in-flight device compute
        self._t_fetch_done = None
        self._pipeline_stats = None
        with TRACER.span("solve.decode") as _dsp, _wfl.span("decode"):
            out = run_guarded(
                lambda: self._decode(pods_sorted, state, outputs, enc, tmpl_snaps),
                section="decode",
            )
            _dsp.set(claims=len(out.claims), unschedulable=len(out.unschedulable))
        _t_end = _time.perf_counter()
        # phase timings for profiling/bench (VERDICT: expose the device vs
        # host split so optimization work isn't flying blind). device_s
        # includes the result transfer (they are inseparable without an
        # extra ~70ms round trip); decode_s is pure host bookkeeping. On
        # the pipelined path device_s ends at the FIRST chunk fetch, so
        # decode_s absorbs the (hidden) later-chunk device time — the
        # honest per-chunk split lives under last_timings["pipeline"].
        _t_device_done = self._t_fetch_done or _t_encode_done
        self.last_timings = {
            "encode_s": _t_encode_done - self._t_solve_start,
            "device_s": _t_device_done - _t_encode_done,
            "decode_s": _t_end - _t_device_done,
        }
        # per-solve padded-vs-real element accounting (bench --report-padding)
        padding: dict = {}
        for kind, real in self._pad_cache.real.items():
            r = real - pad_real0.get(kind, 0)
            p = self._pad_cache.padded.get(kind, 0) - pad_padded0.get(kind, 0)
            if p:
                padding[kind] = {
                    "real": r, "padded": p,
                    "waste_frac": round(1.0 - r / p, 4),
                }
        if self._last_n_open is not None:
            padding["claims_axis"] = {
                "real": int(self._last_n_open),
                "padded": int(enc["n_claims"]),
                "waste_frac": round(
                    1.0 - self._last_n_open / max(enc["n_claims"], 1), 4
                ),
            }
        self.last_timings["padding"] = padding
        if self._scan_stats is not None:
            self.last_timings["scan"] = self._scan_stats
        if self._pipeline_stats is not None:
            self.last_timings["pipeline"] = self._pipeline_stats
        if self._shard_stats is not None:
            self._finalize_shard_stats(self._shard_stats)
            self.last_timings["shard"] = self._shard_stats
        wf = _wfl.current()
        if wf is not None:
            self.last_timings["waterfall"] = self._finalize_waterfall(wf)
        return out

    def _finalize_waterfall(self, wf) -> dict:
        """Reconcile the round waterfall and observe each segment
        self-time into ktpu_round_segment_seconds."""
        from karpenter_tpu.utils.metrics import ROUND_SEGMENT_SECONDS

        rec = wf.finalize()
        for seg, s in rec["segments"].items():
            ROUND_SEGMENT_SECONDS.observe(s, segment=seg)
        return rec

    def _finalize_shard_stats(self, stats: dict) -> None:
        """Roll the dp-row accounting of one meshed solve into the
        ktpu_shard_dp_utilization gauge and per-family speculation
        efficiency (committed-pod-seconds / dispatched-pod-seconds)."""
        from karpenter_tpu.utils.metrics import SHARD_DP_UTILIZATION

        tot = stats.get("dp_rows_total", 0)
        if tot:
            for k in ("committed", "replayed", "idle"):
                SHARD_DP_UTILIZATION.set(
                    stats.get(f"dp_rows_{k}", 0) / tot, state=k
                )
        eff = {}
        for fam, fs in (stats.get("families") or {}).items():
            disp = fs.get("dispatched_pod_s", 0.0)
            if disp > 0:
                fs["efficiency"] = round(
                    fs.get("committed_pod_s", 0.0) / disp, 4
                )
                eff[fam] = fs["efficiency"]
        if eff:
            stats["speculation_efficiency"] = eff

    def whatif_batch(
        self,
        pods: Sequence[Pod],
        existing_nodes: list[ExistingSimNode],
        budgets: Optional[dict[str, dict[str, float]]],
        scenarios: list[tuple[set, set, set]],
        topology_factory,
        volume_reqs: Optional[dict] = None,
        reserved_in_use: Optional[dict[str, int]] = None,
        bound_pods=None,  # data form for the RPC client; the in-process
        # engine seeds topology through topology_factory
        pod_volumes: Optional[dict] = None,
    ) -> Optional[list[tuple[bool, int]]]:
        """Batched disruption what-ifs: evaluate S candidate exclusion sets
        in ONE vmapped device dispatch instead of S sequential re-solves
        (the tensorized twin of multinodeconsolidation.go:136-183's
        per-prefix SimulateScheduling loop).

        pods is the UNION pod set (pending + every scenario's displaced
        pods); each scenario is (excluded_node_names, active_pod_uids,
        counted_pod_uids). The encoded problem is shared — only per-scenario
        validity masks and topology count seeds differ. Returns
        (feasible, n_new_claims) per scenario, where feasible means no
        counted pod went unscheduled.
        """
        import numpy as _np

        self._volume_reqs = normalize_volume_reqs(volume_reqs)
        # a NO_ROOM escalation from an interleaved solve() must not shrink
        # the what-if's claims axis — scenarios displace extra pods and can
        # need MORE slots than the last live solve (the what-if dispatch
        # itself always runs un-windowed: solve_whatif defaults window=0)
        self._n_claims_override = None
        self._window_override = None
        # CSI attach limits ride the batched path: displaced pods carry
        # their (driver, pvc) columns and surviving nodes keep their
        # attach-usage seeds (exist.vols) — the same tensorized check the
        # live solve runs
        self._pod_vols = pod_volumes or {}
        if any(len(alts) > 1 for alts in self._volume_reqs.values()):
            # multi-alternative volume topologies need the host's
            # try-each loop — decline, callers simulate sequentially
            return None
        if self._volume_reqs and existing_nodes:
            # same undefined-key parity guard as solve()
            vol_keys = {
                r.key for alts in self._volume_reqs.values() for a in alts for r in a.values()
            }
            if any(
                not n.requirements.has(k) for n in existing_nodes for k in vol_keys
            ):
                return None
        self._reserved_in_use = reserved_in_use or {}
        pods = list(pods)
        from karpenter_tpu.gang import is_gang_pod

        if any(is_gang_pod(p) for p in pods):
            # the per-pod what-if kernel has no gang atomicity — a partial
            # placement would read as feasible; callers fall back to the
            # sequential simulate, which solves gangs exactly
            return None
        topo0 = topology_factory(pods, scenarios[0][0])
        pods_sorted, enc = self._encode(
            pods, [n.clone() for n in existing_nodes], budgets, topo0
        )
        tt = enc["topo_tensors"]
        E = enc["E"]
        node_names = [n.name for n in self.existing_nodes]
        # materialize per-pod tensors from the kind-level encoding (the
        # union problem is small — pending + candidate pods only)
        P = enc["P"]
        P_pad = _next_pow2(max(P, 1), 1)
        kidx = _np.zeros(P_pad, dtype=_np.int64)
        kidx[:P] = enc["kind_of"][:P]
        pt, tol, it_allow, exist_ok, pod_ports, pod_port_conf, pod_vols, pod_topo = (
            self._materialize_pods(enc, kidx, P)
        )
        base_valid = _np.asarray(pt.valid)
        # Each scenario gathers its COMPACT pod list from the union encoding,
        # so the vmapped scan length is the largest scenario, not the union
        # size (singleton candidate what-ifs stay near-free even when the
        # union carries every candidate's pods). Both axes pad to powers of
        # two so repeated disruption polls share compiled executables.
        S = len(scenarios)
        S_pad = _next_pow2(S, 1)
        per_scenario_idx: list[list[int]] = []
        for excluded, active_uids, counted_uids in scenarios:
            per_scenario_idx.append(
                [
                    i
                    for i, p in enumerate(pods_sorted)
                    if base_valid[i] and p.uid in active_uids
                ]
            )
        L = _next_pow2(max((len(ix) for ix in per_scenario_idx), default=1), 1)
        idx = _np.zeros((S_pad, L), dtype=_np.int32)
        active = _np.zeros((S_pad, L), dtype=bool)
        pc = _np.zeros((S_pad, L), dtype=bool)
        ev = _np.ones((S_pad, E), dtype=bool)
        vg0 = _np.broadcast_to(
            _np.asarray(tt.vg_counts0), (S_pad,) + tt.vg_counts0.shape
        ).copy()
        hg0 = _np.broadcast_to(
            _np.asarray(tt.hg_counts0), (S_pad,) + tt.hg_counts0.shape
        ).copy()
        for s, (excluded, active_uids, counted_uids) in enumerate(scenarios):
            for e, name in enumerate(node_names):
                ev[s, e] = name not in excluded
            for j, i in enumerate(per_scenario_idx[s]):
                idx[s, j] = i
                active[s, j] = True
                pc[s, j] = pods_sorted[i].uid in counted_uids
            if s == 0:
                continue  # scenario 0's seeds are the encoded baseline
            topo_s = topology_factory(pods, excluded)
            for node in self.existing_nodes:
                topo_s.register(l.LABEL_HOSTNAME, node.name)
            counts = topo_ops.encode_topology_counts(
                topo_s, self.encoder, E, enc["n_claims"] + 1, node_names,
                tt.vg_counts0.shape[1], enc["vg_groups"], enc["hg_groups"],
            )
            if counts is None:
                # Group structure diverged across scenarios (inverse
                # anti-affinity groups derive from bound pods, which differ
                # per exclusion set): the shared encoding can't represent
                # every scenario — callers fall back to sequential simulation.
                return None
            vg0[s], hg0[s] = counts

        unsched, n_open = ops_solver.solve_whatif(
            jnp.asarray(idx),
            jnp.asarray(active),
            jnp.asarray(pc),
            jnp.asarray(ev),
            jnp.asarray(vg0),
            jnp.asarray(hg0),
            pt,
            tol,
            it_allow,
            exist_ok,
            pod_ports,
            pod_port_conf,
            pod_vols,
            enc["exist_tensors"],
            self.it_tensors,
            enc["template_tensors"],
            self.well_known,
            tt,
            pod_topo,
            zone_kid=enc["zone_kid"],
            ct_kid=enc["ct_kid"],
            n_claims=enc["n_claims"],
            mv_active=self._mv_active and self.min_values_policy != "BestEffort",
            topo_kids=enc["topo_kids"],
            res_cap0=self._res_cap0,
            rid_kid=self._rid_kid,
            res_vid=self._res_vid,
            res_active=self._res_active,
            res_strict=self.reserved_mode == "strict",
        )
        unsched = _np.asarray(unsched)
        n_open = _np.asarray(n_open)
        return [(int(unsched[s]) == 0, int(n_open[s])) for s in range(S)]

    def _kind_bundles(self, reps: list) -> tuple[list, list]:
        """Assemble per-kind encode bundles (reqs/strict/requests/it_allow/
        tol rows) through the incremental encode cache (KTPU_ENCODE_CACHE).

        Every row is a pure function of kind content and the encode epoch
        (vocab + pads + catalog + templates), so steady-state repeat solves
        — and ResidentSession delta rounds, which encode ONLY arrived kinds
        — assemble cached numpy rows instead of re-walking requirement
        objects. Returns (bundles, rep_req_sets): rep_req_sets[u] is the
        rebuilt Requirements for cache misses (None on hits; callers that
        need it rebuild lazily via _pod_reqs)."""
        U = len(reps)
        k_pad, v_pad = self._pads()
        epoch = (
            self._vocab_sig, k_pad, v_pad, self._T_pad, len(self.templates)
        )
        cache = None
        # a quarantined encode cache is bypassed outright: every kind
        # re-encodes from requirement objects (the exact path) until TTL
        if self.encode_cache_enabled and not QUARANTINE.active("encode_cache"):
            if self._encode_cache_key != epoch:
                self._encode_cache = {}
                self._encode_cache_key = epoch
            elif len(self._encode_cache) > 8192:
                # churning workloads can't pin rows forever
                self._encode_cache.clear()
            cache = self._encode_cache
        bundles: list = [None] * U
        rep_sigs = None
        if cache is not None:
            rep_sigs = [self._kind_sig(p) for p in reps]
            for u in range(U):
                bundles[u] = cache.get(rep_sigs[u])
        n_hits = sum(b is not None for b in bundles)
        miss = [u for u in range(U) if bundles[u] is None]
        rep_req_sets: list = [None] * U
        if miss:
            miss_bundles, miss_reqs = self._encode_kind_rows(
                [reps[u] for u in miss]
            )
            for j, u in enumerate(miss):
                rep_req_sets[u] = miss_reqs[j]
                bundles[u] = miss_bundles[j]
                if cache is not None:
                    cache[rep_sigs[u]] = miss_bundles[j]
        if n_hits:
            from karpenter_tpu.utils.metrics import ENCODE_CACHE_HITS

            ENCODE_CACHE_HITS.inc(n_hits)
            if guard_config.should_audit("encode_cache"):
                hit_idx = [u for u in range(U) if u not in set(miss)]
                bundles = self._audit_encode_cache(reps, bundles, hit_idx)
        return bundles, rep_req_sets

    def _encode_kind_rows(self, reps_sub: list) -> tuple[list, list]:
        """Encode per-kind bundle rows from requirement objects (the
        encode-cache miss path, shared with the cache's shadow audit).
        Returns (bundles, req_sets) aligned with ``reps_sub``."""
        from karpenter_tpu.ops.encode import encode_requirements_np

        k_pad, v_pad = self._pads()
        row_memo: dict = {}
        sub_reqs = [self._pod_reqs(p) for p in reps_sub]
        m_enc = encode_requirements_np(
            self.encoder.vocab, sub_reqs, k_pad, v_pad,
            self.encoder.skip_keys, row_memo=row_memo,
        )
        m_strict = encode_requirements_np(
            self.encoder.vocab,
            [
                Requirements.from_pod(p, include_preferred=False)
                for p in reps_sub
            ],
            k_pad, v_pad, self.encoder.skip_keys, row_memo=row_memo,
        )
        m_allow = self.encoder.it_allow_mask(sub_reqs, self.catalog)
        if m_allow.shape[1] != self._T_pad:  # sharded catalog padding
            m_allow = np.pad(
                m_allow,
                ((0, 0), (0, self._T_pad - m_allow.shape[1])),
                constant_values=False,
            )
        bundles = []
        for j, p in enumerate(reps_sub):
            # hostname selectors can never match a not-yet-named node
            if not self.encoder.hostname_allows(sub_reqs[j], None):
                m_allow[j, :] = False
            bundles.append(
                dict(
                    reqs=tuple(a[j] for a in m_enc),
                    strict=tuple(a[j] for a in m_strict),
                    requests=self.encoder.resources_vector(p.total_requests()),
                    it_allow=m_allow[j],
                    tol=np.array(
                        [
                            tolerates_all(t.taints, p.spec.tolerations) is None
                            for t in self.templates
                        ],
                        dtype=bool,
                    ),
                )
            )
        return bundles, sub_reqs

    @staticmethod
    def _encode_rows_equal(a: dict, b: dict) -> bool:
        for i in range(6):
            if not np.array_equal(np.asarray(a["reqs"][i]), np.asarray(b["reqs"][i])):
                return False
            if not np.array_equal(
                np.asarray(a["strict"][i]), np.asarray(b["strict"][i])
            ):
                return False
        return (
            np.array_equal(np.asarray(a["requests"]), np.asarray(b["requests"]))
            and np.array_equal(np.asarray(a["it_allow"]), np.asarray(b["it_allow"]))
            and np.array_equal(np.asarray(a["tol"]), np.asarray(b["tol"]))
        )

    def _audit_encode_cache(self, reps: list, bundles: list, hit_idx: list):
        """Shadow audit of encode-cache hits: re-encode the hit kinds from
        their requirement objects (the exact twin) and compare every row
        bit-exact. On divergence the fresh rows are the ones used."""
        if not hit_idx:
            return bundles
        fresh, _ = self._encode_kind_rows([reps[u] for u in hit_idx])
        lying = guard_config.lying("encode_cache")
        bad = []
        for j, u in enumerate(hit_idx):
            cmp = bundles[u]
            if lying:  # seeded lying-fast-path fixture
                cmp = dict(cmp, requests=np.asarray(cmp["requests"]) + 1.0)
            if not self._encode_rows_equal(cmp, fresh[j]):
                bad.append(u)
        if not bad:
            guard_audit.record_audit("encode_cache", "pass")
            return bundles
        pods_by_uid, rounds, existing = self._guard_problem_ctx()
        guard_audit.handle_divergence(
            "encode_cache",
            f"{len(bad)} cached encode row(s) != fresh re-encode",
            self,
            pods_by_uid,
            rounds,
            existing,
            detail={"hits_audited": len(hit_idx), "divergent_rows": len(bad)},
        )
        self._encode_cache = {}  # drop the poisoned rows, not just bypass
        out = list(bundles)
        for j, u in enumerate(hit_idx):
            out[u] = fresh[j]
        return out

    @staticmethod
    def _stack_bundles(bundles: list):
        """Stack per-kind bundle rows into the kind-axis problem tensors
        (reqs, strict, requests, it_allow, tol)."""
        from karpenter_tpu.ops.encode import ReqSetTensors as _RST

        reqs_k = _RST(
            *(jnp.asarray(np.stack([b["reqs"][i] for b in bundles])) for i in range(6))
        )
        strict_reqs_k = _RST(
            *(jnp.asarray(np.stack([b["strict"][i] for b in bundles])) for i in range(6))
        )
        it_allow_k = np.stack([b["it_allow"] for b in bundles])
        requests_k = np.stack([b["requests"] for b in bundles])
        tol_k = np.stack([b["tol"] for b in bundles])
        return reqs_k, strict_reqs_k, requests_k, it_allow_k, tol_k

    def _exist_ok_rows(
        self, reps: list, rep_req_sets: list, nodes: list, e_pad: int
    ) -> np.ndarray:
        """[U, e_pad] static pod-kind × existing-node checks (taints +
        skipped-key hostname/instance-type selectors) against the PRISTINE
        input nodes — node-dependent, never cached."""
        U = len(reps)
        exist_ok_k = np.zeros((U, e_pad), dtype=bool)
        if nodes:
            for u in range(U):
                if rep_req_sets[u] is None:
                    rep_req_sets[u] = self._pod_reqs(reps[u])
        for e, n in enumerate(nodes):
            hostname = n.requirements.get(l.LABEL_HOSTNAME).any_value() or None
            it_name = (
                n.requirements.get(l.LABEL_INSTANCE_TYPE).any_value() or None
                if n.requirements.has(l.LABEL_INSTANCE_TYPE)
                else None
            )
            for u, p in enumerate(reps):
                rq = rep_req_sets[u]
                ok = tolerates_all(n.taints, p.spec.tolerations) is None
                ok = ok and self.encoder.hostname_allows(rq, hostname)
                if ok and rq.has(l.LABEL_INSTANCE_TYPE):
                    r = rq.get(l.LABEL_INSTANCE_TYPE)
                    ok = r.has(it_name) if it_name is not None else r.is_lenient()
                exist_ok_k[u, e] = ok
        return exist_ok_k

    def _encode(
        self,
        pods: Sequence[Pod],
        existing_nodes: Optional[list[ExistingSimNode]] = None,
        budgets: Optional[dict[str, dict[str, float]]] = None,
        topology: Optional[Topology] = None,
    ) -> tuple[list[Pod], dict]:
        """Encode one problem into solver tensors (everything _run_solve
        needs); shared by the provisioning solve and the batched what-if
        path, which re-masks the same encoding per scenario."""
        self.existing_nodes = existing_nodes or []
        self.budgets = {k: dict(v) for k, v in (budgets or {}).items()}
        if topology is None:
            # lazy universe: Topology.build's topology-free fast path skips
            # domain-universe construction entirely (the selector-only
            # north star never pays the existing-node requirement sweep)
            topology = Topology.build(
                list(pods),
                lambda: build_universe_domains(
                    self.templates, self.existing_nodes, template_base=self.universe_base()
                ),
            )
        self.topology = topology
        if topology.groups or topology.inverse_groups:
            for node in self.existing_nodes:
                topology.register(l.LABEL_HOSTNAME, node.name)
        # topology keys/domains must be in the vocab before pads freeze
        for g in topology.groups + topology.inverse_groups:
            if g.key in self.encoder.skip_keys:
                continue
            self.encoder.vocab.add_key(g.key)
            for d in g.domains:
                self.encoder.vocab.add_value(g.key, d)
        # ---- FFD sort + pod-kind dedup (one fused pass) ---------------------
        # Every per-pod encoding below is a pure function of pod CONTENT
        # (spec + labels + volume restriction), so it is computed once per
        # distinct kind and gathered per pod. Real workloads are
        # deployment-shaped (P >> kinds), which turns the O(P) python
        # encode loops into O(kinds) + device gathers — and the FFD order
        # groups identical kinds contiguously, so each run of identical
        # pods is ONE segment for the kind-level batch placement path.
        #
        # The sort and the dedup share ONE signature pass: interned content
        # sigs + size keys collect into arrays, np.lexsort orders them
        # (identical to host_scheduler.ffd_sort — both sorts are stable on
        # the same keys), and np.unique factorizes kinds. The volume-
        # restricted case (rare; multi-alternative routes to the host
        # anyway) refines kinds with the per-pod volume signature.
        # ---- gang partition: gangs solve FIRST as all-or-nothing units ----
        # Complete gangs form a rank-ordered prefix (largest slice first —
        # the shared order_gangs rule the host oracle uses too); incomplete
        # and invalid gangs never enter the solve and surface as
        # pre-decided unschedulable entries (the orchestration layer's
        # GangWaitTracker normally holds stragglers back before this).
        from karpenter_tpu import gang as gang_mod

        pods_all = list(pods)
        gangs_g, singles_list, invalid_g = gang_mod.collect_gangs(pods_all)
        pre_unsched: list = list(invalid_g)
        gang_prefix: list = []
        gang_bounds: list = []  # (lo, hi, gang key) within the prefix
        for g in gang_mod.order_gangs(gangs_g):
            if not g.complete:
                pre_unsched.extend(
                    (p, gang_mod.GANG_WAITING_REASON) for p in g.pods_in_rank_order()
                )
                continue
            lo_g = len(gang_prefix)
            gang_prefix.extend(g.pods_in_rank_order())
            gang_bounds.append((lo_g, len(gang_prefix), g.key))
        pods_list = gang_prefix + singles_list
        n_gang = len(gang_prefix)
        P = len(pods_list)
        cap = self.max_claims or _next_pow2(max(P, 1))
        if self._capture and not self.max_claims:
            # resident-session base solves need claim-axis headroom: delta
            # rounds append into THIS state's global claim space, and a
            # NO_ROOM there costs a full re-solve (the axis is a perf
            # knob, not a semantic one — results are axis-independent)
            cap *= 2
        if self._n_claims_override:
            n_claims = self._n_claims_override
        elif self._adaptive_claims and self._last_n_open is not None:
            # steady-state: a 256-bucket above last solve's need (25% + 32
            # headroom); NO_ROOM recovery escalates when the guess is low
            need = int(self._last_n_open * 1.25) + 32
            n_claims = min(cap, max(512, -(-need // 256) * 256))
        else:
            n_claims = cap
        self._last_n_claims = n_claims
        # active window: bounded hot claims axis within the global claim
        # space [0, n_claims). Cold solves keep the full axis; warm solves
        # shrink to a bucket above the live high-water (compaction keeps
        # residency near the live set); spills escalate via solve_round.
        if self._window_override:
            window = min(self._window_override, n_claims)
        elif self.scan_window > 0:
            window = min(self.scan_window, n_claims)
        elif self._adaptive_claims and self._last_w_hw is not None:
            w_need = int(self._last_w_hw * 1.25) + 32
            window = min(n_claims, max(256, -(-w_need // 256) * 256))
        else:
            window = n_claims
        self._last_window = window
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            gather_ffd_keys,
        )

        sig = np.empty(max(P, 1), dtype=np.int64)
        sizes = np.empty(max(P, 1), dtype=np.float64)
        sig[:] = 0
        sizes[:] = 0.0
        if self._volume_reqs:
            vol_ids: dict = {}
            for i, p in enumerate(pods_list):
                s = self._kind_sig(p)
                sig[i] = vol_ids.setdefault(s, len(vol_ids))
                req = p.spec.requests
                sizes[i] = req.get(res.CPU, 0.0) + req.get(res.MEMORY, 0.0) / (4.0 * 2**30)
        else:
            gather_ffd_keys(pods_list, sig, sizes)
        # each gang is its OWN kind (negative sig ids never collide with
        # interned content sigs), so every gang is exactly one contiguous
        # scan segment and cross-gang kind merging cannot happen
        for gi, (lo_g, hi_g, _key) in enumerate(gang_bounds):
            sig[lo_g:hi_g] = -(gi + 1)
        if P:
            if n_gang:
                # the gang prefix keeps its order; only singletons FFD-sort
                s_sig = sig[n_gang:P]
                s_sizes = sizes[n_gang:P]
                _, first0, inv0 = np.unique(s_sig, return_index=True, return_inverse=True)
                ranks = np.argsort(np.argsort(first0))[inv0]
                order_s = np.lexsort((ranks, -s_sizes))
                order = np.concatenate(
                    [np.arange(n_gang, dtype=np.int64), n_gang + order_s]
                )
            else:
                # first-appearance rank in ORIGINAL order = ffd_sort's tie key
                _, first0, inv0 = np.unique(sig[:P], return_index=True, return_inverse=True)
                ranks = np.argsort(np.argsort(first0))[inv0]
                order = np.lexsort((ranks, -sizes[:P]))
            pods_sorted = [pods_list[i] for i in order]
            # kind ids numbered by first appearance in the SORTED sequence
            sig_sorted = sig[:P][order]
            _, first1, inv1 = np.unique(sig_sorted, return_index=True, return_inverse=True)
            r1 = np.argsort(np.argsort(first1))
            kind_of = r1[inv1]
            reps = [pods_sorted[int(first1[u])] for u in np.argsort(r1)]
        else:
            pods_sorted = []
            kind_of = np.zeros(1, dtype=np.int64)
            reps = [Pod()]  # degenerate empty solve
        # kind -> gang key for the gang prefix (prefix positions survive the
        # sort untouched, so kind_of[lo] is the gang's kind id)
        gang_key_of_kind: dict[int, str] = {
            int(kind_of[lo_g]): key for lo_g, _hi, key in gang_bounds
        }

        for p in reps:
            self.encoder.observe_pod(p)
            for alt in self._volume_reqs.get(p.uid) or ():
                for r in alt.values():
                    self.encoder.vocab.add_key(r.key)
                    for v in r.values:
                        self.encoder.vocab.add_value(r.key, v)
        for n in self.existing_nodes:
            self.encoder.observe_requirements(n.requirements)
            self.encoder.observe_resources(n.available)
        if self._vocab_sig != self._sig():
            self._encode_static()
        # per-solve reservation capacities: current catalog counts (the
        # provider decrements on launch) minus ids pinned by in-flight
        # claims the provider hasn't launched yet
        RID = self.it_tensors.res_ofs.shape[1]
        cap0 = np.zeros(RID, dtype=np.int32)
        if self._rid_names:
            from karpenter_tpu.scheduling.reservations import ReservationManager

            rm = ReservationManager(self.catalog)
            for i, rid in enumerate(self._rid_names):
                cap0[i] = rm.capacity.get(rid, 0)
            for rid, n in (self._reserved_in_use or {}).items():
                if rid in self._rid_names:
                    i = self._rid_names.index(rid)
                    cap0[i] = max(cap0[i] - n, 0)
        self._res_cap0 = jnp.asarray(cap0)
        exist_tensors = self._encode_existing(_next_pow2(max(len(self.existing_nodes), 1), 1))
        budget, nodes_budget = self._encode_budgets()
        template_tensors = self.template_tensors._replace(
            budget=budget, nodes_budget=nodes_budget
        )
        # placement objective: resolve per solve (quarantine-aware — a
        # tripped "objective" guard path reverts to lexical) and ride the
        # policy's canonical template rank on every engine's tier-3 pick;
        # lexical materializes NO rank column and stays bit-identical
        from karpenter_tpu import objectives

        self._active_policy = objectives.active_policy(self.objective)
        if self._active_policy != "lexical":
            template_tensors = template_tensors._replace(
                rank=self._objective_rank(self._active_policy)
            )

        U = len(reps)
        k_pad, v_pad = self._pads()
        bundles, rep_req_sets = self._kind_bundles(reps)
        reqs_k, strict_reqs_k, requests_k, it_allow_k, tol_k = (
            self._stack_bundles(bundles)
        )
        # static pod×existing-node checks for the skipped keys + taints
        # (node-dependent: never cached; the Requirements rebuild only
        # runs when existing nodes are present)
        E = exist_tensors.avail.shape[0]
        exist_ok_k = self._exist_ok_rows(reps, rep_req_sets, self.existing_nodes, E)
        # topology tensors (counts + per-kind group relations); the hostname
        # slot space gets one spare column so tier-3's fresh-slot read stays
        # in bounds when every claim slot is open
        # v_pad passed through so the topology-free fast path caches its
        # empty tensors at the final width (pad_to_v becomes a no-op)
        topo_tensors, vg, hg = topo_ops.encode_topology(
            self.topology,
            self.encoder,
            E,
            n_claims + 1,
            [n.name for n in self.existing_nodes],
            v_pad=v_pad,
        )
        topo_tensors = topo_ops.pad_to_v(topo_tensors, v_pad)
        pod_topo_k, pod_topo_host = topo_ops.encode_pod_topology(
            self.topology, vg, hg, reps, strict_reqs_k
        )
        # (the [U, G] toleration matrix rides the per-kind encode bundles)

        # host-port vocabulary + wildcard-expanded conflict masks
        from karpenter_tpu.scheduling import hostports as hostports_mod

        port_keys: list[tuple] = []
        port_index: dict[tuple, int] = {}

        def port_id(key: tuple) -> int:
            if key not in port_index:
                port_index[key] = len(port_keys)
                port_keys.append(key)
            return port_index[key]

        for n in self.existing_nodes:
            for key in n.host_ports:
                port_id(key)
        for p in reps:
            for h in p.spec.host_ports:
                port_id(hostports_mod.port_key(h))
        NP = max(len(port_keys), 1)
        pod_ports_k = np.zeros((U, NP), dtype=bool)
        pod_port_conf_k = np.zeros((U, NP), dtype=bool)
        for u, p in enumerate(reps):
            for h in p.spec.host_ports:
                ip, port, proto = hostports_mod.port_key(h)
                pod_ports_k[u, port_index[(ip, port, proto)]] = True
                for j, (jip, jport, jproto) in enumerate(port_keys):
                    if port == jport and proto == jproto and (
                        ip == hostports_mod.WILDCARD_IP
                        or jip == hostports_mod.WILDCARD_IP
                        or ip == jip
                    ):
                        pod_port_conf_k[u, j] = True
        exist_ports0 = np.zeros((E, NP), dtype=bool)
        for e, n in enumerate(self.existing_nodes):
            for key in n.host_ports:
                exist_ports0[e, port_index[key]] = True
        # bitset packing: port columns ride as uint32 lanes so the per-step
        # conflict tests are fused bitwise ops (kernels.packed_conflict)
        from karpenter_tpu.ops.kernels import pack_bool_np

        pod_ports_k = pack_bool_np(pod_ports_k)
        pod_port_conf_k = pack_bool_np(pod_port_conf_k)
        exist_tensors = exist_tensors._replace(
            ports=jnp.asarray(pack_bool_np(exist_ports0))
        )

        # ---- CSI attach limits (volumeusage.go:187-229) --------------------
        # A (driver, pvc) column vocabulary shared by node usage and pod
        # volumes; distinct-PVC counting is a per-driver popcount over the
        # union mask. Active only when some node publishes limits AND some
        # pod carries volumes — otherwise the inert 1x1 tensors keep the
        # common hot path's compile shapes unchanged.
        limited = any(
            n.volume_usage is not None and n.volume_usage.limits
            for n in self.existing_nodes
        )
        pod_vols_map = self._pod_vols if limited else {}
        if pod_vols_map and any(pod_vols_map.get(p.uid) for p in reps):
            # only drivers SOME node caps get columns: unlimited drivers
            # always compare against +inf, so their PVCs would inflate NV
            # (and the per-step [E,NV]x[NV,ND] einsum) for nothing
            limited_drivers = {
                driver
                for n in self.existing_nodes
                if n.volume_usage is not None
                for driver in n.volume_usage.limits
            }
            col_index: dict[tuple, int] = {}
            drv_index: dict[str, int] = {}

            def vol_col(driver: str, pvc: str) -> "int | None":
                if driver not in limited_drivers:
                    return None
                drv_index.setdefault(driver, len(drv_index))
                return col_index.setdefault((driver, pvc), len(col_index))

            for n in self.existing_nodes:
                vu = n.volume_usage
                if vu is None:
                    continue
                for driver in vu.limits:
                    drv_index.setdefault(driver, len(drv_index))
                for vols in vu.pod_volumes.values():
                    for driver, pvcs in vols.items():
                        for pvc in pvcs:
                            vol_col(driver, pvc)
            for p in reps:
                for driver, pvcs in (pod_vols_map.get(p.uid) or {}).items():
                    for pvc in pvcs:
                        vol_col(driver, pvc)
            # one extra MARKER column (no driver: contributes to no count)
            # flags "this pod carries volumes" even when they all belong to
            # unlimited drivers — the host rejects ANY volume-carrying pod
            # on a node over a shrunk cap (exceedsLimits unions the node's
            # resident volumes), so the check must RUN for those pods
            marker = len(col_index)
            NV = _next_pow2(max(len(col_index) + 1, 1), 1)
            ND = _next_pow2(max(len(drv_index), 1), 1)
            vol_driver0 = np.zeros((NV, ND), dtype=bool)
            for (driver, _pvc), c in col_index.items():
                vol_driver0[c, drv_index[driver]] = True
            exist_vols0 = np.zeros((E, NV), dtype=bool)
            vol_limits0 = np.full((E, ND), np.inf, dtype=np.float32)
            for e, n in enumerate(self.existing_nodes):
                vu = n.volume_usage
                if vu is None:
                    continue
                for driver, cap in vu.limits.items():
                    vol_limits0[e, drv_index[driver]] = float(cap)
                for vols in vu.pod_volumes.values():
                    for driver, pvcs in vols.items():
                        for pvc in pvcs:
                            c = col_index.get((driver, pvc))
                            if c is not None:
                                exist_vols0[e, c] = True
            pod_vols_k = np.zeros((U, NV), dtype=bool)
            for u, p in enumerate(reps):
                vols = pod_vols_map.get(p.uid)
                if vols:
                    pod_vols_k[u, marker] = True
                for driver, pvcs in (vols or {}).items():
                    for pvc in pvcs:
                        c = col_index.get((driver, pvc))
                        if c is not None:
                            pod_vols_k[u, c] = True
        else:
            NV, ND = 1, 1
            vol_driver0 = np.zeros((1, 1), dtype=bool)
            exist_vols0 = np.zeros((E, 1), dtype=bool)
            vol_limits0 = np.full((E, 1), np.inf, dtype=np.float32)
            pod_vols_k = np.zeros((U, 1), dtype=bool)
        # volume bitsets pack like ports; vol_driver becomes a per-driver
        # packed column mask ([ND, NVp]) for the popcount distinct-PVC count
        pod_vols_k = pack_bool_np(pod_vols_k)
        exist_tensors = exist_tensors._replace(
            vols=jnp.asarray(pack_bool_np(exist_vols0)),
            vol_limits=jnp.asarray(vol_limits0),
            vol_driver=jnp.asarray(pack_bool_np(vol_driver0.T)),
        )

        zone_kid, ct_kid = self.encoder.zone_ct_key_ids()
        # static set of vocab keys topology groups narrow — the solver
        # handles these with exact per-key corrections so topology-mixed
        # workloads stay on the fast incremental tier-2 path
        # host-side: the group list IS the source vg_key/vg_valid were
        # built from (encode_topology), and each device read costs a
        # ~100ms round trip over a tunneled TPU
        topo_kids = tuple(
            sorted({self.encoder.vocab.key_to_id[g.key] for g in vg})
        )

        # ---- segments + kind batchability ---------------------------------
        # A kind rides the kind-level batch-fill scan unless it interacts
        # with vocab-key topology (per-placement requirement narrowing),
        # enforced minValues, reservations, finite pool budgets, or an
        # initially-empty hostname-affinity group (bootstrap is ordered).
        segments: list[tuple[int, int, int]] = []
        if P:
            ko = kind_of[:P]
            starts = np.concatenate(([0], np.flatnonzero(ko[1:] != ko[:-1]) + 1))
            ends = np.concatenate((starts[1:], [P]))
            segments = [
                (int(lo), int(hi), int(ko[lo])) for lo, hi in zip(starts, ends)
            ]
        vga_np = pod_topo_host["vga"]
        vgr_np = pod_topo_host["vgr"]
        hga_np = pod_topo_host["hga"]
        hgr_np = pod_topo_host["hgr"]
        from karpenter_tpu.controllers.provisioning.topology import TopologyType

        empty_aff = np.zeros(hga_np.shape[1], dtype=bool)
        for j, g in enumerate(hg):
            if g.type is TopologyType.AFFINITY and g.is_empty():
                empty_aff[j] = True
        allow_fill = (
            not (self._mv_active and self.min_values_policy != "BestEffort")
            and not self._res_active
            and not any(v for v in self.budgets.values())
        )
        batchable = np.zeros(U, dtype=bool)
        if allow_fill:
            for u in range(U):
                batchable[u] = (
                    not vga_np[u].any()
                    and not vgr_np[u].any()
                    and not (hga_np[u] & empty_aff).any()
                )
        # gang kinds ride the gang-atomic kernel only. Since ISSUE 20
        # rung 2 the routed class covers finite budgets (per-block
        # subtractMax debits), vocab-key topology whose applying/recording
        # groups unify to ONE narrow key per gang kind (the rank-block
        # loop runs the kscan _vg_eval narrowing), and hostname-SPREAD
        # groups (hg_evaluate at each block's fresh slot). Enforced
        # minValues, reservations, hostname affinity/anti-affinity, and
        # non-unifiable vg keys still degrade the whole solve to the host
        # oracle, which implements identical all-or-nothing semantics
        # exactly. gang_vg_key[u] is the kind's unified key (-1 = no vg
        # interaction); same-key gang runs dispatch together.
        gang_kind = np.zeros(U, dtype=bool)
        for k in gang_key_of_kind:
            gang_kind[k] = True
        gang_vg_key = np.full(U, -1, dtype=np.int64)
        if gang_bounds:
            mv_block = self._mv_active and self.min_values_policy != "BestEffort"
            vkeys_all = [self.encoder.vocab.key_to_id[g.key] for g in vg]
            host_why = None
            if mv_block:
                host_why = "gang under enforced minValues"
            elif self._res_active:
                host_why = "gang under reservations"
            for u in np.flatnonzero(gang_kind):
                if host_why:
                    break
                js = [
                    j
                    for j in range(len(vg))
                    if vga_np[u, j] or vgr_np[u, j]
                ]
                keys = {vkeys_all[j] for j in js}
                if len(keys) > 1:
                    host_why = "gang vg groups span multiple vocab keys"
                elif keys:
                    kid_ = next(iter(keys))
                    if len(self.encoder.vocab.values[kid_]) > ops_solver.KSCAN_D:
                        host_why = "gang vg key wider than KSCAN_D"
                    else:
                        gang_vg_key[u] = kid_
                for j in np.flatnonzero(hga_np[u] | hgr_np[u]):
                    if hg[j].type is not TopologyType.SPREAD:
                        host_why = "gang hostname affinity/anti-affinity"
                        break
            # the constraint-bearing device class (gang × vg topology /
            # hostname-spread / finite budgets) is guarded: a tripped
            # "gang" quarantine routes it back onto the host oracle (its
            # exact twin) until TTL expiry; the legacy topology-free
            # infinite-budget class predates the guard and stays
            new_class = bool(
                (gang_vg_key >= 0).any()
                or (hga_np[gang_kind] | hgr_np[gang_kind]).any()
                or any(v for v in self.budgets.values())
            )
            if not host_why and new_class:
                self._gang_device_class = True
                if QUARANTINE.active("gang"):
                    host_why = "gang device path quarantined"
            if host_why:
                raise _GangHostRoute(host_why)
        batchable[gang_kind] = False
        # vg-topology kinds whose every applying/recording group shares ONE
        # narrow vocab key ride the same-kind batched scan instead of the
        # per-pod scan (ops/solver.py solve_kind_scan — the reference
        # benchmark's zonal TSC / zone-affinity fifths are exactly this
        # shape); -1 = ineligible, stay per-pod
        kscan_key = np.full(U, -1, dtype=np.int64)
        if allow_fill and vg:
            vkeys = [self.encoder.vocab.key_to_id[g.key] for g in vg]
            for u in range(U):
                if batchable[u]:
                    continue
                js = [
                    j
                    for j in range(len(vg))
                    if vga_np[u, j] or vgr_np[u, j]
                ]
                keys = {vkeys[j] for j in js}
                if len(keys) != 1:
                    continue
                kid_ = next(iter(keys))
                if len(self.encoder.vocab.values[kid_]) <= ops_solver.KSCAN_D:
                    kscan_key[u] = kid_
        kind_records = hgr_np.any(axis=1)  # decode must commit topo counts
        # per-kind hostname-topology interaction: labels the topo_fill
        # speculation family in the shard coverage report (ISSUE 14)
        kind_hg = (hga_np | hgr_np).any(axis=1)

        # the [U, T] per-kind allow mask is the one encode output whose
        # trailing axis is the catalog: place it SHARDED over the mesh's
        # "it" axis at device_put time (replicate-then-constrain would
        # materialize the full copy per device first)
        from karpenter_tpu.ops.encode import place_sharded

        it_allow_dev = place_sharded(np.asarray(it_allow_k), self.mesh, None, "it")
        return pods_sorted, dict(
            reqs_k=reqs_k,
            strict_k=strict_reqs_k,
            requests_k=jnp.asarray(requests_k, dtype=jnp.float32),
            tol_k=jnp.asarray(tol_k),
            it_allow_k=it_allow_dev,
            exist_ok_k=jnp.asarray(exist_ok_k),
            ports_k=jnp.asarray(pod_ports_k),
            conf_k=jnp.asarray(pod_port_conf_k),
            vols_k=jnp.asarray(pod_vols_k),
            pod_topo_k=pod_topo_k,
            kind_of=kind_of,
            segments=segments,
            batchable=batchable,
            kscan_key=kscan_key,
            gang_kind=gang_kind,
            gang_vg_key=gang_vg_key,
            gang_key_of_kind=gang_key_of_kind,
            pre_unsched=pre_unsched,
            kind_records=kind_records,
            kind_hg=kind_hg,
            reps=reps,
            exist_tensors=exist_tensors,
            template_tensors=template_tensors,
            topo_tensors=topo_tensors,
            zone_kid=zone_kid,
            ct_kid=ct_kid,
            n_claims=n_claims,
            window=window,
            topo_kids=topo_kids,
            E=E,
            P=P,
            vg_groups=vg,
            hg_groups=hg,
        )

    def _materialize_pods(self, enc: dict, kind_idx: np.ndarray, n_valid: int):
        """Gather kind-level tensors into per-pod rows (one fused jitted
        device dispatch; nothing P-sized is built on the host). kind_idx is
        already padded to the dispatch length; rows beyond n_valid are
        masked invalid."""
        return _gather_pod_chunk(
            enc["reqs_k"], enc["strict_k"], enc["requests_k"], enc["tol_k"],
            enc["it_allow_k"], enc["exist_ok_k"], enc["ports_k"], enc["conf_k"],
            enc["vols_k"], enc["pod_topo_k"], jnp.asarray(kind_idx), n_valid,
        )

    def _run_solve(self, enc: dict):
        """Dispatch the solve as a host-sequenced run of device calls:
        batchable kind segments ride the kind-level fill scan (one step per
        KIND — the north-star path), vg-topology kinds ride the per-pod
        scan, with the SolverState threaded through every dispatch.

        Profiling: every dispatch runs under a jax.profiler trace
        annotation; set KTPU_PROFILE_DIR to capture a full device trace of
        one solve (the xprof analog of the reference's pprof handlers,
        operator.go:205-219)."""
        import os

        import jax

        from karpenter_tpu.faultinject import FAULT

        def _dispatch():
            # the chaos seam for the degradation ladder: an injected error
            # here is indistinguishable from the device dying mid-solve
            FAULT.point("solver.dispatch", pods=int(enc["P"]))
            profile_dir = os.environ.get("KTPU_PROFILE_DIR")
            ctx = (
                jax.profiler.trace(profile_dir)
                if profile_dir
                else jax.profiler.TraceAnnotation("ktpu_solve")
            )
            with ctx:
                if self.mesh is not None:
                    # GSPMD propagates the catalog's "it" sharding through
                    # the same jitted kernels; collectives ride ICI
                    # (SURVEY §2.9)
                    with self.mesh:
                        return self._run_solve_inner(enc)
                return self._run_solve_inner(enc)

        # KTPU_WATCHDOG_S bounds the whole dispatch sequence (including
        # every merge-loop block_until_ready — the rendezvous-deadlock
        # class); a stall raises DispatchStallError into the ladder
        return run_guarded(_dispatch, section="dispatch")

    def _run_solve_inner(self, enc: dict):
        exist_tensors = enc["exist_tensors"]
        template_tensors = enc["template_tensors"]
        topo_tensors = enc["topo_tensors"]
        n_claims = enc["n_claims"]
        batchable = enc["batchable"]
        kind_of = enc["kind_of"]
        chunk = self.solve_chunk
        common = dict(
            zone_kid=enc["zone_kid"],
            ct_kid=enc["ct_kid"],
            n_claims=n_claims,
            # BestEffort never enforces floors in-solve; achievable floors
            # are written back at decode (nodeclaim.go:606-613)
            mv_active=self._mv_active and self.min_values_policy != "BestEffort",
            topo_kids=enc["topo_kids"],
            rid_kid=self._rid_kid,
            res_vid=self._res_vid,
            res_active=self._res_active,
            res_strict=self.reserved_mode == "strict",
        )
        # per-shard observability (last_timings["shard"], bench
        # --report-shard): mesh extents + a replicated-bytes estimate over
        # the per-kind encode tensors that still broadcast to every device
        # (the sharded ones — catalog, [.., T] masks, window/bank columns —
        # are excluded by construction); the dp merge loop fills in the
        # round/commit counters
        if self.mesh is not None:
            ms = dict(self.mesh.shape)
            rep_bytes = 0
            for leaf in jax.tree_util.tree_leaves(
                [
                    enc["reqs_k"], enc["requests_k"], enc["tol_k"],
                    enc["exist_ok_k"], enc["ports_k"], enc["conf_k"],
                    enc["vols_k"],
                ]
            ):
                rep_bytes += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            self._shard_stats = {
                "dp": int(ms.get("dp", 1)),
                "it": int(ms.get("it", 1)),
                "merge_rounds": 0,
                "groups_committed": 0,
                "groups_replayed": 0,
                "group_pods": [],
                "replicated_bytes": int(rep_bytes),
                # one packed verdict word per merge round is the loop's
                # ONLY host sync (ISSUE 13): fetches == rounds, bytes =
                # uint32 lanes on the wire, sync_blocked_s = wall spent
                # waiting on commit decisions (merge_wall_s - blocked =
                # dispatch/decode overlap restored)
                "verdict_fetches": 0,
                "verdict_bytes": 0,
                # sync_blocked_s stays the sum (compat); the waterfall
                # needs the two phases split: verdict-word fetches vs
                # block_until_ready drains (the one-collective-in-flight
                # rule plus graft/replay completion waits)
                "sync_blocked_s": 0.0,
                "sync_verdict_s": 0.0,
                "sync_drain_s": 0.0,
                "merge_wall_s": 0.0,
                # dp-row utilization: every row of every merge round is
                # committed (grafted useful work), replayed (refused,
                # re-ran sequentially), or idle (dispatch padding)
                "dp_rows_total": 0,
                "dp_rows_committed": 0,
                "dp_rows_replayed": 0,
                "dp_rows_idle": 0,
                "families": {
                    f: {
                        "committed": 0, "replayed": 0,
                        # speculation efficiency numerator/denominator:
                        # pod-seconds weighted by each round's dispatch+
                        # drain wall (committed / dispatched -> ratio)
                        "committed_pod_s": 0.0, "dispatched_pod_s": 0.0,
                    }
                    for f in _SHARD_FAMILIES
                },
                # per-family chunk-group routing coverage (bench
                # --report-shard): dp = the group entered a speculative
                # fan-out round (commit OR replay), sequential = it never
                # left the plain ordered scan
                "coverage": {
                    f: {"dp": 0, "sequential": 0} for f in _SHARD_FAMILIES
                },
            }
            from karpenter_tpu.utils.metrics import SHARD_REPLICATED_BYTES

            SHARD_REPLICATED_BYTES.set(float(rep_bytes))
        else:
            self._shard_stats = None
        state = ops_solver.initial_state(
            exist_tensors, self.it_tensors, template_tensors, topo_tensors,
            n_claims, int(enc["ports_k"].shape[1]), self._res_cap0,
            window=enc["window"], topo_kids=enc["topo_kids"],
        )
        # group consecutive segments into maximal same-mode runs; kind-scan
        # runs additionally split per topology key (the key is a static
        # kernel argument)
        kscan_key = enc["kscan_key"]
        gang_kind = enc["gang_kind"]
        gang_vg_key = enc["gang_vg_key"]

        def _seg_mode(seg):
            k = seg[2]
            if gang_kind[k]:
                # gang runs additionally split per unified vg key (-1 =
                # no vg interaction) — the key is a static kernel argument
                return ("gang", int(gang_vg_key[k]))
            if batchable[k]:
                return ("fill",)
            if kscan_key[k] >= 0:
                return ("kscan", int(kscan_key[k]))
            return ("perpod",)

        runs: list[tuple[tuple, list]] = []
        for seg in enc["segments"]:
            m = _seg_mode(seg)
            if runs and runs[-1][0] == m:
                runs[-1][1].append(seg)
            else:
                runs.append((m, [seg]))
        # ---- software pipeline: split big fill runs into ~K chunks -------
        # Each sub-run is its own dispatch (state threaded -> bit-identical
        # to one scan) AND its own decode chunk group: while the device
        # runs chunk i+1, chunk i's outputs cross the wire and decode on
        # the host. Per-pod runs are already chunked by solve_chunk; kscan
        # runs keep exact-B shapes (splitting them would mint executables
        # per split size for little decode overlap).
        K_pipe = self._pipeline_target(enc)
        if K_pipe:
            target = max(-(-enc["P"] // K_pipe), 1)
            split: list[tuple[tuple, list]] = []
            for mode, segs in runs:
                if mode[0] != "fill" or len(segs) <= 1:
                    split.append((mode, segs))
                    continue
                cur: list = []
                cur_pods = 0
                for seg in segs:
                    cur.append(seg)
                    cur_pods += seg[1] - seg[0]
                    if cur_pods >= target:
                        split.append((mode, cur))
                        cur, cur_pods = [], 0
                if cur:
                    split.append((mode, cur))
            runs = split
            chunk = min(chunk, max(target, 256))
        from karpenter_tpu.tracing.tracer import TRACER

        _trace_on = TRACER.enabled
        # compaction bookkeeping: r_min over the pods a boundary has NOT
        # yet dispatched decides which resident claims are capacity-dead
        requests_np = np.asarray(enc["requests_k"], dtype=np.float32)
        remaining = np.zeros(requests_np.shape[0], dtype=np.int64)
        for _m, _segs in runs:
            for lo_, hi_, k_ in _segs:
                remaining[k_] += hi_ - lo_
        # Boundary compaction runs when the solve is windowed, and ALSO on
        # large un-windowed solves: eviction is what makes w_hw measure
        # TRUE residency, which the next warm solve's window sizing feeds
        # on (otherwise live == opens and the adaptive window could never
        # undercut the claims axis). Small solves skip it — the extra
        # dispatch + executable isn't worth a sub-second scan.
        window_active = (
            enc["window"] < n_claims or enc["P"] >= self.compact_min_pods
        )
        self._n_compactions = 0

        def _maybe_compact(st):
            if not window_active or not (remaining > 0).any():
                return st
            r_min = requests_np[remaining > 0].min(axis=0)
            prev = self._last_compact_rmin
            self._last_compact_rmin = (
                r_min if prev is None else np.maximum(prev, r_min)
            )
            st, _closed = ops_solver.compact_state(
                st, self.it_tensors, jnp.asarray(r_min), n_claims,
                topo_kids=enc["topo_kids"],
            )
            self._n_compactions += 1
            return st

        def _dispatch_fill(st, segs):
            """One sequential kind-level fill dispatch (shared by the
            plain path and the dp merge loop's replay rung)."""
            B = len(segs)
            # bucketed padding: multiple-of-8 up to 32, multiple-of-32
            # above (every padded row is a full fill step); the
            # PadBucketCache reuses a previously-compiled bucket when
            # one covers the request within the pow2 ceiling, so
            # steady-state shapes converge instead of recompiling
            B_pad = self._pad_cache.pad(
                "fill_segments", B, step=(8 if B <= 32 else 32)
            )
            kind_ids = np.zeros(B_pad, dtype=np.int64)
            counts = np.zeros(B_pad, dtype=np.int32)
            for j, (lo, hi, k) in enumerate(segs):
                kind_ids[j] = k
                counts[j] = hi - lo
            xs = _gather_fill_xs(
                enc["reqs_k"], enc["requests_k"], enc["tol_k"],
                enc["it_allow_k"], enc["exist_ok_k"], enc["ports_k"],
                enc["conf_k"], enc["vols_k"], enc["pod_topo_k"],
                jnp.asarray(kind_ids), jnp.asarray(counts),
            )
            return ops_solver.solve_fill(
                st, xs, exist_tensors, self.it_tensors, template_tensors,
                self.well_known, topo_tensors,
                zone_kid=enc["zone_kid"], ct_kid=enc["ct_kid"],
                n_claims=n_claims,
            )

        def _dispatch_kscan(st, segs, key, grid_audit=True):
            """One sequential kind-scan dispatch for vocab key `key`
            (shared by the plain path and the dp merge loop's replay and
            audit-twin rungs; the twin disables the nested grid audit —
            the speculative audit already compares full states).
            Exact B: a padded segment would run the full-width precompute
            for nothing (the inner loop already has a dynamic trip
            count); runs are small, so the executable variants stay few."""
            B = len(segs)
            kind_ids = np.zeros(B, dtype=np.int64)
            counts = np.zeros(B, dtype=np.int32)
            for j, (lo, hi, k) in enumerate(segs):
                kind_ids[j] = k
                counts[j] = hi - lo
            maxc = self._pad_cache.pad("kscan_cap", int(counts.max()), step=64)
            xs = _gather_kind_xs(
                enc["reqs_k"], enc["strict_k"], enc["requests_k"],
                enc["tol_k"], enc["it_allow_k"], enc["exist_ok_k"],
                enc["ports_k"], enc["conf_k"], enc["vols_k"],
                enc["pod_topo_k"], jnp.asarray(kind_ids),
                jnp.asarray(counts),
            )
            grid_inc = not QUARANTINE.active("grid")
            kscan_args = (
                xs, exist_tensors, self.it_tensors, template_tensors,
                self.well_known, topo_tensors,
            )
            kscan_kw = dict(
                zone_kid=enc["zone_kid"], ct_kid=enc["ct_kid"],
                n_claims=n_claims, key_kid=key,
                n_domains=len(self.encoder.vocab.values[key]),
                maxc=maxc,
            )
            st_in = st
            st, ys = ops_solver.solve_kind_scan(
                st, *kscan_args, grid_incremental=grid_inc, **kscan_kw
            )
            if grid_audit and grid_inc and guard_config.should_audit("grid"):
                st, ys = self._audit_kscan_grid(
                    st_in, st, ys, kscan_args, kscan_kw
                )
            return st, ys

        # ---- dp-sharded speculative fill (ISSUE 8) -----------------------
        # On a mesh whose dp axis has extent > 1, CONSECUTIVE pipelined
        # fill chunk groups become one "fill_dp" item: each merge round
        # batches up to DP groups into a single vmapped dispatch against
        # the committed state (one group per dp row) and commits them in
        # order — graft when provably independent, sequential replay
        # otherwise (see ops/solver.py dp section). Formerly the gate
        # required `no real existing nodes` and a topology-free problem;
        # ISSUE 14 folded both couplings into the verdict word as per-row
        # deltas with on-device disjointness proofs (existing-node debit
        # bit, hg record-vs-apply bit), so only the KTPU_SHARD_EXISTING
        # opt-out re-imposes the existing-node gate. The fill routing
        # itself already guarantees infinite budgets, no reservations and
        # no enforced minValues for batchable kinds.
        dp_n = 1
        if self.mesh is not None:
            dp_n = int(dict(self.mesh.shape).get("dp", 1))

        def _dp_block_reason(family_flag: bool, optout: str) -> str:
            """Name the first failed dp-eligibility conjunct ("" when
            eligible) — the `reason` label on sequential-path routing
            increments, so the coverage matrix is self-describing."""
            if not K_pipe:
                return "no_pipeline"
            if dp_n <= 1:
                return "no_dp_mesh"
            if not self.shard_dp:
                return "shard_dp_off"
            if not family_flag:
                return optout
            # a quarantined speculative path runs every group sequentially
            # (the exact twin) until the breaker's TTL expires
            if QUARANTINE.active("speculative"):
                return "quarantined"
            if not (self.shard_existing or not self.existing_nodes):
                return "existing_optout"
            return ""

        fill_block_reason = _dp_block_reason(True, "")
        dp_eligible = not fill_block_reason
        if dp_eligible:
            merged_runs: list = []
            i = 0
            while i < len(runs):
                if runs[i][0][0] == "fill":
                    j = i
                    groups = []
                    while j < len(runs) and runs[j][0][0] == "fill":
                        groups.append(runs[j][1])
                        j += 1
                    if len(groups) >= 2:
                        merged_runs.append((("fill_dp",), groups))
                        i = j
                        continue
                merged_runs.append(runs[i])
                i += 1
            runs = merged_runs
        # ---- dp-sharded speculative kscan (ISSUE 13 rung 2) --------------
        # kscan runs join the fan-out under the per-domain grid deadness
        # predicate + vg/hg record-vs-apply disjointness (ops/solver.py
        # kscan dp section) — unlike fill, topology state is ALLOWED here
        # because the verdict proves count independence per round and the
        # merge re-bases recorded deltas. Runs split into chunk groups of
        # whole segments by the same pod target the fill pipeline uses.
        kscan_block_reason = _dp_block_reason(self.shard_kscan, "kscan_optout")
        kscan_dp_eligible = not kscan_block_reason
        if kscan_dp_eligible:
            split_k: list = []
            for mode, segs in runs:
                if mode[0] != "kscan" or len(segs) <= 1:
                    split_k.append((mode, segs))
                    continue
                # the chunk-group target is sized to THIS run, not the
                # whole problem — kscan runs are often a small slice of
                # a mostly-fill solve and would otherwise never split
                run_pods = sum(hi - lo for lo, hi, _k in segs)
                target = max(-(-run_pods // K_pipe), 1)
                kgroups: list = []
                cur: list = []
                cur_pods = 0
                for seg in segs:
                    cur.append(seg)
                    cur_pods += seg[1] - seg[0]
                    if cur_pods >= target:
                        kgroups.append(cur)
                        cur, cur_pods = [], 0
                if cur:
                    kgroups.append(cur)
                if len(kgroups) >= 2:
                    split_k.append((("kscan_dp", mode[1]), kgroups))
                else:
                    split_k.append((mode, segs))
            runs = split_k
        # ---- dp-sharded speculative per-pod runs (ISSUE 14c) -------------
        # The per-pod engine mutates exactly the ShardKscanState slice —
        # including the budget/nodes_budget debits and reservation
        # capacities, which ride the slice as order-free deltas guarded by
        # the budget/reservation disjointness verdict bit — so consecutive
        # solve_chunk chunks speculate one-per-dp-row under the same
        # verdict contract (solve_perpod_dp) and merge through
        # merge_shard_kscan even with enforced minValues, reservations, or
        # finite disruption budgets. KTPU_SHARD_PERPOD=0 opts out.
        perpod_block_reason = _dp_block_reason(self.shard_perpod, "perpod_optout")
        perpod_dp_eligible = not perpod_block_reason

        outputs: list[tuple] = []
        tmpl_snaps: list = []  # post-dispatch GLOBAL template snapshot per
        # output: the pipelined decode opens claims before the final state
        # lands, and a slot's template is fixed the moment the claim opens
        for mode, segs in runs:
            _wsp = _wfl.open_span(f"dispatch.{mode[0]}")
            if _trace_on:
                import time as _time

                _t_run0 = _time.perf_counter()
            if mode[0] == "gang":
                # gang-atomic slice placement: one scan segment per gang,
                # pods in rank order; padded rows carry count=0 (no-ops).
                # mode[1] is the run's unified vg key (-1 = no vg
                # interaction); gang segments stay out of the dp fan-out
                # (each gang is one sequential all-or-nothing dispatch)
                gkey = mode[1]
                self._shard_eligible("gang", "sequential", reason="gang_atomic")
                B = len(segs)
                B_pad = self._pad_cache.pad("gang_segments", B, step=8)
                kind_ids = np.zeros(B_pad, dtype=np.int64)
                counts = np.zeros(B_pad, dtype=np.int32)
                for j, (lo, hi, k) in enumerate(segs):
                    kind_ids[j] = k
                    counts[j] = hi - lo
                # hosts-per-slice static bound: a gang of N pods never
                # opens more than N claims
                maxg = self._pad_cache.pad("gang_cap", int(counts.max()), step=8)
                xs = _gather_kind_xs(
                    enc["reqs_k"], enc["strict_k"], enc["requests_k"],
                    enc["tol_k"], enc["it_allow_k"], enc["exist_ok_k"],
                    enc["ports_k"], enc["conf_k"], enc["vols_k"],
                    enc["pod_topo_k"], jnp.asarray(kind_ids),
                    jnp.asarray(counts),
                )
                gang_kw = dict(key_kid=-1, n_domains=1, tk_idx=-1)
                if gkey >= 0:
                    gang_kw = dict(
                        key_kid=gkey,
                        n_domains=len(self.encoder.vocab.values[gkey]),
                        tk_idx=enc["topo_kids"].index(gkey),
                    )
                state, ys = ops_solver.solve_gang(
                    state, xs, exist_tensors, self.it_tensors, template_tensors,
                    self.well_known, topo_tensors,
                    zone_kid=enc["zone_kid"], ct_kid=enc["ct_kid"],
                    n_claims=n_claims, maxg=maxg, **gang_kw,
                )
                outputs.append(("gang", segs, ys))
                tmpl_snaps.append(ops_solver.global_template(state))
                for lo_, hi_, k_ in segs:
                    remaining[k_] -= hi_ - lo_
                state = _maybe_compact(state)
            elif mode[0] == "fill":
                if self._active_policy != "lexical":
                    # K-variant objective dispatch: the group solves once
                    # per rank variant in ONE vmapped dispatch and the
                    # best-scoring feasible row commits
                    state = self._run_fill_objective(
                        enc, state, [segs], outputs, tmpl_snaps, remaining,
                        _maybe_compact, _dispatch_fill,
                    )
                else:
                    self._shard_eligible(
                        self._fill_family(enc, segs), "sequential",
                        reason=fill_block_reason or "single_group",
                    )
                    state, ys = _dispatch_fill(state, segs)
                    # fill grids address WINDOW rows; the decode maps them
                    # to global claim ids via this dispatch's slot_of
                    # snapshot
                    outputs.append(("fill", segs, ys, state.slot_of))
                    tmpl_snaps.append(ops_solver.global_template(state))
                    for lo_, hi_, k_ in segs:
                        remaining[k_] -= hi_ - lo_
                    state = _maybe_compact(state)
            elif mode[0] == "fill_dp":
                if self._active_policy != "lexical":
                    # objective variants take the dp rows a non-lexical
                    # solve would have spent on chunk-group speculation:
                    # each merge round fans rank variants of ONE group
                    state = self._run_fill_objective(
                        enc, state, segs, outputs, tmpl_snaps, remaining,
                        _maybe_compact, _dispatch_fill,
                    )
                else:
                    # `segs` is a LIST of chunk groups here; the dp merge
                    # loop appends one ("fill", ...) output per group,
                    # exactly like the sequential branch would have
                    state = self._run_fill_dp(
                        enc, state, segs, outputs, tmpl_snaps, remaining,
                        _maybe_compact, _dispatch_fill,
                    )
            elif mode[0] == "kscan":
                self._shard_eligible(
                    "kscan", "sequential",
                    reason=kscan_block_reason or "single_group",
                )
                state, ys = _dispatch_kscan(state, segs, mode[1])
                outputs.append(("kscan", segs, ys))
                tmpl_snaps.append(ops_solver.global_template(state))
                for lo_, hi_, k_ in segs:
                    remaining[k_] -= hi_ - lo_
                state = _maybe_compact(state)
            elif mode[0] == "kscan_dp":
                # `segs` is a LIST of chunk groups; the dp merge loop
                # appends one ("kscan", ...) output per group, exactly
                # like the sequential branch would have
                state = self._run_kscan_dp(
                    enc, state, mode[1], segs, outputs, tmpl_snaps,
                    remaining, _maybe_compact, _dispatch_kscan,
                )
            else:
                lo, hi = segs[0][0], segs[-1][1]
                chunks = [
                    (clo, min(clo + chunk, hi))
                    for clo in range(lo, hi, chunk)
                ]
                if perpod_dp_eligible and len(chunks) >= 2:
                    # `chunks` is a LIST of (lo, hi) pod chunks; the dp
                    # merge loop appends one ("pods", ...) output per
                    # chunk, exactly like the sequential loop below would
                    state = self._run_perpod_dp(
                        enc, state, chunks, common, outputs, tmpl_snaps,
                        remaining, _maybe_compact,
                    )
                else:
                    for clo, chi in chunks:
                        L = chi - clo
                        self._shard_eligible(
                            "perpod", "sequential",
                            reason=perpod_block_reason or "single_chunk",
                        )
                        # multiple-of-8 bucket instead of pow2: a 1100-pod
                        # remainder chunk pads to 1104 rows, not 2048
                        L_pad = self._pad_cache.pad("perpod_pods", L, step=8)
                        kidx = np.zeros(L_pad, dtype=np.int64)
                        kidx[:L] = kind_of[clo:chi]
                        pt, tol, it_allow, exist_ok, ports, conf, vols, ptopo = (
                            self._materialize_pods(enc, kidx, L)
                        )
                        res = ops_solver.solve_from(
                            state, pt, tol, it_allow, exist_ok, ports, conf,
                            vols, exist_tensors, self.it_tensors,
                            template_tensors, self.well_known, topo_tensors,
                            ptopo, **common,
                        )
                        state = res.claims
                        outputs.append(("pods", clo, chi, res.assignment))
                        tmpl_snaps.append(ops_solver.global_template(state))
                        np.subtract.at(remaining, kind_of[clo:chi], 1)
                        state = _maybe_compact(state)
            if _trace_on:
                # per-mode child spans: dispatch cost only — the device
                # runs async, so the wait shows up under solve.wire
                TRACER.record_span(
                    f"solve.dispatch.{mode[0]}",
                    _time.perf_counter() - _t_run0,
                    segments=len(segs),
                )
            _wfl.close_span(_wsp)
        return state, outputs, tmpl_snaps

    def _dp_wait(self, x, label: str) -> float:
        """jax.block_until_ready with the blocked wall attributed: the
        drain side of the merge loops' sync split (sync_drain_s — the
        compat sync_blocked_s key keeps the verdict+drain sum) and a
        waterfall leaf under `label`."""
        import time as _time

        t0 = _time.perf_counter()
        jax.block_until_ready(x)
        dt = _time.perf_counter() - t0
        stats = self._shard_stats
        if stats is not None:
            stats["sync_drain_s"] += dt
            stats["sync_blocked_s"] += dt
        _wfl.add_current(label, dt)
        return dt

    def _dp_round_account(self, round_groups, n_commit, dp_n, disp_s, fam_of):
        """Per merge round dp-row utilization (committed / replayed /
        padded-idle) and speculation pod-seconds: every dispatched group
        rode the fan-out for `disp_s` wall, so its pods contribute
        disp_s*pods to the family's dispatched denominator, and only the
        committed prefix also reaches the numerator."""
        stats = self._shard_stats
        if stats is None:
            return
        stats["dp_rows_total"] += dp_n
        stats["dp_rows_committed"] += n_commit
        stats["dp_rows_replayed"] += len(round_groups) - n_commit
        stats["dp_rows_idle"] += dp_n - len(round_groups)
        for r, segs in enumerate(round_groups):
            fs = stats["families"][fam_of(segs)]
            pods = sum(hi - lo for lo, hi, *_k in segs)
            fs["dispatched_pod_s"] += disp_s * pods
            if r < n_commit:
                fs["committed_pod_s"] += disp_s * pods

    def _run_fill_dp(
        self, enc, state, groups, outputs, tmpl_snaps, remaining,
        maybe_compact, dispatch_fill,
    ):
        """Speculative dp-row execution of consecutive pipelined fill
        chunk groups (ops/solver.py dp section has the exactness proof):
        each merge round batches up to DP groups into ONE vmapped dispatch
        against the committed state, then commits groups in order — graft
        (merge_shard_fill, committed claims acting as decode-only rows the
        group constrained against but never rescanned) when the commit
        conditions provably hold, sequential replay otherwise. Either way
        the committed state and outputs are bit-identical to the
        sequential loop's."""
        import time as _time

        from karpenter_tpu.faultinject import FAULT
        from karpenter_tpu.ops.kernels import fetch_tree, leading_ones
        from karpenter_tpu.utils.metrics import (
            SHARD_MERGE_ROUNDS, SHARD_VERDICT_BYTES,
        )

        dp_n = int(dict(self.mesh.shape).get("dp", 1))
        n_claims = enc["n_claims"]
        stats = self._shard_stats
        t_loop0 = _time.perf_counter()
        gi = 0
        while gi < len(groups):
            round_groups = groups[gi : gi + dp_n]
            # drain whatever is still in flight (mode-loop tail on round
            # one) BEFORE the round's collective-bearing dispatch: the
            # one-collective-in-flight rule must hold at dispatch time.
            # A wait, not a transfer — the round still fetches exactly
            # one verdict word from the host's point of view.
            self._dp_wait(state, "fill_dp.drain")
            # the round base stays a device-scalar reference — the merge
            # takes base.n_open/base.w_open on device, no host fetch
            base = state
            B_max = max(len(s) for s in round_groups)
            B_pad = self._pad_cache.pad(
                "fill_segments_dp", B_max, step=(8 if B_max <= 32 else 32)
            )
            # a short round pads to DP rows with count-0 groups (no-ops),
            # so the vmapped executable is reused across rounds
            kid_b = np.zeros((dp_n, B_pad), dtype=np.int64)
            cnt_b = np.zeros((dp_n, B_pad), dtype=np.int32)
            for r, segs in enumerate(round_groups):
                for j, (lo, hi, k) in enumerate(segs):
                    kid_b[r, j] = k
                    cnt_b[r, j] = hi - lo
            xs_b = _gather_fill_xs_dp(
                enc["reqs_k"], enc["requests_k"], enc["tol_k"],
                enc["it_allow_k"], enc["exist_ok_k"], enc["ports_k"],
                enc["conf_k"], enc["vols_k"], enc["pod_topo_k"],
                jnp.asarray(kid_b), jnp.asarray(cnt_b),
            )
            t_disp0 = _time.perf_counter()
            spec_states, spec_ys, verdict = ops_solver.solve_fill_dp(
                state, xs_b, enc["exist_tensors"], self.it_tensors,
                enc["template_tensors"], self.well_known, enc["topo_tensors"],
                zone_kid=enc["zone_kid"], ct_kid=enc["ct_kid"],
                n_claims=n_claims,
            )
            # serialize the round's collective computations: >1
            # collective-bearing computation in flight deadlocks the
            # virtual-device CPU backend's rendezvous (fetch_tree has the
            # matching guard)
            self._dp_wait((spec_states, spec_ys, verdict), "fill_dp.device")
            disp_s = _time.perf_counter() - t_disp0
            # the round's SINGLE synchronization point: one packed word
            # carrying every group's commit verdict (prefix-ANDed on
            # device, so leading ones == the committable prefix)
            t_sync = _time.perf_counter()
            (vw,) = fetch_tree([verdict], wf_label="fill_dp.sync_verdict")
            vw = np.asarray(vw)
            n_commit = leading_ones(vw, len(round_groups))
            if stats is not None:
                dt_sync = _time.perf_counter() - t_sync
                stats["merge_rounds"] += 1
                stats["verdict_fetches"] += 1
                stats["verdict_bytes"] += int(vw.nbytes)
                stats["sync_verdict_s"] += dt_sync
                stats["sync_blocked_s"] += dt_sync
            SHARD_VERDICT_BYTES.inc(int(vw.nbytes))
            self._dp_round_account(
                round_groups, n_commit, dp_n, disp_s,
                lambda segs: self._fill_family(enc, segs),
            )
            for r in range(n_commit):
                segs = round_groups[r]
                family = self._fill_family(enc, segs)
                spec_r, ys_r = ops_solver.take_dp_row(
                    (spec_states, spec_ys), jnp.int32(r)
                )
                self._dp_wait(ys_r.fill_c, "fill_dp.graft")
                # chaos seam: cut a speculative merge exactly at the
                # commit decision (an injected error here degrades the
                # whole solve via the ladder, never a half-graft)
                FAULT.point(
                    "solver.merge.commit", segments=len(segs), family=family
                )
                audit = guard_config.should_audit("speculative")
                seq_twin = None
                if audit:
                    # exact twin FIRST, from the same pre-merge committed
                    # state (one collective computation in flight at a
                    # time — the CPU-backend rendezvous rule the
                    # surrounding loop already follows)
                    seq_twin = dispatch_fill(state, segs)
                    self._dp_wait(seq_twin[0], "fill_dp.audit")
                state, shifted = ops_solver.merge_shard_fill(
                    state, spec_r, base
                )
                self._dp_wait(state, "fill_dp.graft")  # one-at-a-time rule
                if audit:
                    state, commit_out = self._audit_shard_merge(
                        state, segs, seq_twin,
                        ("fill", segs, ys_r, shifted),
                        lambda ss, yy, sg=segs: ("fill", sg, yy, ss.slot_of),
                        family=family,
                    )
                    outputs.append(commit_out)
                else:
                    outputs.append(("fill", segs, ys_r, shifted))
                SHARD_MERGE_ROUNDS.inc(outcome="committed", family=family)
                self._shard_account(segs, True, family)
                tmpl_snaps.append(ops_solver.global_template(state))
                for lo_, hi_, k_ in segs:
                    remaining[k_] -= hi_ - lo_
                state = maybe_compact(state)
                # snapshot + compact drained before the next dispatch
                self._dp_wait((state, tmpl_snaps[-1]), "fill_dp.graft")
            if n_commit < len(round_groups):
                # replay exactly ONE refused group (its xs rows were
                # already gathered per-group by dispatch_fill — O(group)
                # host work, not O(DP)); the remaining groups re-enter as
                # a FRESH speculative round from the updated state, so a
                # single refusal doesn't serialize the whole tail
                segs = round_groups[n_commit]
                family = self._fill_family(enc, segs)
                state, ys_seq = dispatch_fill(state, segs)
                self._dp_wait(state, "fill_dp.replay")  # one-at-a-time rule
                outputs.append(("fill", segs, ys_seq, state.slot_of))
                SHARD_MERGE_ROUNDS.inc(outcome="replayed", family=family)
                self._shard_account(segs, False, family)
                tmpl_snaps.append(ops_solver.global_template(state))
                for lo_, hi_, k_ in segs:
                    remaining[k_] -= hi_ - lo_
                state = maybe_compact(state)
                # snapshot + compact drained before the next dispatch
                self._dp_wait((state, tmpl_snaps[-1]), "fill_dp.replay")
                gi += n_commit + 1
            else:
                gi += n_commit
        if stats is not None:
            stats["merge_wall_s"] += _time.perf_counter() - t_loop0
        return state

    def _objective_price_t(self):
        """[T] f32 per-type min offering price column, cached until the
        next catalog re-encode (+inf = unpriced, so an unknown price can
        never look cheap to the cost objective)."""
        if self._price_t is None:
            from karpenter_tpu.ops import encode as ops_encode

            self._price_t = ops_encode.type_price_column(self.it_tensors)
            self._price_t_np = np.asarray(self._price_t)
        return self._price_t

    def _objective_rank(self, policy: str):
        """The policy's canonical [G] template rank, device-resident and
        cached per policy until the next re-encode."""
        r = self._objective_ranks.get(policy)
        if r is None:
            from karpenter_tpu.objectives import scoring as obj_scoring

            r = jnp.asarray(obj_scoring.canonical_rank(policy, self.templates))
            self._objective_ranks[policy] = r
        return r

    def _objective_variant_ranks(self, policy: str, kv: int):
        """[KV, G] rank variants (row 0 = canonical), cached per
        (policy, kv). KV may clamp below the ask when there are fewer
        templates than variants."""
        key = (policy, "variants", kv)
        r = self._objective_ranks.get(key)
        if r is None:
            from karpenter_tpu.objectives import scoring as obj_scoring

            base = obj_scoring.canonical_rank(policy, self.templates)
            r = jnp.asarray(obj_scoring.variant_ranks(base, kv))
            self._objective_ranks[key] = r
        return r

    def _run_fill_objective(
        self, enc, state, groups, outputs, tmpl_snaps, remaining,
        maybe_compact, dispatch_fill,
    ):
        """K-variant objective execution of fill chunk groups: each merge
        round solves ONE group under KV objective-perturbed template
        ranks in a single vmapped dispatch (variants ride the dp axis the
        way speculative groups do — padded-idle dp rows are free variant
        capacity) and fetches ONE packed verdict word carrying every
        variant's feasibility bit plus the argmin-score winner. The
        winner's state IS the sequential solve of the group under that
        rank — same base, full-fidelity scan — so no graft/deadness proof
        is needed; a round with no feasible variant replays the group
        through the normal sequential dispatch and its escalation ladder
        (canonical rank, via the template tensors' rank column)."""
        import time as _time

        from karpenter_tpu import objectives
        from karpenter_tpu.ops.kernels import fetch_tree
        from karpenter_tpu.utils.metrics import (
            OBJECTIVE_ROUNDS, OBJECTIVE_VARIANT_WINS, SHARD_VERDICT_BYTES,
        )

        policy = self._active_policy
        obj_id = objectives.objective_id(policy)
        dp_n = (
            int(dict(self.mesh.shape).get("dp", 1))
            if self.mesh is not None
            else 1
        )
        kv = objectives.variant_count(dp_n)
        ranks = self._objective_variant_ranks(policy, kv)
        price_t = self._objective_price_t()
        n_claims = enc["n_claims"]
        stats = self._shard_stats
        t_loop0 = _time.perf_counter()
        for segs in groups:
            # one collective-bearing computation in flight at a time (the
            # CPU-backend rendezvous rule every dp loop follows)
            self._dp_wait(state, "fill_obj.drain")
            B = len(segs)
            B_pad = self._pad_cache.pad(
                "fill_segments", B, step=(8 if B <= 32 else 32)
            )
            kind_ids = np.zeros(B_pad, dtype=np.int64)
            counts = np.zeros(B_pad, dtype=np.int32)
            for j, (lo, hi, k) in enumerate(segs):
                kind_ids[j] = k
                counts[j] = hi - lo
            xs = _gather_fill_xs(
                enc["reqs_k"], enc["requests_k"], enc["tol_k"],
                enc["it_allow_k"], enc["exist_ok_k"], enc["ports_k"],
                enc["conf_k"], enc["vols_k"], enc["pod_topo_k"],
                jnp.asarray(kind_ids), jnp.asarray(counts),
            )
            base_w_open = state.w_open  # device scalar; only audits fetch
            spec, ys, word, scores = ops_solver.solve_fill_variants(
                state, xs, enc["exist_tensors"], self.it_tensors,
                enc["template_tensors"], self.well_known,
                enc["topo_tensors"], ranks, price_t,
                zone_kid=enc["zone_kid"], ct_kid=enc["ct_kid"],
                n_claims=n_claims, objective=obj_id,
            )
            self._dp_wait((spec, ys, word), "fill_obj.device")
            # the round's SINGLE synchronization point: feasibility bits
            # in the low lanes, the winner index in the top byte
            t_sync = _time.perf_counter()
            (vw,) = fetch_tree([word], wf_label="fill_obj.sync_verdict")
            vw = np.asarray(vw)
            vw_int = int(vw.reshape(-1)[0])
            winner = (vw_int >> 24) & 0xFF
            feasible_any = bool(vw_int & ((1 << 24) - 1))
            if stats is not None:
                dt_sync = _time.perf_counter() - t_sync
                stats["merge_rounds"] += 1
                stats["verdict_fetches"] += 1
                stats["verdict_bytes"] += int(vw.nbytes)
                stats["sync_verdict_s"] += dt_sync
                stats["sync_blocked_s"] += dt_sync
            SHARD_VERDICT_BYTES.inc(int(vw.nbytes))
            if feasible_any:
                spec_w, ys_w, score_w = ops_solver.take_dp_row(
                    (spec, ys, scores), jnp.int32(winner)
                )
                self._dp_wait(ys_w.fill_c, "fill_obj.commit")
                state = state._replace(
                    reqs=spec_w.reqs, used=spec_w.used, its=spec_w.its,
                    template=spec_w.template, open=spec_w.open,
                    pods=spec_w.pods, slot_of=spec_w.slot_of,
                    claim_ports=spec_w.claim_ports, held=spec_w.held,
                    n_open=spec_w.n_open, w_open=spec_w.w_open,
                    spills=spec_w.spills, exist_reqs=spec_w.exist_reqs,
                    exist_used=spec_w.exist_used,
                    exist_ports=spec_w.exist_ports,
                    exist_vols=spec_w.exist_vols,
                    hg_counts=spec_w.hg_counts,
                    w_hw=jnp.maximum(state.w_hw, spec_w.w_open),
                )
                self._dp_wait(state, "fill_obj.commit")
                if guard_config.should_audit("objective"):
                    self._audit_objective_commit(
                        policy, base_w_open, spec_w, score_w
                    )
                outputs.append(("fill", segs, ys_w, state.slot_of))
                OBJECTIVE_ROUNDS.inc(policy=policy, outcome="committed")
                OBJECTIVE_VARIANT_WINS.inc(
                    policy=policy,
                    variant="canonical" if winner == 0 else "perturbed",
                )
            else:
                # no variant packed the group cleanly: sequential replay
                # under the canonical rank keeps every escalation path
                # (window spill, claim-axis growth) intact
                state, ys_seq = dispatch_fill(state, segs)
                self._dp_wait(state, "fill_obj.replay")
                outputs.append(("fill", segs, ys_seq, state.slot_of))
                OBJECTIVE_ROUNDS.inc(policy=policy, outcome="replayed")
            tmpl_snaps.append(ops_solver.global_template(state))
            for lo_, hi_, k_ in segs:
                remaining[k_] -= hi_ - lo_
            state = maybe_compact(state)
            self._dp_wait((state, tmpl_snaps[-1]), "fill_obj.commit")
        if stats is not None:
            stats["merge_wall_s"] += _time.perf_counter() - t_loop0
        return state

    def _audit_objective_commit(self, policy, base_w_open, spec_w, score_w):
        """Objective-twin shadow audit: re-score the committed winner's
        opened claims on host (objectives/oracle.py — np.float32 formula
        twin of the device reduction) and compare against the device-
        reported score. The rel tolerance covers f32 summation-order
        drift; a LYING scorer (KTPU_GUARD_LIE=objective) reports +1.0 off
        and trips quarantine, which routes every later solve back onto
        the lexical policy for the TTL."""
        from karpenter_tpu.objectives import oracle as obj_oracle
        from karpenter_tpu.ops.kernels import fetch_tree

        b_wo, wo, open_m, pods_w, tmpl_w, its_w, fast = fetch_tree(
            [
                base_w_open, spec_w.w_open, spec_w.open, spec_w.pods,
                spec_w.template, spec_w.its, score_w,
            ],
            wf_label="fill_obj.audit",
        )
        fast_val = float(np.asarray(fast))
        if guard_config.lying("objective"):  # seeded lying-scorer fixture
            fast_val += 1.0
        self._objective_price_t()
        host_val = obj_oracle.score_opened(
            policy, int(b_wo), int(wo), np.asarray(open_m),
            np.asarray(pods_w), np.asarray(tmpl_w), np.asarray(its_w),
            self._price_t_np, len(self.templates),
        )
        if np.isclose(fast_val, host_val, rtol=1e-4, atol=1e-3):
            guard_audit.record_audit("objective", "pass")
            return
        pods_by_uid, rounds, existing = self._guard_problem_ctx()
        guard_audit.handle_divergence(
            "objective",
            "device objective score != host re-score",
            self,
            pods_by_uid,
            rounds,
            existing,
            detail={
                "policy": policy,
                "device_score": fast_val,
                "host_score": host_val,
            },
        )

    def _run_kscan_dp(
        self, enc, state, key, groups, outputs, tmpl_snaps, remaining,
        maybe_compact, dispatch_kscan,
    ):
        """Speculative dp-row execution of kscan (zonal-spread) chunk
        groups: same one-verdict-word-per-round merge loop as
        _run_fill_dp, with the kscan deadness predicate (per-domain
        capacity grid) and vg/hg record-vs-apply disjointness folded into
        the on-device verdict. Commit grafts window fields plus the
        recorded topology deltas (merge_shard_kscan); refusal replays the
        one refused group sequentially — either way bit-identical to the
        sequential loop (ops/solver.py kscan dp section has the
        exactness argument)."""
        import time as _time

        from karpenter_tpu.faultinject import FAULT
        from karpenter_tpu.ops.kernels import fetch_tree, leading_ones
        from karpenter_tpu.utils.metrics import (
            SHARD_MERGE_ROUNDS, SHARD_VERDICT_BYTES,
        )

        dp_n = int(dict(self.mesh.shape).get("dp", 1))
        n_claims = enc["n_claims"]
        stats = self._shard_stats
        t_loop0 = _time.perf_counter()
        gi = 0
        while gi < len(groups):
            round_groups = groups[gi : gi + dp_n]
            # same rule as _run_fill_dp: drain in-flight work before the
            # round's collective-bearing dispatch (a wait, not a fetch)
            self._dp_wait(state, "kscan_dp.drain")
            base = state
            B_max = max(len(s) for s in round_groups)
            B_pad = self._pad_cache.pad("kscan_segments_dp", B_max, step=8)
            kid_b = np.zeros((dp_n, B_pad), dtype=np.int64)
            cnt_b = np.zeros((dp_n, B_pad), dtype=np.int32)
            for r, segs in enumerate(round_groups):
                for j, (lo, hi, k) in enumerate(segs):
                    kid_b[r, j] = k
                    cnt_b[r, j] = hi - lo
            maxc = self._pad_cache.pad("kscan_cap", int(cnt_b.max()), step=64)
            xs_b = _gather_kind_xs_dp(
                enc["reqs_k"], enc["strict_k"], enc["requests_k"],
                enc["tol_k"], enc["it_allow_k"], enc["exist_ok_k"],
                enc["ports_k"], enc["conf_k"], enc["vols_k"],
                enc["pod_topo_k"], jnp.asarray(kid_b), jnp.asarray(cnt_b),
            )
            grid_inc = not QUARANTINE.active("grid")
            t_disp0 = _time.perf_counter()
            spec_states, spec_ys, verdict = ops_solver.solve_kscan_dp(
                state, xs_b, enc["exist_tensors"], self.it_tensors,
                enc["template_tensors"], self.well_known, enc["topo_tensors"],
                zone_kid=enc["zone_kid"], ct_kid=enc["ct_kid"],
                n_claims=n_claims, key_kid=key,
                n_domains=len(self.encoder.vocab.values[key]), maxc=maxc,
                grid_incremental=grid_inc,
            )
            self._dp_wait((spec_states, spec_ys, verdict), "kscan_dp.device")
            disp_s = _time.perf_counter() - t_disp0
            t_sync = _time.perf_counter()
            (vw,) = fetch_tree([verdict], wf_label="kscan_dp.sync_verdict")
            vw = np.asarray(vw)
            n_commit = leading_ones(vw, len(round_groups))
            if stats is not None:
                dt_sync = _time.perf_counter() - t_sync
                stats["merge_rounds"] += 1
                stats["verdict_fetches"] += 1
                stats["verdict_bytes"] += int(vw.nbytes)
                stats["sync_verdict_s"] += dt_sync
                stats["sync_blocked_s"] += dt_sync
            SHARD_VERDICT_BYTES.inc(int(vw.nbytes))
            self._dp_round_account(
                round_groups, n_commit, dp_n, disp_s, lambda _segs: "kscan"
            )
            for r in range(n_commit):
                segs = round_groups[r]
                spec_r, ys_r = ops_solver.take_dp_row(
                    (spec_states, spec_ys), jnp.int32(r)
                )
                self._dp_wait(ys_r.assignment, "kscan_dp.graft")
                FAULT.point(
                    "solver.merge.commit", segments=len(segs), family="kscan"
                )
                audit = guard_config.should_audit("speculative")
                seq_twin = None
                if audit:
                    # twin runs the boundary-exact (non-incremental) grid:
                    # the speculative row reset its grid at the group
                    # boundary, so a grid-incremental twin would diverge
                    # on observability only; the merge contract is over
                    # state + assignments
                    seq_twin = dispatch_kscan(
                        state, segs, key, grid_audit=False
                    )
                    self._dp_wait(seq_twin[0], "kscan_dp.audit")
                state, _shifted, assign = ops_solver.merge_shard_kscan(
                    state, spec_r, ys_r.assignment, base
                )
                self._dp_wait(state, "kscan_dp.graft")
                ys_out = ys_r._replace(assignment=assign)
                if audit:
                    state, commit_out = self._audit_shard_merge(
                        state, segs, seq_twin,
                        ("kscan", segs, ys_out),
                        lambda ss, yy, sg=segs: ("kscan", sg, yy),
                        family="kscan",
                    )
                    outputs.append(commit_out)
                else:
                    outputs.append(("kscan", segs, ys_out))
                SHARD_MERGE_ROUNDS.inc(outcome="committed", family="kscan")
                self._shard_account(segs, True, "kscan")
                tmpl_snaps.append(ops_solver.global_template(state))
                for lo_, hi_, k_ in segs:
                    remaining[k_] -= hi_ - lo_
                state = maybe_compact(state)
                # snapshot + compact drained before the next dispatch
                self._dp_wait((state, tmpl_snaps[-1]), "kscan_dp.graft")
            if n_commit < len(round_groups):
                segs = round_groups[n_commit]
                state, ys_seq = dispatch_kscan(state, segs, key)
                self._dp_wait(state, "kscan_dp.replay")  # one-at-a-time rule
                outputs.append(("kscan", segs, ys_seq))
                SHARD_MERGE_ROUNDS.inc(outcome="replayed", family="kscan")
                self._shard_account(segs, False, "kscan")
                tmpl_snaps.append(ops_solver.global_template(state))
                for lo_, hi_, k_ in segs:
                    remaining[k_] -= hi_ - lo_
                state = maybe_compact(state)
                # snapshot + compact drained before the next dispatch
                self._dp_wait((state, tmpl_snaps[-1]), "kscan_dp.replay")
                gi += n_commit + 1
            else:
                gi += n_commit
        if stats is not None:
            stats["merge_wall_s"] += _time.perf_counter() - t_loop0
        return state

    def _run_perpod_dp(
        self, enc, state, chunks, common, outputs, tmpl_snaps, remaining,
        maybe_compact,
    ):
        """Speculative dp-row execution of consecutive per-pod chunks
        (ISSUE 14c): same one-verdict-word-per-round merge loop as
        _run_fill_dp/_run_kscan_dp, with the per-pod engine's chunk scan
        vmapped one chunk per dp row (solve_perpod_dp) and commits grafted
        through merge_shard_kscan (window fields + vg/hg deltas +
        existing-node debits). Refusal replays the one refused chunk via
        the plain solve_from — either way bit-identical to the sequential
        chunk loop."""
        import time as _time

        from karpenter_tpu.faultinject import FAULT
        from karpenter_tpu.ops.kernels import fetch_tree, leading_ones
        from karpenter_tpu.utils.metrics import (
            SHARD_MERGE_ROUNDS, SHARD_VERDICT_BYTES,
        )

        kind_of = enc["kind_of"]
        dp_n = int(dict(self.mesh.shape).get("dp", 1))
        stats = self._shard_stats
        t_loop0 = _time.perf_counter()

        def dispatch_seq(st, clo, chi):
            """One sequential per-pod chunk dispatch (the replay and
            audit-twin rung — the mode loop's plain body)."""
            L = chi - clo
            L_pad = self._pad_cache.pad("perpod_pods", L, step=8)
            kidx = np.zeros(L_pad, dtype=np.int64)
            kidx[:L] = kind_of[clo:chi]
            pt, tol, it_allow, exist_ok, ports, conf, vols, ptopo = (
                self._materialize_pods(enc, kidx, L)
            )
            res = ops_solver.solve_from(
                state if st is None else st, pt, tol, it_allow, exist_ok,
                ports, conf, vols, enc["exist_tensors"], self.it_tensors,
                enc["template_tensors"], self.well_known,
                enc["topo_tensors"], ptopo, **common,
            )
            return res.claims, res.assignment

        gi = 0
        while gi < len(chunks):
            round_chunks = chunks[gi : gi + dp_n]
            # same rule as _run_fill_dp: drain in-flight work before the
            # round's collective-bearing dispatch (a wait, not a fetch)
            self._dp_wait(state, "perpod_dp.drain")
            base = state
            L_max = max(chi - clo for clo, chi in round_chunks)
            # a short round pads to DP rows with zero valid pods (padding
            # rows go r_min = +inf and are trivially dead no-ops)
            L_pad = self._pad_cache.pad("perpod_pods_dp", L_max, step=8)
            kidx_b = np.zeros((dp_n, L_pad), dtype=np.int64)
            nval_b = np.zeros((dp_n,), dtype=np.int32)
            for r, (clo, chi) in enumerate(round_chunks):
                L = chi - clo
                kidx_b[r, :L] = kind_of[clo:chi]
                nval_b[r] = L
            pt, tol, it_allow, exist_ok, ports, conf, vols, ptopo = (
                _gather_pod_chunk_dp(
                    enc["reqs_k"], enc["strict_k"], enc["requests_k"],
                    enc["tol_k"], enc["it_allow_k"], enc["exist_ok_k"],
                    enc["ports_k"], enc["conf_k"], enc["vols_k"],
                    enc["pod_topo_k"], jnp.asarray(kidx_b),
                    jnp.asarray(nval_b),
                )
            )
            t_disp0 = _time.perf_counter()
            spec_states, spec_assign, verdict = ops_solver.solve_perpod_dp(
                state, pt, tol, it_allow, exist_ok, ports, conf, vols,
                enc["exist_tensors"], self.it_tensors,
                enc["template_tensors"], self.well_known,
                enc["topo_tensors"], ptopo, **common,
            )
            self._dp_wait((spec_states, spec_assign, verdict), "perpod_dp.device")
            disp_s = _time.perf_counter() - t_disp0
            t_sync = _time.perf_counter()
            (vw,) = fetch_tree([verdict], wf_label="perpod_dp.sync_verdict")
            vw = np.asarray(vw)
            n_commit = leading_ones(vw, len(round_chunks))
            if stats is not None:
                dt_sync = _time.perf_counter() - t_sync
                stats["merge_rounds"] += 1
                stats["verdict_fetches"] += 1
                stats["verdict_bytes"] += int(vw.nbytes)
                stats["sync_verdict_s"] += dt_sync
                stats["sync_blocked_s"] += dt_sync
            SHARD_VERDICT_BYTES.inc(int(vw.nbytes))
            self._dp_round_account(
                [[(clo, chi, -1)] for clo, chi in round_chunks],
                n_commit, dp_n, disp_s, lambda _segs: "perpod",
            )
            for r in range(n_commit):
                clo, chi = round_chunks[r]
                segs = [(clo, chi, -1)]
                spec_r, assign_r = ops_solver.take_dp_row(
                    (spec_states, spec_assign), jnp.int32(r)
                )
                self._dp_wait(assign_r, "perpod_dp.graft")
                FAULT.point(
                    "solver.merge.commit", segments=1, family="perpod"
                )
                audit = guard_config.should_audit("speculative")
                seq_twin = None
                if audit:
                    # exact twin FIRST, from the same pre-merge committed
                    # state (one collective computation in flight at a
                    # time)
                    seq_twin = dispatch_seq(state, clo, chi)
                    self._dp_wait(seq_twin[0], "perpod_dp.audit")
                state, _shifted, assign = ops_solver.merge_shard_kscan(
                    state, spec_r, assign_r, base
                )
                self._dp_wait(state, "perpod_dp.graft")  # one-at-a-time rule
                if audit:
                    state, commit_out = self._audit_shard_merge(
                        state, segs, seq_twin,
                        ("pods", clo, chi, assign),
                        lambda ss, yy, c=clo, h=chi: ("pods", c, h, yy),
                        family="perpod",
                    )
                    outputs.append(commit_out)
                else:
                    outputs.append(("pods", clo, chi, assign))
                SHARD_MERGE_ROUNDS.inc(outcome="committed", family="perpod")
                self._shard_account(segs, True, "perpod")
                tmpl_snaps.append(ops_solver.global_template(state))
                np.subtract.at(remaining, kind_of[clo:chi], 1)
                state = maybe_compact(state)
                # snapshot + compact drained before the next dispatch
                self._dp_wait((state, tmpl_snaps[-1]), "perpod_dp.graft")
            if n_commit < len(round_chunks):
                clo, chi = round_chunks[n_commit]
                state, assign_seq = dispatch_seq(state, clo, chi)
                self._dp_wait(state, "perpod_dp.replay")  # one-at-a-time rule
                outputs.append(("pods", clo, chi, assign_seq))
                SHARD_MERGE_ROUNDS.inc(outcome="replayed", family="perpod")
                self._shard_account([(clo, chi, -1)], False, "perpod")
                tmpl_snaps.append(ops_solver.global_template(state))
                np.subtract.at(remaining, kind_of[clo:chi], 1)
                state = maybe_compact(state)
                # snapshot + compact drained before the next dispatch
                self._dp_wait((state, tmpl_snaps[-1]), "perpod_dp.replay")
                gi += n_commit + 1
            else:
                gi += n_commit
        if stats is not None:
            stats["merge_wall_s"] += _time.perf_counter() - t_loop0
        return state

    def _fill_family(self, enc, segs) -> str:
        """Speculation-family label of a fill-shaped chunk group:
        `existing` when the solve carries real existing nodes (the debit
        bit is then what proves commits safe), else `topo_fill` when any
        of the group's kinds interacts with a hostname group, else plain
        `fill`."""
        if self.existing_nodes:
            return "existing"
        kind_hg = enc.get("kind_hg")
        if kind_hg is not None and any(
            bool(kind_hg[k]) for _lo, _hi, k in segs
        ):
            return "topo_fill"
        return "fill"

    def _shard_eligible(self, family: str, path: str, reason: str = ""):
        """Per-chunk-group routing accounting: `path` is "dp" when the
        group entered a speculative fan-out round (commit or replay),
        "sequential" when it stayed on the plain ordered scan; `reason`
        names the first failed eligibility conjunct on sequential
        increments ("" on the dp path). Feeds the
        ktpu_shard_family_eligible_total counter and the bench
        --report-shard coverage fractions."""
        from karpenter_tpu.utils.metrics import SHARD_FAMILY_ELIGIBLE

        SHARD_FAMILY_ELIGIBLE.inc(family=family, path=path, reason=reason)
        stats = self._shard_stats
        if stats is not None:
            cov = stats.setdefault("coverage", {}).setdefault(
                family, {"dp": 0, "sequential": 0}
            )
            cov[path] += 1

    def _shard_account(self, segs, committed: bool, family: str):
        self._shard_eligible(family, "dp")
        stats = self._shard_stats
        if stats is None:
            return
        stats["group_pods"].append(
            int(sum(hi - lo for lo, hi, _k in segs))
        )
        stats["groups_committed" if committed else "groups_replayed"] += 1
        fam = stats["families"][family]
        fam["committed" if committed else "replayed"] += 1

    @staticmethod
    def _guard_trees_equal(a, b) -> bool:
        """Bit-exact pytree comparison (one batched device fetch)."""
        from karpenter_tpu.ops.kernels import fetch_tree

        la = jax.tree_util.tree_leaves(a)
        lb = jax.tree_util.tree_leaves(b)
        if len(la) != len(lb):
            return False
        vals = fetch_tree(la + lb)
        n = len(la)
        for x, y in zip(vals[:n], vals[n:]):
            x, y = np.asarray(x), np.asarray(y)
            if x.shape != y.shape or x.dtype != y.dtype:
                return False
            if np.issubdtype(x.dtype, np.floating):
                if not np.array_equal(x, y, equal_nan=True):
                    return False
            elif not np.array_equal(x, y):
                return False
        return True

    def _guard_problem_ctx(self):
        """(pods_by_uid, rounds, existing) for a divergence bundle: the
        solve currently in flight (stashed by solve()) as one round."""
        pods, existing = getattr(self, "_guard_problem", None) or ([], [])
        pods_by_uid = {p.uid: p for p in pods}
        return pods_by_uid, [list(pods_by_uid)], existing

    def _audit_kscan_grid(self, state_in, state_fast, ys_fast, args, kw):
        """Shadow audit of the incremental kscan capacity grid: re-run the
        SAME segments from the SAME entry state with every boundary forced
        onto the full-width divide-and-verify recompute, and compare the
        exit state + assignments bit-exact. On divergence the exact twin's
        results are the ones this solve keeps."""
        state_ex, ys_ex = ops_solver.solve_kind_scan(
            state_in, *args, grid_incremental=False, **kw
        )
        jax.block_until_ready(state_ex)
        fast_cmp = state_fast
        if guard_config.lying("grid"):  # seeded lying-fast-path fixture
            fast_cmp = state_fast._replace(n_open=state_fast.n_open + 1)
        # ys.grid_reused legitimately differs (the twin never reuses);
        # the exactness contract is over state + assignments
        if self._guard_trees_equal(
            (fast_cmp, ys_fast.assignment), (state_ex, ys_ex.assignment)
        ):
            guard_audit.record_audit("grid", "pass")
            return state_fast, ys_fast
        pods_by_uid, rounds, existing = self._guard_problem_ctx()
        guard_audit.handle_divergence(
            "grid",
            "incremental grid reuse != full recompute",
            self,
            pods_by_uid,
            rounds,
            existing,
            detail={"segments": int(ys_fast.assignment.shape[0])},
        )
        return state_ex, ys_ex

    def _audit_shard_merge(
        self, state_fast, segs, seq_twin, commit_out, seq_out_fn,
        family: str = "fill",
    ):
        """Shadow audit of a committed dp-speculative merge group (fill
        or kscan family): the sequential replay (run from the identical
        pre-merge state) is the exact twin; the merged state must match
        it bit-for-bit. On divergence the sequential results replace the
        graft — `seq_out_fn(state_seq, ys_seq)` builds the replacement
        output tuple."""
        state_seq, ys_seq = seq_twin
        fast_cmp = state_fast
        if guard_config.lying("speculative"):
            fast_cmp = state_fast._replace(n_open=state_fast.n_open + 1)
        if self._guard_trees_equal(fast_cmp, state_seq):
            guard_audit.record_audit("speculative", "pass")
            return state_fast, commit_out
        pods_by_uid, rounds, existing = self._guard_problem_ctx()
        guard_audit.handle_divergence(
            "speculative",
            "merged shard state != sequential replay",
            self,
            pods_by_uid,
            rounds,
            existing,
            detail={"segments": len(segs), "family": family},
        )
        return state_seq, seq_out_fn(state_seq, ys_seq)

    def _audit_gang_solve(self, result, host_twin):
        """Shadow audit of the device gang kernel's constraint-bearing
        class (gang × topology / finite budgets — the rungs that used to
        raise _GangHostRoute): the host oracle on the identical problem
        is the exact twin, compared over the full canonical result
        signature. On divergence the host result is the one returned and
        the "gang" quarantine routes the class back to the oracle."""
        if guard_config.lying("gang") and result.assignments:
            # seeded lying-fast-path fixture: GENUINELY corrupt the device
            # result — only this shadow audit stands between it and the
            # caller (the property under test)
            uid = min(result.assignments)
            result.assignments[uid] = result.assignments[uid] + 1
        href = host_twin()
        if guard_audit.result_signature(result) == guard_audit.result_signature(
            href
        ):
            guard_audit.record_audit("gang", "pass")
            return result
        pods_by_uid, rounds, existing = self._guard_problem_ctx()
        guard_audit.handle_divergence(
            "gang",
            "device gang solve != host oracle",
            self,
            pods_by_uid,
            rounds,
            existing,
        )
        return href

    def _pipeline_target(self, enc: dict) -> int:
        """Chunk-group count for the software pipeline; 0 disables (small
        solves keep the single-fetch single-round-trip path)."""
        K = self.pipeline_chunks
        if K <= 1 or enc["P"] < max(self.pipeline_min_pods, 1):
            return 0
        return K

    def _template_it_index(self, template):
        """(instance_types, catalog-column indices) for a template, cached —
        decode filters each claim's viable ITs with one vectorized mask
        gather instead of an O(|catalog|) name-set scan per claim."""
        cached = self._tmpl_it_idx.get(id(template))
        if cached is None:
            its = list(template.instance_types)
            idx = np.array(
                [self._it_index[it.name] for it in its], dtype=np.int64
            )
            cached = self._tmpl_it_idx[id(template)] = (its, idx)
        return cached

    def _decode(
        self,
        pods_sorted: list[Pod],
        state: ops_solver.SolverState,
        outputs: list,
        enc: dict,
        tmpl_snaps: Optional[list] = None,
    ) -> SchedulingResult:
        """Claim-level decode straight from device state (no per-pod host
        requirement replay).

        The device decides WHO goes WHERE, and its SolverState carries the
        exact narrowed requirement masks, f32 resource usage, viable-type
        sets and reservation holds for every claim slot. Decode:

          1. fetches the dispatch outputs in batched transfers
             (kernels.fetch_tree) — per-array np.asarray pays a full
             round trip per read, ruinous over a tunneled TPU;
          2. replays only the cheap pod->slot bookkeeping host-side (list
             appends in scan order, preserving the oracle's claim and pod
             ordering — queue.go:72-90 / scheduler.go:598 semantics);
          3. reconstructs each claim's Requirements at CLAIM granularity:
             template requirements (carrying minValues) + each distinct pod
             KIND's requirements (requirement intersection is idempotent
             across content-identical pods) + the device's vg-topology
             narrowing read back from the claim's requirement masks (vg
             narrowing always yields finite In sets over vocab domains,
             exactly the domains topology.go:226-250 would have chosen —
             bit-parity is enforced by the differential suites).

        Fetch modes:
          * single-fetch (small solves): ONE transfer carries every output
            plus the final-state reads — exactly one wire round trip.
          * pipelined (>= pipeline_min_pods with >= 2 dispatch chunks):
            outputs are fetched and decoded in chunk GROUPS while the
            device still executes later chunks (all dispatches were issued
            asynchronously before decode starts), so wire latency and host
            decode hide behind device compute; a final small fetch brings
            the state reads. `solve.pipeline.chunk[i]` spans attribute the
            overlap: a chunk's wire+decode time is overlapped whenever
            later chunks are still in flight (in_flight > 0).

        Usage comes from the device carry, which accumulated in the same
        f32 order as the host oracle: per-pod adds for scan segments, one
        multiply-add per fill batch (see _merge_scaled).
        """
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            finalize_min_values,
            finalize_reserved,
        )
        from karpenter_tpu.ops.kernels import fetch_tree
        from karpenter_tpu.scheduling import hostports as hpmod
        from karpenter_tpu.tracing.tracer import TRACER
        import time as _time

        # Fill counts ride the wire as int16 — bounded by per-claim pod
        # capacity (allocatable `pods` is O(hundreds), _count_cap_seq) —
        # and a fetched fill_max scalar guards the narrowing loudly.
        # The slicing/casting ("slimming") of every output runs INSIDE a
        # cached jitted prep: done eagerly it costs one tunneled dispatch
        # PER OP, and interleaved fill/kscan solves produce hundreds of
        # slim ops (~0.7s of pure dispatch latency at the 16k mix).
        # Requirement masks are read ONLY for vg-topology narrowing
        # (fold_narrowing), and only at the topology keys' rows — gathered
        # on device (K_pad -> len(topo_kids)), or skipped entirely for
        # topology-free problems.
        tk = tuple(enc["topo_kids"])
        flat: list = []  # device arrays, in recipe order
        specs: list = []  # static twin of `outputs` for the prep closure
        flat_spans: list = []  # per-output [lo, hi) into flat
        weights: list = []  # per-output decode weight (pods covered)
        for o in outputs:
            lo_f = len(flat)
            if o[0] == "pods":
                flat.append(o[3])
                specs.append(("pods",))
                weights.append(o[2] - o[1])
            elif o[0] == "kscan":
                flat.append(o[2].assignment)
                flat.append(o[2].grid_reused)
                specs.append(("kscan", len(o[1])))
                weights.append(sum(hi - lo for lo, hi, _ in o[1]))
            elif o[0] == "gang":
                ys = o[2]
                flat.extend(
                    [ys.open_g, ys.n_opened, ys.fill, ys.leftover, ys.status]
                )
                specs.append(("gang", len(o[1])))
                weights.append(sum(hi - lo for lo, hi, _ in o[1]))
            else:
                ys = o[2]
                flat.extend(
                    [ys.fill_c, ys.fill_e, ys.open_start, ys.n_opened, ys.status, o[3]]
                )
                specs.append(("fill", len(o[1])))
                weights.append(sum(hi - lo for lo, hi, _ in o[1]))
            flat_spans.append((lo_f, len(flat)))
        # prep-cache keys carry the pad signature plus the claims-axis and
        # window sizes so a bucket change rebuilds the jitted prep instead
        # of reusing a stale executable against resized tensors
        pad_sig = self._pads() + (enc["n_claims"], enc["window"])

        def _cached_prep(key, builder):
            prep = self._fetch_prep_cache.get(key)
            if prep is None:
                if len(self._fetch_prep_cache) >= 512:
                    # output structures track workload shape: bound the
                    # cache like kernels._PACK_CACHE so a long-running
                    # control plane with churning workloads can't pin
                    # executables forever
                    self._fetch_prep_cache.clear()
                prep = self._fetch_prep_cache[key] = jax.jit(builder())
            return prep

        fetched: dict = {}
        E = enc["E"]
        kind_of = enc["kind_of"]
        reps: list[Pod] = enc["reps"]
        vocab = self.encoder.vocab
        topo_kids = enc["topo_kids"]

        claims: list[SimClaim] = []
        slot_to_claim: dict[int, SimClaim] = {}
        claim_kinds: dict[int, dict[int, int]] = {}  # slot -> kind -> count
        node_kinds: dict[int, dict[int, int]] = {}
        # pods decided before the solve ran: invalid gang annotations and
        # gangs still waiting for stragglers (the host oracle reports the
        # same entries at the same point of its cascade)
        unschedulable: list[tuple[Pod, str]] = list(enc.get("pre_unsched") or [])
        assignments: dict[str, int] = {}
        existing_assignments: dict[str, str] = {}
        hostname_seq = 0

        # per-kind memos: every pod of a kind is content-identical, so its
        # requirements / totals / port keys are computed once
        U = len(reps)
        kind_reqs_c: list = [None] * U
        kind_total_c: list = [None] * U
        kind_ports_c: list = [None] * U

        def kind_reqs(k: int) -> Requirements:
            r = kind_reqs_c[k]
            if r is None:
                r = kind_reqs_c[k] = self._pod_reqs(reps[k])
            return r

        def kind_total(k: int) -> dict:
            t = kind_total_c[k]
            if t is None:
                t = kind_total_c[k] = reps[k].total_requests()
            return t

        def kind_ports(k: int) -> list[tuple]:
            p = kind_ports_c[k]
            if p is None:
                p = kind_ports_c[k] = [
                    hpmod.port_key(h) for h in reps[k].spec.host_ports
                ]
            return p

        # bound before any decode runs: the final state's template column
        # on the single-fetch path, the chunk group's post-dispatch
        # snapshot on the pipelined path (identical for opened slots — a
        # claim's template is fixed the moment it opens)
        claim_template = None

        def ensure_claim(slot: int) -> SimClaim:
            nonlocal hostname_seq
            claim = slot_to_claim.get(slot)
            if claim is None:
                tmpl = self.templates[int(claim_template[slot])]
                hostname_seq += 1
                hostname = hostname_placeholder(hostname_seq)
                requirements = tmpl.requirements.copy()
                requirements.add(
                    Requirement.new(l.LABEL_HOSTNAME, Operator.IN, hostname)
                )
                claim = SimClaim(
                    template=tmpl,
                    requirements=requirements,
                    used={},  # finalized from the device carry below
                    instance_types=[],  # finalized from the device mask below
                    pods=[],
                    slot=slot,
                    hostname=hostname,
                )
                slot_to_claim[slot] = claim
                claims.append(claim)
                claim_kinds[slot] = {}
            return claim

        # running pod count per claim slot — the water-fill levels of later
        # segments depend on it (fewest-pods-first replays exactly)
        claim_pod_counts = np.zeros(enc["n_claims"], dtype=np.int64)
        NC1 = np.int64(enc["n_claims"] + 1)
        # [incremental, full] kscan capacity-grid updates this solve
        kscan_grid_stats = [0, 0]

        def decode_pod(i: int, slot: int) -> None:
            pod = pods_sorted[i]
            if slot == ops_solver.NO_ROOM:
                unschedulable.append((pod, NO_ROOM_REASON))
                return
            if slot < 0:
                unschedulable.append((pod, NO_CLAIM_REASON))
                return
            k = int(kind_of[i])
            if slot < E:
                node = self.existing_nodes[slot]
                node.used = res.merge(node.used, kind_total(k))
                node.pods.append(pod)
                node.host_ports.extend(kind_ports(k))
                nk = node_kinds.setdefault(slot, {})
                nk[k] = nk.get(k, 0) + 1
                existing_assignments[pod.metadata.uid] = node.name
                return
            slot -= E
            assignments[pod.metadata.uid] = slot
            claim = ensure_claim(slot)
            claim.pods.append(pod)
            claim.host_ports.extend(kind_ports(k))
            ck = claim_kinds[slot]
            ck[k] = ck.get(k, 0) + 1
            claim_pod_counts[slot] += 1

        from types import SimpleNamespace

        fill_ctx = SimpleNamespace(
            E=E,
            NC1=NC1,
            existing_nodes=self.existing_nodes,
            pods_sorted=pods_sorted,
            ensure_claim=ensure_claim,
            slot_to_claim=slot_to_claim,
            claim_kinds=claim_kinds,
            claim_pod_counts=claim_pod_counts,
            assignments=assignments,
            existing_assignments=existing_assignments,
            unschedulable=unschedulable,
            node_kinds=node_kinds,
            kind_ports=kind_ports,
            kind_total=kind_total,
        )

        def decode_fill_output(segs, f) -> None:
            # shared with ResidentSession delta rounds (_decode_fill_segments)
            _decode_fill_segments(fill_ctx, segs, f)

        def decode_gang_output(segs, f) -> None:
            """Gang-grouped claim expansion: slice host j takes the
            contiguous rank block [j*f, (j+1)*f). All-or-nothing by
            construction — the kernel commits either every host of the
            slice or none, so a partial gang can never decode; a spilled
            gang fails every member together with one reason."""
            from karpenter_tpu.gang import GANG_SPILL_REASON

            gang_by_kind = enc.get("gang_key_of_kind") or {}
            open_g = f["open_g"]
            n_opened = f["n_opened"]
            fills = f["fill"]
            leftover = f["leftover"]
            status = f["status"]
            for j, (lo, hi, kind) in enumerate(segs):
                count = hi - lo
                if count == 0:
                    continue
                if int(leftover[j]):
                    st = int(status[j])
                    if st == ops_solver.NO_ROOM:
                        reason = NO_ROOM_REASON
                    elif st == ops_solver.GANG_SPILL:
                        reason = GANG_SPILL_REASON
                    else:
                        reason = NO_CLAIM_REASON
                    for i2 in range(lo, hi):
                        unschedulable.append((pods_sorted[i2], reason))
                    continue
                fj = int(fills[j])
                base = int(open_g[j])
                n_h = int(n_opened[j])
                pk = kind_ports(kind)
                for cj in range(n_h):
                    slot = base + cj
                    claim = ensure_claim(slot)
                    claim.gang = gang_by_kind.get(int(kind))
                    batch = [
                        pods_sorted[i2]
                        for i2 in range(lo + cj * fj, lo + min((cj + 1) * fj, count))
                    ]
                    claim.pods.extend(batch)
                    for p in batch:
                        assignments[p.metadata.uid] = slot
                    if pk:
                        claim.host_ports.extend(pk * len(batch))
                    ck = claim_kinds[slot]
                    ck[kind] = ck.get(kind, 0) + len(batch)
                    claim_pod_counts[slot] += len(batch)

        def apply_assignments(idx0: int, arr: np.ndarray) -> None:
            """Vectorized per-pod decode: arr[i] is pod (idx0+i)'s E-space
            slot (global claim ids) or a negative sentinel. Claims apply
            grouped by slot (stable order -> per-claim pod order matches
            the sequential replay; new slots ascend, so ensure_claim order
            and hostnames match too); existing-node landings keep the
            per-pod path (sequential f32 usage merges are order-exact);
            failures append in pod order."""
            cm = arr >= E
            if cm.any():
                ci = np.flatnonzero(cm)
                cs = arr[ci] - E
                o = np.argsort(cs, kind="stable")
                cs_s = cs[o]
                ci_s = ci[o] + idx0
                bounds = np.flatnonzero(np.diff(cs_s)) + 1
                starts = np.concatenate(([0], bounds))
                ends = np.concatenate((bounds, [len(cs_s)]))
                for a, b in zip(starts.tolist(), ends.tolist()):
                    s = int(cs_s[a])
                    claim = ensure_claim(s)
                    il = ci_s[a:b].tolist()
                    batch = [pods_sorted[i] for i in il]
                    claim.pods.extend(batch)
                    ck = claim_kinds[s]
                    for i, p in zip(il, batch):
                        assignments[p.metadata.uid] = s
                        k = int(kind_of[i])
                        ck[k] = ck.get(k, 0) + 1
                        pk = kind_ports(k)
                        if pk:
                            claim.host_ports.extend(pk)
                    claim_pod_counts[s] += b - a
            em = (arr >= 0) & (arr < E)
            if em.any():
                for i in np.flatnonzero(em).tolist():
                    decode_pod(idx0 + i, int(arr[i]))
            nm = arr < 0
            if nm.any():
                for i in np.flatnonzero(nm).tolist():
                    reason = (
                        NO_ROOM_REASON
                        if arr[i] == ops_solver.NO_ROOM
                        else NO_CLAIM_REASON
                    )
                    unschedulable.append((pods_sorted[idx0 + i], reason))

        def apply_output(out) -> None:
            if out[0] == "pods":
                _, lo, hi, assignment = out
                apply_assignments(
                    lo, np.asarray(assignment[: hi - lo], dtype=np.int64)
                )
            elif out[0] == "gang":
                decode_gang_output(out[1], out[2])
            elif out[0] == "kscan":
                _, segs, assign, grid_reused = out
                n_inc = int(np.asarray(grid_reused).sum())
                kscan_grid_stats[0] += n_inc
                kscan_grid_stats[1] += len(segs) - n_inc
                for j, (lo, hi, _kind) in enumerate(segs):
                    apply_assignments(
                        lo, np.asarray(assign[j][: hi - lo], dtype=np.int64)
                    )
            else:
                decode_fill_output(out[1], out[2])

        def rehydrate(o, spec, it_f):
            """Rebuild one output from its fetched host arrays (the jitted
            prep's emission order); returns (output, is_fill)."""
            if spec[0] == "pods":
                return (o[0], o[1], o[2], next(it_f)), False
            if spec[0] == "kscan":
                return (o[0], o[1], next(it_f), next(it_f)), False
            if spec[0] == "gang":
                return (
                    o[0],
                    o[1],
                    {
                        "open_g": next(it_f),
                        "n_opened": next(it_f),
                        "fill": next(it_f),
                        "leftover": next(it_f),
                        "status": next(it_f),
                    },
                ), False
            return (
                o[0],
                o[1],
                {
                    "fill_c": next(it_f),
                    "fill_e": next(it_f),
                    "open_start": next(it_f),
                    "n_opened": next(it_f),
                    "status": next(it_f),
                    "slot_map": next(it_f),
                },
            ), True

        def widen_fill(idx_range, new_outs) -> None:
            # a fill count overflowed the int16 wire narrowing (a claim
            # admitted >32k identical pods) — refetch those grids at full
            # width; correctness over the wire win on this exotic shape
            for i, o in zip(idx_range, new_outs):
                if o[0] != "fill":
                    continue
                ys = outputs[i][2]
                B = len(o[1])
                o[2]["fill_c"] = np.asarray(ys.fill_c[:B])
                o[2]["fill_e"] = np.asarray(ys.fill_e[:B])

        # chunk-sink deltas (gRPC SolveStream): only rows appended since
        # the previous flush cross the wire
        sink = self._chunk_sink
        emitted_claim: dict[int, int] = {}
        sink_marks = [0, 0]  # existing_assignments, unschedulable

        def flush_chunk() -> None:
            if sink is None:
                return
            delta_claims = []
            for claim in claims:
                n0 = emitted_claim.get(claim.slot, 0)
                if len(claim.pods) > n0:
                    delta_claims.append(
                        (claim.slot, [p.uid for p in claim.pods[n0:]])
                    )
                    emitted_claim[claim.slot] = len(claim.pods)
            ea = list(existing_assignments.items())
            delta_exist = ea[sink_marks[0] :]
            sink_marks[0] = len(ea)
            delta_unsched = [(p.uid, r) for p, r in unschedulable[sink_marks[1] :]]
            sink_marks[1] = len(unschedulable)
            if delta_claims or delta_exist or delta_unsched:
                sink(
                    (
                        "chunk",
                        {
                            "claims": delta_claims,
                            "existing": delta_exist,
                            "unsched": delta_unsched,
                        },
                    )
                )

        groups = None
        if tmpl_snaps is not None and len(outputs) >= 2:
            K = self._pipeline_target(enc)
            if K >= 2:
                groups = _partition_ranges(weights, K)
                if len(groups) < 2:
                    groups = None

        if groups is None:
            # ---- single-fetch path: exactly ONE wire round trip, state
            # reads included (it doubles as the device sync; every extra
            # round trip over a tunneled TPU costs ~70ms)
            prep = _cached_prep(
                ("full", tuple(specs), tk, pad_sig),
                lambda: _make_fetch_prep(tuple(specs), tk),
            )
            with TRACER.span("solve.wire", arrays=len(flat)):
                fetched_flat = fetch_tree(prep(state, flat))
            self._t_fetch_done = _time.perf_counter()
            it_f = iter(fetched_flat)
            fetched = {name: next(it_f) for name in _STATE_HEAD}
            new_outputs = []
            any_fill = False
            for o, spec in zip(outputs, specs):
                out, is_fill = rehydrate(o, spec, it_f)
                any_fill |= is_fill
                new_outputs.append(out)
            fill_max = next(it_f) if any_fill else None
            if tk:
                for name in ("c_mask", "c_inf", "c_def", "e_mask", "e_inf", "e_def"):
                    fetched[name] = next(it_f)
            if fill_max is not None and int(fill_max) >= 2**15:
                widen_fill(range(len(new_outputs)), new_outputs)
            claim_template = fetched["template"]
            for out in new_outputs:
                apply_output(out)
            flush_chunk()
        else:
            # ---- pipelined path: fetch + decode chunk group i while the
            # device executes groups > i (every dispatch was issued
            # asynchronously before decode started), hiding wire latency
            # and host decode behind device compute
            from karpenter_tpu.envelope.sampler import (
                read_cpu_seconds,
                read_rss_bytes,
            )

            G = len(groups)
            chunk_stats: list[dict] = []
            with TRACER.span("solve.pipeline", chunks=G) as psp:
                for gi, (glo, ghi) in enumerate(groups):
                    in_flight = G - 1 - gi  # chunk groups still on device
                    last_group = gi == G - 1
                    cpu0 = read_cpu_seconds()
                    with TRACER.span(
                        f"solve.pipeline.chunk[{gi}]", idx=gi, in_flight=in_flight
                    ) as csp:
                        sg = tuple(specs[glo:ghi])
                        f_lo = flat_spans[glo][0]
                        f_hi = flat_spans[ghi - 1][1]
                        t0 = _time.perf_counter()
                        if last_group:
                            # the final-state reads RIDE the last chunk
                            # group's transfer: the trailing wire drain
                            # (a whole extra round trip) disappears
                            prep = _cached_prep(
                                ("group_final", sg, tk, pad_sig),
                                lambda sg=sg: _make_group_final_prep(sg, tk),
                            )
                            fetched_flat = fetch_tree(
                                prep(tmpl_snaps[ghi - 1], flat[f_lo:f_hi], state)
                            )
                        else:
                            prep = _cached_prep(
                                ("group", sg, pad_sig),
                                lambda sg=sg: _make_group_prep(sg),
                            )
                            fetched_flat = fetch_tree(
                                prep(tmpl_snaps[ghi - 1], flat[f_lo:f_hi])
                            )
                        t1 = _time.perf_counter()
                        if self._t_fetch_done is None:
                            self._t_fetch_done = t1
                        it_f = iter(fetched_flat)
                        claim_template = next(it_f)
                        new_outs = []
                        any_fill = False
                        for o, spec in zip(outputs[glo:ghi], specs[glo:ghi]):
                            out, is_fill = rehydrate(o, spec, it_f)
                            any_fill |= is_fill
                            new_outs.append(out)
                        fill_max = next(it_f) if any_fill else None
                        if last_group:
                            fetched = {name: next(it_f) for name in _STATE_HEAD}
                            if tk:
                                for name in (
                                    "c_mask", "c_inf", "c_def",
                                    "e_mask", "e_inf", "e_def",
                                ):
                                    fetched[name] = next(it_f)
                            claim_template = fetched["template"]
                        if fill_max is not None and int(fill_max) >= 2**15:
                            widen_fill(range(glo, ghi), new_outs)
                        for out in new_outs:
                            apply_output(out)
                        flush_chunk()
                        t2 = _time.perf_counter()
                        stat = {
                            "idx": gi,
                            "pods": int(sum(weights[glo:ghi])),
                            "in_flight": in_flight,
                            "wire_s": t1 - t0,
                            "decode_s": t2 - t1,
                            "host_rss_mb": round(read_rss_bytes() / 2**20, 1),
                            "cpu_s": round(read_cpu_seconds() - cpu0, 4),
                        }
                        csp.set(
                            wire_s=round(stat["wire_s"], 4),
                            decode_s=round(stat["decode_s"], 4),
                            pods=stat["pods"],
                        )
                        chunk_stats.append(stat)
                # no trailing drain: the final-state reads rode the last
                # chunk group's transfer (group_final prep), so the
                # pipeline's only exposed round trip is chunk 0's device
                # wait — `fetched` was populated inside the loop
                t_final = 0.0
                # overlap attribution: a chunk's wire+decode time is
                # overlapped exactly when later chunk groups were still in
                # flight on the device; the last chunk and the final fetch
                # are the exposed (non-overlapped) remainder. Chunk 0's
                # wire time is EXCLUDED from both sides — it is dominated
                # by the wait for the device to finish chunk 0 (the
                # pipeline fill, i.e. device time observed through the
                # fetch), not by hideable wire/decode work.
                def _chunk_cost(s):
                    w = s["wire_s"] if s["idx"] > 0 else 0.0
                    return w + s["decode_s"]

                overlapped = sum(
                    _chunk_cost(s) for s in chunk_stats if s["in_flight"]
                )
                total = sum(_chunk_cost(s) for s in chunk_stats) + t_final
                overlap_frac = round(overlapped / total, 4) if total > 0 else 0.0
                psp.set(overlap_frac=overlap_frac, final_fetch_s=round(t_final, 4))
                self._pipeline_stats = {
                    "n_chunks": G,
                    "overlap_frac": overlap_frac,
                    # the final-state reads rode the last chunk's transfer
                    "fused_final": True,
                    # chunk 0's fetch = device drain of chunk 0 + its
                    # transfer (the pipeline fill; analogous to the old
                    # single-fetch device wait)
                    "sync_wire_s": round(chunk_stats[0]["wire_s"], 4),
                    "wire_s": round(
                        sum(s["wire_s"] for s in chunk_stats) + t_final, 4
                    ),
                    "host_decode_s": round(
                        sum(s["decode_s"] for s in chunk_stats), 4
                    ),
                    "final_fetch_s": round(t_final, 4),
                    "chunks": [
                        {
                            **s,
                            "wire_s": round(s["wire_s"], 4),
                            "decode_s": round(s["decode_s"], 4),
                        }
                        for s in chunk_stats
                    ],
                }
        self._last_n_open = int(fetched["n_open"])
        self._last_w_hw = int(fetched["w_hw"])
        # claims-axis occupancy for the bench/gates: live high-water vs the
        # window, frozen-bank size, spill count (window-bound NO_ROOMs)
        n_spills = int(fetched["spills"])
        self._scan_stats = {
            "window": int(enc["window"]),
            "n_claims": int(enc["n_claims"]),
            "n_open": int(fetched["n_open"]),
            "live_hw": int(fetched["w_hw"]),
            "resident": int(fetched["w_open"]),
            "frozen": int(fetched["n_open"]) - int(fetched["w_open"]),
            "spills": n_spills,
            "compactions": int(getattr(self, "_n_compactions", 0)),
        }
        if n_spills:
            from karpenter_tpu.utils.metrics import SCAN_WINDOW_SPILLS

            SCAN_WINDOW_SPILLS.inc(n_spills)
        if kscan_grid_stats[0] or kscan_grid_stats[1]:
            from karpenter_tpu.utils.metrics import KSCAN_GRID_UPDATES

            if kscan_grid_stats[0]:
                KSCAN_GRID_UPDATES.inc(kscan_grid_stats[0], mode="incremental")
            if kscan_grid_stats[1]:
                KSCAN_GRID_UPDATES.inc(kscan_grid_stats[1], mode="full")
            self._scan_stats["kscan_grid_incremental"] = kscan_grid_stats[0]
            self._scan_stats["kscan_grid_full"] = kscan_grid_stats[1]

        # ---- finalization from device state --------------------------------
        def fold_narrowing(reqs: Requirements, mask_r, inf_r, def_r, what: str):
            """Intersect the device's vg-topology narrowing into host reqs.

            Rows are PRE-GATHERED to the topo_kids axis (row j = key
            topo_kids[j]). For a key the device never narrowed, the mask
            equals the host-side intersection already rebuilt from
            template+kind reqs, so the extra add is an exact no-op; for a
            narrowed key it lands precisely on the device-chosen set."""
            for j, kid in enumerate(topo_kids):
                if not def_r[j] or inf_r[j]:
                    continue
                key = vocab.keys[kid]
                vals = [
                    v
                    for vi, v in enumerate(vocab.values[kid])
                    if mask_r[j, vi]
                ]
                if not vals:
                    raise DivergenceError(
                        f"device narrowed {key} to the empty set on {what}"
                    )
                reqs.add(Requirement.new(key, Operator.IN, *vals))

        its_mask = fetched["its"]
        held = fetched["held"]
        used_np = np.asarray(fetched["used"])
        rids = self.encoder._resource_ids
        # The per-claim requirements rebuild was the last per-claim Python
        # on the hot path (ROADMAP lever): a solve opens thousands of
        # claims drawn from a handful of (template, kind-set) combinations,
        # so the expensive pieces — the template ∩ kind-requirements
        # intersection, the resource-name/rid layout, and the viable
        # instance-type selection — are memoized per combination and each
        # claim pays only a dict copy + its own hostname/narrowing fold.
        proto_cache: dict = {}  # (tmpl id, kinds sig) -> (reqs, names, ridx)
        its_cache: dict = {}  # (tmpl id, its-row bytes) -> [InstanceType]
        n_rid = len(self._rid_names) if self._rid_names else 0
        for claim in claims:
            s = claim.slot
            kinds = claim_kinds[s]
            ksig = tuple(sorted(kinds))
            tid = id(claim.template)
            memo = proto_cache.get((tid, ksig))
            if memo is None:
                proto = claim.template.requirements.copy()
                names = set(claim.template.daemon_requests)
                for k in ksig:
                    proto.add(*kind_reqs(k).values())
                    names.update(kind_total(k))
                names = sorted(names)
                ridx = np.array([rids[n] for n in names], dtype=np.int64)
                memo = proto_cache[(tid, ksig)] = (proto, names, ridx)
            proto, names, ridx = memo
            # template ∩ kind reqs (shared) + this claim's hostname; the
            # intersection is commutative, so this equals the old
            # per-claim re-add of every kind's requirements
            reqs = proto.copy()
            reqs.add(Requirement.new(l.LABEL_HOSTNAME, Operator.IN, claim.hostname))
            claim.requirements = reqs
            if topo_kids:
                fold_narrowing(
                    reqs,
                    fetched["c_mask"][s],
                    fetched["c_inf"][s],
                    fetched["c_def"][s],
                    f"claim slot {s}",
                )
            # usage from the device carry (daemon overhead folded in on
            # open): one fancy-index gather per claim over the memoized
            # name layout
            vec = used_np[s][ridx]
            claim.used = dict(zip(names, vec.tolist()))
            # viable instance types straight from the device solver state
            # (the device carried budget bookkeeping too); TEMPLATE catalog
            # order so cheapest_launch tie-breaks identically to the host.
            # Identical mask rows (thousands of same-shape claims at the
            # north star) share one decoded list.
            row = np.asarray(its_mask[s])
            ikey = (tid, row.tobytes())
            sel_list = its_cache.get(ikey)
            if sel_list is None:
                t_its, t_cat_idx = self._template_it_index(claim.template)
                sel = np.flatnonzero(row[t_cat_idx])
                sel_list = its_cache[ikey] = [t_its[i] for i in sel.tolist()]
            claim.instance_types = list(sel_list)
            # reservations the scan committed for this claim slot
            if n_rid:
                claim.reserved_ids = frozenset(
                    self._rid_names[r] for r in np.nonzero(held[s][:n_rid])[0]
                )
            finalize_reserved(claim)
            if self.min_values_policy == "BestEffort":
                finalize_min_values(claim)

        for e, kinds in node_kinds.items():
            node = self.existing_nodes[e]
            for k in kinds:
                node.requirements.add(*kind_reqs(k).values())
            if topo_kids:
                fold_narrowing(
                    node.requirements,
                    fetched["e_mask"][e],
                    fetched["e_inf"][e],
                    fetched["e_def"][e],
                    f"existing node {node.name}",
                )
        # attach-tracking parity with the host oracle's can_add_existing
        if self._pod_vols:
            by_name = {n.name: n for n in self.existing_nodes}
            for uid, node_name in existing_assignments.items():
                vols = self._pod_vols.get(uid)
                node = by_name.get(node_name)
                if vols and node is not None and node.volume_usage is not None:
                    node.volume_usage.add(uid, vols)

        result = SchedulingResult(
            claims=claims,
            unschedulable=unschedulable,
            assignments=assignments,
            existing=self.existing_nodes,
            existing_assignments=existing_assignments,
        )
        if self._capture:
            # resident-session adoption material: the post-solve device
            # state plus the decode bookkeeping delta rounds extend. The
            # relaxation/NO_ROOM loops overwrite this per round, so the
            # capture always matches the RETURNED result.
            self._captured = dict(
                state=state,
                enc=enc,
                pods_sorted=list(pods_sorted),
                claims=claims,
                slot_to_claim=slot_to_claim,
                claim_kinds=claim_kinds,
                claim_pod_counts=claim_pod_counts,
                assignments=assignments,
                existing_assignments=existing_assignments,
                unschedulable=unschedulable,
                node_kinds=node_kinds,
                n_open=self._last_n_open,
                compact_rmin=self._last_compact_rmin,
            )
        return result


# ---------------------------------------------------------------------------
# Resident incremental solver (ISSUE 7): schedule deltas, not snapshots
# ---------------------------------------------------------------------------


def resident_enabled() -> bool:
    """KTPU_RESIDENT gate (default on; =0 restores the snapshot path —
    every round is a plain TPUScheduler.solve, bit-for-bit)."""
    import os

    return os.environ.get("KTPU_RESIDENT", "1") not in ("0", "false")


class _DeltaUnsafe(RuntimeError):
    """A delta round failed a soundness gate BEFORE any state mutation;
    the session falls back to a full re-solve for this round."""

    def __init__(self, mode: str, reason: str):
        super().__init__(reason)
        self.mode = mode
        self.reason = reason


class ResidentSession:
    """Keeps SolverState resident on device across solve() calls and feeds
    only the DELTA (arrived / departed pods) through the pipeline — the
    ROADMAP's "turn a batch solver into a service" refactor. Wraps a
    TPUScheduler; drop-in for it at the Provisioner/RPC seam (unknown
    attributes delegate to the wrapped scheduler).

    Invariant: whenever a round stays on the delta path, the cumulative
    result is BIT-identical to a cold full re-solve of the current pod set
    in session (arrival) order — enforced by conservative host-side gates,
    each of which falls back to a full re-solve when it cannot PROVE
    identity:

      * arrivals append only when the cold FFD sort of the union keeps
        every resident pod in place (stable-lexsort prefix check over the
        shared (size, kind-rank) keys) — then the scan-prefix property of
        the chunked solve makes the append exact;
      * arrivals must not undercut the eviction floor (the elementwise-max
        r_min any boundary compaction used): a smaller arrival could have
        fit a claim the base solve froze;
      * departures retract only when they form an exact suffix of "pure"
        rounds (rounds whose pods landed exclusively on claims those
        rounds opened) — then ops_solver.retract_tail's suffix undo is an
        exact rollback to the state the surviving prefix produced;
      * the session only goes resident at all for the fill-regime
        constraint family (topology-free, no gangs, no enforced minValues,
        no reservations, no finite budgets, no DRA/volume machinery) with
        a clean base solve (no unschedulable pods, no relaxation);
      * any cluster-shape change — vocab/pads growth, catalog/template
        rebuild (a new scheduler), existing-node content change — is an
        epoch invalidation: full re-solve, new resident base.

    Modes (ktpu_resident_rounds_total{mode}): delta / full / invalidated.
    """

    # the Provisioner materializes bound_pods only for schedulers that ask
    wants_bound_pods = False

    def __init__(self, sched: TPUScheduler):
        self.sched = sched
        self._r: Optional[dict] = None
        self.last_mode = "full"
        self.last_reason = "cold"
        self.rounds_total = {"delta": 0, "full": 0, "invalidated": 0}
        self.last_timings: dict = {}
        # verdict of the last round's shadow audit (None = not sampled)
        self.last_audit: Optional[dict] = None

    def __getattr__(self, name):
        return getattr(self.sched, name)

    # -- bookkeeping helpers ----------------------------------------------

    @staticmethod
    def _existing_sig(nodes) -> tuple:
        return tuple(
            (
                n.name,
                str(n.requirements),
                tuple(sorted(n.available.items())),
                tuple(sorted(n.used.items())),
                repr(n.taints),
                tuple(n.host_ports),
                n.volume_usage is not None,
            )
            for n in nodes or []
        )

    def _grows_vocab(self, rep: Pod) -> bool:
        """Whether encoding this kind would grow the vocab / resource axis
        (a session epoch change — the resident problem tensors predate
        it). Mirrors ProblemEncoder.observe_pod without mutating."""
        enc = self.sched.encoder
        v = enc.vocab
        for rq in self.sched._pod_reqs(rep).values():
            if rq.key in enc.skip_keys:
                continue
            kid = v.key_to_id.get(rq.key)
            if kid is None:
                return True
            vt = v.value_to_id[kid]
            if any(val not in vt for val in rq.values):
                return True
        return any(
            name not in enc._resource_ids for name in rep.total_requests()
        )

    def _kind_reqs(self, k: int) -> Requirements:
        r = self._r
        out = r["kind_reqs_c"].get(k)
        if out is None:
            out = r["kind_reqs_c"][k] = self.sched._pod_reqs(r["kind_reps"][k])
        return out

    def _kind_total(self, k: int) -> dict:
        r = self._r
        out = r["kind_total_c"].get(k)
        if out is None:
            out = r["kind_total_c"][k] = r["kind_reps"][k].total_requests()
        return out

    def _kind_ports(self, k: int) -> list:
        r = self._r
        out = r["kind_ports_c"].get(k)
        if out is None:
            from karpenter_tpu.scheduling import hostports as hpmod

            out = r["kind_ports_c"][k] = [
                hpmod.port_key(h) for h in r["kind_reps"][k].spec.host_ports
            ]
        return out

    # -- the TPUScheduler surface -----------------------------------------

    def solve(
        self,
        pods,
        existing_nodes=None,
        budgets=None,
        topology=None,
        topology_factory=None,
        volume_reqs=None,
        reserved_mode=None,
        reserved_in_use=None,
        dra_problem=None,
        pod_volumes=None,
        deadline=None,
        now=None,
        bound_pods=None,
        chunk_sink=None,
    ) -> SchedulingResult:
        import time as _time

        pods = list(pods)
        kwargs = dict(
            budgets=budgets,
            topology=topology,
            topology_factory=topology_factory,
            volume_reqs=volume_reqs,
            reserved_mode=reserved_mode,
            reserved_in_use=reserved_in_use,
            dra_problem=dra_problem,
            pod_volumes=pod_volumes,
            deadline=deadline,
            now=now,
            bound_pods=bound_pods,
            chunk_sink=chunk_sink,
        )
        if not resident_enabled():
            # snapshot path, untouched (acceptance: KTPU_RESIDENT=0)
            self._r = None
            return self.sched.solve(pods, existing_nodes, **kwargs)
        # chunk_sink stays supported: it is output plumbing (SolveStream),
        # not a constraint — full rounds stream through it; delta rounds
        # produce no chunks, so the final frame carries everything
        supported = not (
            budgets
            or volume_reqs
            or reserved_in_use
            or dra_problem is not None
            or pod_volumes
            or (
                reserved_mode is not None
                and reserved_mode != self.sched.reserved_mode
            )
        )
        t0 = _time.perf_counter()
        self.last_audit = None
        # the session records ONE ledger entry for the whole round; the
        # wrapped scheduler's internal solves (full path, audit twin) are
        # sub-steps, not rounds
        self.sched._ledger_suppress = True
        self.sched._last_fallback = None
        try:
            if not supported:
                raise _DeltaUnsafe("full", "unsupported_args")
            if QUARANTINE.active("resident"):
                # a tripped resident breaker routes every round onto
                # snapshot solves (the exact twin) until TTL expiry
                raise _DeltaUnsafe("full", "quarantined")
            plan = self._classify(
                pods, existing_nodes, topology, topology_factory, bound_pods
            )
            result = self._solve_delta(plan, deadline=deadline, now=now)
            mode, reason = "delta", "delta"
            if guard_config.lying("resident") and result.assignments:
                # seeded lying-fast-path fixture: GENUINELY corrupt the
                # delta result — only a shadow audit stands between this
                # and the caller (which is the property under test)
                uid = min(result.assignments)
                result.assignments[uid] = result.assignments[uid] + 1
            if guard_config.should_audit("resident"):
                result, mode, reason = self._audit_delta(result)
        except _DeltaUnsafe as gate:
            mode, reason = gate.mode, gate.reason
            result = self._solve_full(
                pods, existing_nodes, kwargs, capture=supported
            )
        finally:
            self.sched._ledger_suppress = False
        self.last_mode, self.last_reason = mode, reason
        self.rounds_total[mode] += 1
        from karpenter_tpu.utils.metrics import RESIDENT_ROUNDS

        RESIDENT_ROUNDS.inc(mode=mode)
        # host-fallback solves (e.g. DRA) never reach _solve_once, so the
        # wrapped scheduler may not have timings yet
        self.last_timings = dict(getattr(self.sched, "last_timings", {}) or {})
        if mode == "delta":
            # a delta round never ran the instrumented full path; don't
            # carry a stale waterfall from an earlier full round
            self.last_timings.pop("waterfall", None)
        self.last_timings["resident"] = {
            "mode": mode,
            "reason": reason,
            "wall_s": _time.perf_counter() - t0,
            "audit": self.last_audit,
        }
        from karpenter_tpu.obs import ledger as obs_ledger

        obs_ledger.record_session_round(
            self, pods=len(pods), wall_s=_time.perf_counter() - t0
        )
        return result

    # -- guard: shadow audit + state fingerprint ---------------------------

    def _audit_delta(self, fast_result) -> tuple:
        """Shadow audit of a delta round: re-derive the session's current
        pod set via the exact twin (a cold full re-solve from the pristine
        inputs, the same oracle the tier-1 parity suite uses) and compare
        canonical result signatures. A divergence drops the resident
        state, quarantines the path, and returns the exact result."""
        import time as _time

        r = self._r
        pods = [r["pod_by_uid"][u] for u in r["order"]]
        exist = [n.clone() for n in r["exist_pristine"]]
        t0 = _time.perf_counter()
        cold = self.sched.solve(pods, exist)
        audit_s = _time.perf_counter() - t0
        if guard_audit.result_signature(fast_result) == guard_audit.result_signature(
            cold
        ):
            guard_audit.record_audit("resident", "pass")
            self.last_audit = {"verdict": "pass", "twin_s": audit_s}
            return fast_result, "delta", "delta"
        # bundle the solve sequence that reproduces this: the resident
        # base (everything before the divergent round) then the union
        last = r["rounds"][-1]
        base_uids = list(r["order"][: last["start_idx"]])
        all_uids = list(r["order"])
        bundle_rounds = [base_uids, all_uids] if base_uids else [all_uids]
        bundle_path = guard_audit.handle_divergence(
            "resident",
            "delta round result != cold full re-solve",
            self.sched,
            dict(r["pod_by_uid"]),
            bundle_rounds,
            r["exist_pristine"],
            detail={"rounds_resident": len(r["rounds"])},
        )
        self.last_audit = {
            "verdict": "divergence",
            "twin_s": audit_s,
            "bundle": bundle_path,
        }
        self._r = None  # the fast state lied; drop it, serve the exact twin
        return cold, "full", "guard_divergence"

    @staticmethod
    def _round_sig(uids, n_open_start: int) -> bytes:
        """Content signature of one committed round (fingerprint chain
        link): the pods it bound and the claim watermark it started from."""
        import hashlib

        h = hashlib.blake2s(digest_size=8)
        h.update(str(int(n_open_start)).encode())
        for u in sorted(uids):
            h.update(b"\x00")
            h.update(str(u).encode())
        return h.digest()

    @property
    def fingerprint(self) -> str:
        """Running hash over committed round signatures; '' when there is
        no resident state. Echoed through RPC session metadata so a
        server-side registry eviction / restart mid-session is detected as
        a typed SESSION_LOST instead of silently solving against a fresh
        (empty) session."""
        r = self._r
        if r is None:
            return ""
        import hashlib

        h = hashlib.blake2s(digest_size=8)
        for rec in r["rounds"]:
            h.update(rec["sig"])
        return h.hexdigest()

    @classmethod
    def replay_chain(cls, sched, pods_by_uid, existing, rounds):
        """Rebuild a resident session by replaying a cumulative capsule
        transcript (obs.ledger.session_chain_transcript form: round k's
        entry is every uid resident after round k, in arrival order).

        Each replayed round re-runs the same gates the original session
        ran, so a chain whose rounds all stayed resident reproduces the
        identical round-sig sequence — the caller checks fingerprint
        equality against the lost session before trusting the rebuild.
        Returns None when any replayed round comes back unschedulable or a
        transcript uid has no pod in the capsule (a truncated/foreign
        capsule cannot be adopted)."""
        session = cls(sched)
        # replayed rounds DO record in the ledger (real device work on
        # this replica) but carry a replay mark: fleet stitching counts
        # each round id exactly once, at the replica that first ran it
        session._replaying = True
        try:
            for uids in rounds:
                try:
                    pods = [pods_by_uid[u] for u in uids]
                except KeyError:
                    return None
                exist = [n.clone() for n in existing]
                result = session.solve(pods, exist)
                if result.unschedulable:
                    return None
        finally:
            session._replaying = False
        return session

    # -- full path ---------------------------------------------------------

    def _solve_full(self, pods, existing_nodes, kwargs, capture: bool):
        self._r = None
        if not capture:
            return self.sched.solve(pods, existing_nodes, **kwargs)
        self.sched._capture = True
        self.sched._captured = None
        try:
            result = self.sched.solve(pods, existing_nodes, **kwargs)
        finally:
            cap = self.sched._captured
            self.sched._captured = None
            self.sched._capture = False
        self._adopt(cap, existing_nodes, result)
        return result

    def _adopt(self, cap, input_existing, result) -> None:
        """Go resident on a clean captured full solve, when the problem
        sits inside the delta-safe constraint family."""
        if cap is None or result.unschedulable or result.relaxations:
            return
        enc = cap["enc"]
        if enc["P"] <= 0 or cap["n_open"] is None:
            return
        if enc["topo_kids"] or enc["vg_groups"] or enc["hg_groups"]:
            return
        topo = getattr(self.sched, "topology", None)
        if topo is not None and (topo.groups or topo.inverse_groups):
            return
        if bool(np.asarray(enc["gang_kind"]).any()) or enc.get("pre_unsched"):
            return
        if not bool(np.all(enc["batchable"])):
            return
        if self.sched._res_active or self.sched._mv_active:
            return
        if any(v for v in self.sched.budgets.values()):
            return
        if not self.sched.encode_cache_enabled:
            return
        pods_sorted = cap["pods_sorted"]
        if len({p.uid for p in pods_sorted}) != len(pods_sorted):
            return
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            pod_ffd_key,
        )

        sizes = np.empty(len(pods_sorted), dtype=np.float64)
        for i, p in enumerate(pods_sorted):
            sizes[i] = pod_ffd_key(p)[1]
        reps = enc["reps"]
        self._r = dict(
            state=cap["state"],
            enc=enc,
            n_claims=enc["n_claims"],
            order=[p.uid for p in pods_sorted],
            pod_by_uid={p.uid: p for p in pods_sorted},
            # session kid numbering == union first-appearance rank (the
            # sorted-order invariant makes the two coincide); ids are
            # never reused, so relative rank order survives retractions
            ranks=np.asarray(enc["kind_of"][: enc["P"]], dtype=np.int64).copy(),
            sizes=sizes,
            kind_sig_to_kid={
                self.sched._kind_sig(rep): k for k, rep in enumerate(reps)
            },
            kind_reps={k: rep for k, rep in enumerate(reps)},
            next_kid=len(reps),
            kind_reqs_c={},
            kind_total_c={},
            kind_ports_c={},
            claims=cap["claims"],
            slot_to_claim=cap["slot_to_claim"],
            claim_kinds=cap["claim_kinds"],
            claim_pod_counts=cap["claim_pod_counts"],
            assignments=cap["assignments"],
            existing_assignments=cap["existing_assignments"],
            node_kinds=cap["node_kinds"],
            existing_nodes=result.existing,
            exist_pristine=[n.clone() for n in (input_existing or [])],
            exist_sig=self._existing_sig(input_existing),
            hostname_seq=len(cap["claims"]),
            rounds=[
                dict(
                    uids={p.uid for p in pods_sorted},
                    start_idx=0,
                    n_open_start=0,
                    pure=True,
                    new_kids=list(range(len(reps))),
                    sig=self._round_sig((p.uid for p in pods_sorted), 0),
                )
            ],
            n_open=int(cap["n_open"]),
            compact_rmin=cap["compact_rmin"],
            proto_cache={},
            its_cache={},
            vocab_sig=self.sched._sig(),
        )

    # -- classification ----------------------------------------------------

    def _classify(
        self, pods, existing_nodes, topology, topology_factory, bound_pods
    ) -> dict:
        r = self._r
        if r is None:
            raise _DeltaUnsafe("full", "cold")
        if self.sched._sig() != r["vocab_sig"]:
            raise _DeltaUnsafe("invalidated", "vocab_changed")
        if self._existing_sig(existing_nodes) != r["exist_sig"]:
            raise _DeltaUnsafe("invalidated", "existing_changed")
        pod_by_uid = r["pod_by_uid"]
        uid_list = [p.metadata.uid for p in pods]
        uids = set(uid_list)
        if len(uids) != len(pods):
            raise _DeltaUnsafe("full", "duplicate_uids")
        # resident pods must be content-identical to their recorded selves
        # (a mutated spec under a reused uid is a different problem); pod
        # specs are immutable post-construction, so the SAME object needs
        # no re-check — only a replacement object pays the sig comparison
        arrivals: list[Pod] = []
        for p, uid in zip(pods, uid_list):
            old = pod_by_uid.get(uid)
            if old is None:
                arrivals.append(p)
            elif old is not p and (
                self.sched._kind_sig(p) != self.sched._kind_sig(old)
            ):
                raise _DeltaUnsafe("invalidated", "pod_mutated")
        departed = set(pod_by_uid) - uids
        if not arrivals and not departed:
            # an unchanged pod set still re-solves identically; cheap path
            raise _DeltaUnsafe("full", "no_delta")
        # ---- departures: exact suffix of pure rounds ----------------------
        retract_k = 0
        if departed:
            acc: set = set()
            rounds = r["rounds"]
            while acc != departed:
                retract_k += 1
                if retract_k >= len(rounds):
                    # the base round would have to unwind: full re-solve
                    # (the "retract-triggers-full-resolve" edge)
                    raise _DeltaUnsafe("full", "retract_base")
                rec = rounds[-retract_k]
                if not rec["pure"]:
                    raise _DeltaUnsafe("full", "retract_impure")
                acc |= rec["uids"]
                if not acc <= departed:
                    raise _DeltaUnsafe("full", "retract_unaligned")
        # ---- arrivals: constraint family + ordering -----------------------
        plan_kinds: list = []  # (sig, kid, rep, is_new)
        if arrivals:
            from karpenter_tpu.controllers.provisioning.topology import (
                pods_declare_topology,
            )
            from karpenter_tpu.gang import is_gang_pod

            if pods_declare_topology(arrivals):
                raise _DeltaUnsafe("full", "topology")
            if any(
                entry[0].spec.pod_anti_affinity for entry in bound_pods or ()
            ):
                raise _DeltaUnsafe("full", "topology")
            if topology is not None and (
                topology.groups or topology.inverse_groups
            ):
                raise _DeltaUnsafe("full", "topology")
            if topology_factory is not None:
                t = topology_factory(list(arrivals))
                if t.groups or t.inverse_groups:
                    raise _DeltaUnsafe("full", "topology")
            for p in arrivals:
                if is_gang_pod(p):
                    raise _DeltaUnsafe("full", "gang")
                sp = p.spec
                if (
                    sp.host_ports
                    or sp.pvc_names
                    or sp.resource_claims
                    or sp.node_name
                ):
                    raise _DeltaUnsafe("full", "pod_features")
            # kinds whose last pods leave with the retracted suffix GHOST:
            # a re-arriving ghost must take a FRESH id, or its stale
            # (too-small) rank would sort it ahead of kinds that first
            # appear earlier in the new union order
            surviving_kids = None
            if retract_k:
                cut_idx = r["rounds"][-retract_k]["start_idx"]
                surviving_kids = set(r["ranks"][:cut_idx].tolist())
            seen: dict = {}
            next_kid = r["next_kid"]
            for p in arrivals:
                sig = self.sched._kind_sig(p)
                if sig in seen:
                    continue
                kid = r["kind_sig_to_kid"].get(sig)
                if kid is not None and (
                    surviving_kids is not None and kid not in surviving_kids
                ):
                    kid = None  # ghosting with the suffix: register fresh
                if kid is None:
                    if self._grows_vocab(p):
                        raise _DeltaUnsafe("invalidated", "vocab_growth")
                    seen[sig] = (next_kid, p, True)
                    next_kid += 1
                else:
                    seen[sig] = (kid, r["kind_reps"][kid], False)
            plan_kinds = [
                (sig, kid, rep, new) for sig, (kid, rep, new) in seen.items()
            ]
        return dict(
            arrivals=arrivals,
            departed=departed,
            retract_k=retract_k,
            plan_kinds=plan_kinds,
        )

    # -- delta path --------------------------------------------------------

    def _solve_delta(self, plan, deadline=None, now=None) -> SchedulingResult:
        import time as _time

        r = self._r
        sched = self.sched
        arrivals = plan["arrivals"]
        retract_k = plan["retract_k"]

        # ---- validate + encode the arrival delta BEFORE mutating anything
        delta = None
        if arrivals:
            kid_of_sig = {sig: kid for sig, kid, _rep, _new in plan_sorted(plan)}
            local_reps = [rep for _sig, _kid, rep, _new in plan_sorted(plan)]
            local_kids = [kid for _sig, kid, _rep, _new in plan_sorted(plan)]
            local_of_kid = {kid: i for i, kid in enumerate(local_kids)}
            bundles, rep_req_sets = sched._kind_bundles(local_reps)
            # eviction floor: an arrival below any compaction's r_min could
            # have fit a claim the resident state froze
            rmin = r["compact_rmin"]
            if rmin is not None:
                for b in bundles:
                    if not bool(np.all(b["requests"] >= rmin)):
                        raise _DeltaUnsafe("full", "below_eviction_floor")
            from karpenter_tpu.controllers.provisioning.host_scheduler import (
                pod_ffd_key,
            )

            nA = len(arrivals)
            a_ranks = np.empty(nA, dtype=np.int64)
            a_sizes = np.empty(nA, dtype=np.float64)
            for i, p in enumerate(arrivals):
                a_ranks[i] = kid_of_sig[sched._kind_sig(p)]
                a_sizes[i] = pod_ffd_key(p)[1]
            # survivors = session order minus departed (a sorted sequence
            # stays sorted under deletion); prefix check: the cold stable
            # lexsort of the union must keep every survivor in place
            if retract_k:
                cut_idx = r["rounds"][-retract_k]["start_idx"]
            else:
                cut_idx = len(r["order"])
            s_ranks = r["ranks"][:cut_idx]
            s_sizes = r["sizes"][:cut_idx]
            n_surv = len(s_ranks)
            order = np.lexsort(
                (
                    np.concatenate([s_ranks, a_ranks]),
                    -np.concatenate([s_sizes, a_sizes]),
                )
            )
            if not bool((order[:n_surv] == np.arange(n_surv)).all()):
                raise _DeltaUnsafe("full", "ffd_reorder")
            a_order = (order[n_surv:] - n_surv).astype(np.int64)
            arrivals_sorted = [arrivals[i] for i in a_order]
            kids_sorted = a_ranks[a_order]
            sizes_sorted = a_sizes[a_order]
            # segments: runs of identical kinds (contiguous by stable sort)
            seg_list: list = []
            lo = 0
            for i in range(1, nA + 1):
                if i == nA or kids_sorted[i] != kids_sorted[lo]:
                    seg_list.append((lo, i, int(kids_sorted[lo])))
                    lo = i
            delta = dict(
                arrivals_sorted=arrivals_sorted,
                kids_sorted=kids_sorted,
                sizes_sorted=sizes_sorted,
                seg_list=seg_list,
                bundles=bundles,
                rep_req_sets=rep_req_sets,
                local_reps=local_reps,
                local_of_kid=local_of_kid,
            )

        t0 = _time.perf_counter()
        # delta dispatches run under the scheduler's mesh (when it has
        # one) exactly like full solves do in _run_solve: the resident
        # state's sharded window/bank columns stay sharded across rounds
        # instead of re-replicating at the first un-meshed dispatch
        from contextlib import nullcontext

        from karpenter_tpu.faultinject import FAULT

        t_encode = t0
        with sched.mesh if sched.mesh is not None else nullcontext():
            # validate-then-commit: everything above was pure validation;
            # from here the resident state mutates. ANY failure mid-apply
            # (injected via solver.resident.apply or real) must leave the
            # session invalidated-not-poisoned — the half-applied dict is
            # dropped and the round falls back to a full re-solve.
            try:
                # chaos seam before any mutation
                FAULT.point(
                    "solver.resident.apply", stage="begin",
                    arrivals=len(arrivals), retracts=retract_k,
                )
                # ---- 1. retract departed suffix rounds (device + host
                # rollback)
                if retract_k:
                    self._retract(retract_k)
                # mid-apply chaos seam: the retract has already mutated
                # device + host state when this fires
                FAULT.point(
                    "solver.resident.apply", stage="mid",
                    arrivals=len(arrivals), retracts=retract_k,
                )
                # ---- 2. append arrivals through the fill pipeline
                t_encode = _time.perf_counter()
                if delta is not None:
                    self._append(delta)
            except _DeltaUnsafe:
                raise  # _append's own gates already picked their mode
            except Exception as err:
                self._r = None
                raise _DeltaUnsafe(
                    "invalidated", f"apply_error:{type(err).__name__}"
                )
        t_end = _time.perf_counter()
        sched.last_timings = {
            "encode_s": t_encode - t0,
            "device_s": t_end - t_encode,
            "decode_s": 0.0,
        }
        from karpenter_tpu.utils.metrics import RESIDENT_DELTA_PODS

        RESIDENT_DELTA_PODS.observe(len(arrivals) + len(plan["departed"]))
        return SchedulingResult(
            claims=list(r["claims"]),
            unschedulable=[],
            assignments=dict(r["assignments"]),
            existing=r["existing_nodes"],
            existing_assignments=dict(r["existing_assignments"]),
        )

    def _retract(self, k: int) -> None:
        """Suffix undo of the last k (pure) rounds: one retract_tail
        dispatch plus the mirrored host-bookkeeping rollback."""
        r = self._r
        target = r["rounds"][-k]
        cut = int(target["n_open_start"])
        r["state"] = ops_solver.retract_tail(r["state"], jnp.int32(cut))
        claims = r["claims"]
        while claims and claims[-1].slot >= cut:
            c = claims.pop()
            r["slot_to_claim"].pop(c.slot, None)
            r["claim_kinds"].pop(c.slot, None)
            r["claim_pod_counts"][c.slot] = 0
            for p in c.pods:
                r["assignments"].pop(p.uid, None)
        start = target["start_idx"]
        for uid in r["order"][start:]:
            r["pod_by_uid"].pop(uid, None)
        r["order"] = r["order"][:start]
        r["ranks"] = r["ranks"][:start]
        r["sizes"] = r["sizes"][:start]
        # drop kind registrations no surviving pod uses, WITHOUT reusing
        # their ids (monotone ids keep rank order == first-appearance
        # order even when a retracted kind later re-arrives)
        surviving = set(r["ranks"].tolist())
        for rec in r["rounds"][-k:]:
            for kid in rec["new_kids"]:
                if kid not in surviving:
                    rep = r["kind_reps"].pop(kid, None)
                    if rep is not None:
                        r["kind_sig_to_kid"].pop(self.sched._kind_sig(rep), None)
                    r["kind_reqs_c"].pop(kid, None)
                    r["kind_total_c"].pop(kid, None)
                    r["kind_ports_c"].pop(kid, None)
        del r["rounds"][-k:]
        r["hostname_seq"] = len(claims)
        r["n_open"] = cut

    def _append(self, delta: dict) -> None:
        """Encode ONLY the arrival kinds (cache-assembled rows), run ONE
        fill dispatch against the resident state, and extend the session
        bookkeeping through the shared fill decode."""
        from types import SimpleNamespace

        from karpenter_tpu.ops import topology as topo_ops_mod
        from karpenter_tpu.ops.kernels import fetch_tree

        r = self._r
        sched = self.sched
        enc = r["enc"]
        state = r["state"]
        n_claims = r["n_claims"]
        E = enc["E"]
        arrivals_sorted = delta["arrivals_sorted"]
        seg_list = delta["seg_list"]
        bundles = delta["bundles"]
        local_of_kid = delta["local_of_kid"]

        # register arrival kinds up front — the decode's kind memos index
        # them; a later abort (delta_leftover) drops the whole resident,
        # registry included, so early registration cannot leak
        new_kids: list = []
        for kid, i_local in delta["local_of_kid"].items():
            if kid not in r["kind_reps"]:
                rep = delta["local_reps"][i_local]
                r["kind_reps"][kid] = rep
                r["kind_sig_to_kid"][sched._kind_sig(rep)] = kid
                new_kids.append(kid)
        r["next_kid"] = max(r["next_kid"], max(r["kind_reps"]) + 1)

        reqs_k, strict_k, requests_k, it_allow_k, tol_k = sched._stack_bundles(
            bundles
        )
        exist_ok_k = sched._exist_ok_rows(
            delta["local_reps"], delta["rep_req_sets"], r["exist_pristine"], E
        )
        # arrival kinds carry no host ports / CSI volumes (gated), so the
        # packed bitsets are inert rows at the resident lane widths
        M = len(bundles)
        ports_k = np.zeros((M, int(state.claim_ports.shape[1])), dtype=np.uint32)
        vols_k = np.zeros((M, int(state.exist_vols.shape[1])), dtype=np.uint32)
        pod_topo_k, _pod_topo_host = topo_ops_mod.encode_pod_topology(
            Topology(), [], [], delta["local_reps"], strict_k
        )
        B = len(seg_list)
        B_pad = sched._pad_cache.pad(
            "fill_segments", B, step=(8 if B <= 32 else 32)
        )
        kind_ids = np.zeros(B_pad, dtype=np.int64)
        counts = np.zeros(B_pad, dtype=np.int32)
        for j, (lo, hi, kid) in enumerate(seg_list):
            kind_ids[j] = local_of_kid[kid]
            counts[j] = hi - lo
        xs = _gather_fill_xs(
            reqs_k,
            jnp.asarray(requests_k, dtype=jnp.float32),
            jnp.asarray(tol_k),
            jnp.asarray(it_allow_k),
            jnp.asarray(exist_ok_k),
            jnp.asarray(ports_k),
            jnp.asarray(ports_k),
            jnp.asarray(vols_k),
            pod_topo_k,
            jnp.asarray(kind_ids),
            jnp.asarray(counts),
        )
        state, ys = ops_solver.solve_fill(
            state,
            xs,
            enc["exist_tensors"],
            sched.it_tensors,
            enc["template_tensors"],
            sched.well_known,
            enc["topo_tensors"],
            zone_kid=enc["zone_kid"],
            ct_kid=enc["ct_kid"],
            n_claims=n_claims,
        )
        (
            fill_c,
            fill_e,
            open_start,
            n_opened,
            tmpl_arr,
            leftover,
            status,
            slot_map,
            n_open_new,
        ) = fetch_tree(
            [
                ys.fill_c,
                ys.fill_e,
                ys.open_start,
                ys.n_opened,
                ys.tmpl,
                ys.leftover,
                ys.status,
                state.slot_of,
                state.n_open,
            ]
        )
        if int(np.asarray(leftover)[:B].sum()) > 0:
            # an arrival failed (NO_ROOM, window spill, or genuinely
            # unschedulable): the cold path owns relaxation/escalation.
            # State was mutated, but the full re-solve rebuilds from
            # scratch, so dropping the resident is safe.
            self._r = None
            raise _DeltaUnsafe("full", "delta_leftover")
        slot_map_np = np.asarray(slot_map, dtype=np.int64)
        fill_c = np.asarray(fill_c)[:B]
        fill_e = np.asarray(fill_e)[:B]
        open_start = np.asarray(open_start)
        n_opened = np.asarray(n_opened)
        tmpl_arr = np.asarray(tmpl_arr)
        claim_template_map: dict[int, int] = {}
        for j in range(B):
            for w in range(int(open_start[j]), int(open_start[j]) + int(n_opened[j])):
                claim_template_map[int(slot_map_np[w])] = int(tmpl_arr[j])

        def ensure_claim(slot: int) -> SimClaim:
            claim = r["slot_to_claim"].get(slot)
            if claim is None:
                tmpl = sched.templates[claim_template_map[slot]]
                r["hostname_seq"] += 1
                hostname = hostname_placeholder(r["hostname_seq"])
                requirements = tmpl.requirements.copy()
                requirements.add(
                    Requirement.new(l.LABEL_HOSTNAME, Operator.IN, hostname)
                )
                claim = SimClaim(
                    template=tmpl,
                    requirements=requirements,
                    used={},
                    instance_types=[],
                    pods=[],
                    slot=slot,
                    hostname=hostname,
                )
                r["slot_to_claim"][slot] = claim
                r["claims"].append(claim)
                r["claim_kinds"][slot] = {}
            return claim

        round_unsched: list = []
        ctx = SimpleNamespace(
            E=E,
            NC1=np.int64(n_claims + 1),
            existing_nodes=r["existing_nodes"],
            pods_sorted=arrivals_sorted,
            ensure_claim=ensure_claim,
            slot_to_claim=r["slot_to_claim"],
            claim_kinds=r["claim_kinds"],
            claim_pod_counts=r["claim_pod_counts"],
            assignments=r["assignments"],
            existing_assignments=r["existing_assignments"],
            unschedulable=round_unsched,
            node_kinds=r["node_kinds"],
            kind_ports=self._kind_ports,
            kind_total=self._kind_total,
        )
        f = {
            "fill_c": fill_c,
            "fill_e": fill_e,
            "open_start": open_start,
            "n_opened": n_opened,
            "status": np.asarray(status),
            "slot_map": slot_map_np,
        }
        _decode_fill_segments(ctx, seg_list, f)
        assert not round_unsched, "leftover check missed a failure"
        # existing-node requirement intersections for kinds that landed
        # tier-1 this round (idempotent adds, like the cold finalization)
        if fill_e.any():
            for j, (lo, hi, kid) in enumerate(seg_list):
                for e in np.flatnonzero(fill_e[j]).tolist():
                    r["existing_nodes"][e].requirements.add(
                        *self._kind_reqs(kid).values()
                    )
        # ---- refresh the touched claims' device-carried columns ----------
        js, ss = np.nonzero(fill_c)
        pre_n_open = r["n_open"]
        rows = sorted(
            {int(s) for s in ss}
            | {
                w
                for j in range(B)
                for w in range(
                    int(open_start[j]), int(open_start[j]) + int(n_opened[j])
                )
            }
        )
        if rows:
            rows_np = np.asarray(rows, dtype=np.int64)
            u_rows, i_rows = fetch_tree(
                [state.used[rows_np], state.its[rows_np]]
            )
            self._finalize_touched(
                [int(slot_map_np[w]) for w in rows],
                np.asarray(u_rows),
                np.asarray(i_rows),
            )
        # ---- commit session bookkeeping ----------------------------------
        pure = not bool(fill_e.any()) and all(
            int(slot_map_np[s]) >= pre_n_open for s in ss
        )
        start_idx = len(r["order"])
        r["order"].extend(p.uid for p in arrivals_sorted)
        r["pod_by_uid"].update({p.uid: p for p in arrivals_sorted})
        r["ranks"] = np.concatenate([r["ranks"], delta["kids_sorted"]])
        r["sizes"] = np.concatenate([r["sizes"], delta["sizes_sorted"]])
        r["rounds"].append(
            dict(
                uids={p.uid for p in arrivals_sorted},
                start_idx=start_idx,
                n_open_start=pre_n_open,
                pure=pure,
                new_kids=new_kids,
                sig=self._round_sig(
                    (p.uid for p in arrivals_sorted), pre_n_open
                ),
            )
        )
        r["n_open"] = int(n_open_new)
        r["state"] = state

    def _finalize_touched(self, touched_slots, used_rows, its_rows) -> None:
        """Rebuild used / viable instance types / requirements for claims
        the delta touched, from the device carry — the cold finalization's
        memoized per-(template, kind-set) pattern, minus the topology
        narrowing fold (sessions are topology-free)."""
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            finalize_reserved,
        )

        r = self._r
        rids = self.sched.encoder._resource_ids
        for slot, urow, irow in zip(touched_slots, used_rows, its_rows):
            claim = r["slot_to_claim"][slot]
            kinds = r["claim_kinds"][slot]
            ksig = tuple(sorted(kinds))
            tid = id(claim.template)
            memo = r["proto_cache"].get((tid, ksig))
            if memo is None:
                proto = claim.template.requirements.copy()
                names = set(claim.template.daemon_requests)
                for k in ksig:
                    proto.add(*self._kind_reqs(k).values())
                    names.update(self._kind_total(k))
                names = sorted(names)
                ridx = np.array([rids[n] for n in names], dtype=np.int64)
                memo = r["proto_cache"][(tid, ksig)] = (proto, names, ridx)
            proto, names, ridx = memo
            reqs = proto.copy()
            reqs.add(
                Requirement.new(l.LABEL_HOSTNAME, Operator.IN, claim.hostname)
            )
            claim.requirements = reqs
            vec = np.asarray(urow)[ridx]
            claim.used = dict(zip(names, vec.tolist()))
            row = np.asarray(irow)
            ikey = (tid, row.tobytes())
            sel_list = r["its_cache"].get(ikey)
            if sel_list is None:
                t_its, t_cat_idx = self.sched._template_it_index(claim.template)
                sel = np.flatnonzero(row[t_cat_idx])
                sel_list = r["its_cache"][ikey] = [t_its[i] for i in sel.tolist()]
            claim.instance_types = list(sel_list)
            finalize_reserved(claim)


def plan_sorted(plan: dict) -> list:
    """The plan's kind entries in first-appearance (kid) order — the
    local tensor axis the delta dispatch gathers from."""
    return sorted(plan["plan_kinds"], key=lambda t: t[1])
