"""Exact-semantics host scheduler — the oracle for the TPU engine.

A faithful Python rendering of the reference's Solve loop
(scheduler.go:440-790, nodeclaim.go:124-242, nodeclaim.go:541): FFD pod
order, in-flight claims retried fewest-pods-first with earliest-index
tie-break, per-claim viable-instance-type filtering by the
compat × fits × hasOffering triple mask, weight-ordered template fallback.

Deliberately simple and allocation-happy: correctness oracle first, CPU
fallback second. The TPU engine (scheduler.py) must match its packing
exactly on featured-covered problems.
"""

from __future__ import annotations

import numpy as np

import time as _time
from dataclasses import dataclass, field
from typing import Optional

from karpenter_tpu.cloudprovider.instancetype import AllocatableOfferings, InstanceType
from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import ClaimTemplate
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.scheduling import Operator, Requirement, Requirements
from karpenter_tpu.scheduling.reservations import ReservedOfferingError, offerings_to_reserve
from karpenter_tpu.scheduling.taints import tolerates_all

if False:  # typing-only import to avoid a cycle
    from karpenter_tpu.controllers.provisioning.topology import Topology
from karpenter_tpu.utils import resources as res

# Unschedulable reason stamped on pods the Solve deadline cut off
# (provisioner.go:415: the 1m context expires and the queue drains with
# ctx.Err() per remaining pod).
SOLVE_TIMEOUT_REASON = "scheduling timeout exceeded"


@dataclass
class SimClaim:
    """One simulated in-flight NodeClaim."""

    template: ClaimTemplate
    requirements: Requirements
    used: dict[str, float]
    instance_types: list[InstanceType]
    pods: list[Pod] = field(default_factory=list)
    slot: int = 0
    hostname: str = ""  # placeholder hostname (nodeclaim.go:93)
    host_ports: list[tuple] = field(default_factory=list)
    # reservation ids this claim pessimistically holds (nodeclaim.go:52-60)
    reserved_ids: frozenset = frozenset()
    # BestEffort minValues relaxation happened (scheduler.go:769)
    min_values_relaxed: bool = False
    # gang key when this claim is one host of a dedicated multi-host slice
    # (gang claims never accept tier-2 adds; disruption treats the slice's
    # claim group atomically)
    gang: Optional[str] = None

    def cheapest_launch(self) -> tuple[Optional[InstanceType], float]:
        """Cheapest (type, price) among viable types/offerings compatible
        with the final requirements (kwok Create behavior)."""
        best_it, best_price = None, float("inf")
        for it in self.instance_types:
            p = it.cheapest_offering_price(self.requirements)
            if p < best_price:
                best_it, best_price = it, p
        return best_it, best_price


@dataclass
class ExistingSimNode:
    """Tier-1 candidate: an existing or in-flight real node
    (existingnode.go:32-75). requirements seed from the node's labels (incl.
    hostname) and evolve as pods land; available is allocatable minus
    current pods minus remaining daemon overhead."""

    name: str
    index: int
    requirements: Requirements
    available: dict[str, float]
    taints: list = field(default_factory=list)
    used: dict[str, float] = field(default_factory=dict)
    pods: list[Pod] = field(default_factory=list)
    host_ports: list[tuple] = field(default_factory=list)  # (ip, port, proto)
    # CSI attach tracking seeded from the live node (statenode.go:411);
    # None = no limits published, unconstrained
    volume_usage: object = None

    def clone(self) -> "ExistingSimNode":
        """Pristine copy for simulation retries (relaxation loop)."""
        return ExistingSimNode(
            name=self.name,
            index=self.index,
            requirements=self.requirements.copy(),
            available=dict(self.available),
            taints=list(self.taints),
            used=dict(self.used),
            pods=list(self.pods),
            host_ports=list(self.host_ports),
            volume_usage=self.volume_usage.copy() if self.volume_usage is not None else None,
        )


@dataclass
class SchedulingResult:
    claims: list[SimClaim]
    unschedulable: list[tuple[Pod, str]]
    assignments: dict[str, int]  # pod uid -> claim slot
    existing: list[ExistingSimNode] = field(default_factory=list)
    existing_assignments: dict[str, str] = field(default_factory=dict)  # pod uid -> node name
    # the winning round's DRARound (device allocation metadata), when DRA ran
    dra: object = None
    # relaxation-ladder provenance (explainer): pod uid -> the rung names
    # the shared ladder shed before this result (empty on the happy path)
    relaxations: dict = field(default_factory=dict)

    @property
    def node_count(self) -> int:
        return len(self.claims)

    def total_price(self) -> float:
        return sum(c.cheapest_launch()[1] for c in self.claims)


def hostname_placeholder(seq: int) -> str:
    """Simulation-only hostname for new claims (nodeclaim.go:93); shared by
    both engines so hostname-domain bookkeeping lines up."""
    return f"hostname-placeholder-{seq:04d}"


def finalize_reserved(claim: SimClaim) -> None:
    """FinalizeScheduling's reserved-capacity injection (nodeclaim.go:385-
    401): a claim holding reservations is pinned to capacity-type=reserved
    + its reservation ids so multiple claims never over-launch into one
    reservation. Shared by both engines' decode paths."""
    if not claim.reserved_ids:
        return
    from karpenter_tpu.cloudprovider.instancetype import RESERVATION_ID_LABEL

    claim.requirements.add(
        Requirement.new(l.CAPACITY_TYPE_LABEL_KEY, Operator.IN, l.CAPACITY_TYPE_RESERVED)
    )
    claim.requirements.add(
        Requirement.new(RESERVATION_ID_LABEL, Operator.IN, *sorted(claim.reserved_ids))
    )


def normalize_volume_reqs(volume_reqs: Optional[dict]) -> dict:
    """uid -> non-empty list[Requirements] alternatives (drops None/empty)."""
    return {uid: list(v) for uid, v in (volume_reqs or {}).items() if v}


def _canon_terms(terms) -> tuple:
    """Affinity/TSC term lists with their label_selector dicts sorted by
    key, so content-equal pods built with different key order share a
    kind; every other term field rides along positionally."""
    import dataclasses

    out = []
    for t in terms:
        row = []
        for f in dataclasses.fields(t):
            v = getattr(t, f.name)
            if isinstance(v, dict):
                v = tuple(sorted(v.items()))
            elif isinstance(v, list):
                v = tuple(v)
            row.append(v)
        out.append(tuple(row))
    return tuple(out)


# Content-sig intern table: the full canonical tuples are large, and
# hashing them on every dict lookup dominates encode time at 100k pods.
# Interning returns a small int whose hash is free. Deployment-shaped
# workloads keep the table tiny, but long-running control planes see
# unbounded distinct contents (pod-template-hash churn), so the table is
# evicted past a bound. The token COUNTER never resets: tokens stay
# process-unique, so a pre-eviction token cached on a live pod can never
# alias a post-eviction content (equal contents merely stop deduping
# across an eviction — a perf, not correctness, event).
_SIG_IDS: dict[tuple, int] = {}
_SIG_LIMIT = 1 << 18
_sig_next = 0


def _intern_sig(s: tuple) -> int:
    global _sig_next
    tok = _SIG_IDS.get(s)
    if tok is None:
        if len(_SIG_IDS) >= _SIG_LIMIT:
            _SIG_IDS.clear()
        tok = _SIG_IDS[s] = _sig_next
        _sig_next += 1
    return tok


def pod_content_sig(pod: Pod) -> int:
    """Canonical content signature for pod-kind grouping, cached on the pod
    object (pod specs are immutable post-construction, matching Kubernetes;
    the preference-relaxation ladder derives NEW pod copies and drops the
    cache). Two pods with equal signatures produce identical rows in every
    encoded problem tensor. Dict-typed fields are canonicalized by sorted
    key (insertion order must not split kinds); list-typed fields keep
    their order (it is semantically meaningful for relaxation ladders).
    Returns an interned int token: equal token <=> equal content."""
    s = pod.__dict__.get("_ktpu_sig")
    if s is None:
        sp = pod.spec
        s = (
            tuple(sorted(sp.requests.items())),
            tuple(sorted(sp.limits.items())),
            tuple(sorted(sp.node_selector.items())),
            repr(sp.node_affinity),
            _canon_terms(sp.pod_affinity),
            _canon_terms(sp.pod_anti_affinity),
            _canon_terms(sp.preferred_pod_affinity),
            _canon_terms(sp.preferred_pod_anti_affinity),
            _canon_terms(sp.topology_spread_constraints),
            repr(sp.tolerations),
            repr(sp.host_ports),
            sp.node_name,
            sp.priority,
            tuple(sp.pvc_names),
            tuple(sp.resource_claims),
            sp.termination_grace_period_seconds,
            tuple(sorted(pod.metadata.labels.items())),
            pod.metadata.namespace,  # topology groups are per-namespace
        )
        s = _intern_sig(s)
        pod.__dict__["_ktpu_sig"] = s
    return s


def pod_ffd_key(pod: Pod) -> tuple[int, float]:
    """(content sig, FFD size) fused and cached together — the per-pod work
    of the solve's hot sort loop collapses to one dict lookup on warm
    paths (same invalidation contract as pod_content_sig: relaxation
    copies drop the cache)."""
    key = pod.__dict__.get("_ktpu_ffd")
    if key is None:
        req = pod.spec.requests
        key = (
            pod_content_sig(pod),
            req.get(res.CPU, 0.0) + req.get(res.MEMORY, 0.0) / (4.0 * 2**30),
        )
        pod.__dict__["_ktpu_ffd"] = key
    return key


def gather_ffd_keys(pods: list, sigs: np.ndarray, sizes: np.ndarray) -> None:
    """Fill sigs/sizes (len >= len(pods)) with each pod's FFD key: the C
    gather reads the warm caches in one pass, then only the -1 sentinel
    misses (new pods) pay the Python path — which also populates their
    caches for the next solve. Shared by ffd_sort and the encode."""
    from karpenter_tpu import native

    n = len(pods)
    if native.ffd_keys is not None and n and isinstance(pods, list):
        if native.ffd_keys(pods, sigs[:n], sizes[:n]):
            for i in np.flatnonzero(sigs[:n] == -1):
                sigs[i], sizes[i] = pod_ffd_key(pods[i])
        return
    for i, p in enumerate(pods):
        sigs[i], sizes[i] = pod_ffd_key(p)


def ffd_sort(pods: list[Pod]) -> list[Pod]:
    """CPU+memory descending (queue.go:72-90), ties grouped by pod kind in
    first-appearance order (the reference's sort is unstable on ties, so
    any tie order is within its semantics; grouping makes identical pods
    contiguous, which the kind-level batch placement path relies on).
    Shared by both engines so their pod orders are identical. One pass
    collects keys into arrays and np.lexsort does the ordering (both
    lexsort and the previous sorted() are stable, so the order is
    unchanged — this is purely the vectorized form)."""
    n = len(pods)
    sizes = np.empty(n, dtype=np.float64)
    sigs = np.empty(n, dtype=np.int64)
    gather_ffd_keys(list(pods), sigs, sizes)
    # first-appearance rank per sig (vectorized; stable like the dict walk)
    _, first, inv = np.unique(sigs, return_index=True, return_inverse=True)
    ranks = np.argsort(np.argsort(first))[inv]
    order = np.lexsort((ranks, -sizes))
    return [pods[i] for i in order]


def filter_instance_types(
    its: list[InstanceType],
    requirements: Requirements,
    total_requests: dict[str, float],
    relax_min_values: bool = False,
) -> list[InstanceType]:
    """The inner kernel (nodeclaim.go:541): keep types where requirements
    intersect AND requests fit an allocatable group AND that group has a
    compatible available offering.

    relax_min_values (MinValuesPolicy=BestEffort, nodeclaim.go:606-613):
    unmet minValues floors keep the surviving set instead of emptying it;
    the achievable floors are written back at finalize."""
    remaining = []
    for it in its:
        if not it.requirements.intersects_ok(requirements):
            continue
        if _fits_and_offering(it.allocatable_offerings(), requirements, total_requests):
            remaining.append(it)
    # minValues (nodeclaim.go:606-617, Strict policy): the surviving set
    # must retain enough distinct values per min-keyed requirement
    if remaining and requirements.has_min_values() and not relax_min_values:
        from karpenter_tpu.cloudprovider.instancetype import satisfies_min_values

        _, _, err = satisfies_min_values(remaining, requirements)
        if err:
            return []
    return remaining


def finalize_min_values(claim: SimClaim) -> None:
    """BestEffort bookkeeping at the end of a solve (scheduler.go:763-772 +
    nodeclaim.go:214-219): floors the final viable set cannot meet are
    lowered to the achievable distinct-value count and the claim is
    flagged relaxed. No-op for satisfiable floors (and always a no-op
    under Strict, where unmet floors never survive the filter)."""
    reqs = claim.requirements
    if not reqs.has_min_values():
        return
    from karpenter_tpu.cloudprovider.instancetype import satisfies_min_values

    _, unsat, err = satisfies_min_values(claim.instance_types, reqs)
    if not err:
        return
    for key, achievable in unsat.items():
        reqs.relax_min_values(key, achievable)
    claim.min_values_relaxed = True


def _fits_and_offering(
    groups: list[AllocatableOfferings], requirements: Requirements, requests: dict[str, float]
) -> bool:
    for group in groups:
        if not res.fits(requests, group.allocatable):
            continue
        for o in group.offerings:
            if requirements.is_compatible(o.requirements, l.WELL_KNOWN_LABELS):
                return True
    return False


class HostScheduler:
    def __init__(
        self,
        templates: list[ClaimTemplate],
        existing_nodes: Optional[list[ExistingSimNode]] = None,
        budgets: Optional[dict[str, dict[str, float]]] = None,
        topology: Optional["Topology"] = None,
        volume_reqs: Optional[dict] = None,
        reserved_mode: str = "fallback",
        reserved_capacity_enabled: bool = True,
        min_values_policy: str = "Strict",
        reserved_in_use: Optional[dict[str, int]] = None,
        dra_problem=None,
        pod_volumes: Optional[dict] = None,
        deadline: Optional[float] = None,
        now=None,
    ):
        """budgets: nodepool -> remaining resources (limits minus current
        usage; may include the synthetic 'nodes' count). Absent pool =
        unlimited. topology: pre-built Topology (counts seeded from the
        live cluster); None disables topology handling. volume_reqs: pod
        uid -> PVC-implied topology alternatives (list[Requirements]).
        pod_volumes: pod uid -> CSI Volumes (driver -> pvc ids) for
        attach-limit checks. reserved_mode: strict fails adds that would
        lose reserved capacity (scheduler.go:59-78); fallback lets them
        fall through to spot/on-demand."""
        from karpenter_tpu.controllers.provisioning.topology import Topology as _T

        self.templates = templates
        self.existing_nodes = existing_nodes or []
        self.budgets = {k: dict(v) for k, v in (budgets or {}).items()}
        self.topology = topology if topology is not None else _T()
        self.volume_reqs = normalize_volume_reqs(volume_reqs)
        self.pod_volumes = pod_volumes or {}
        self.reserved_mode = reserved_mode
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.min_values_policy = min_values_policy
        self.reserved_in_use = reserved_in_use or {}
        self.dra_problem = dra_problem  # scheduling.dra.integration.DRAProblem
        # Solve deadline (provisioner.go:415 1m context): checked at the top
        # of every pod iteration like the reference's ctx.Err() poll, so an
        # expired solve fails the REMAINING queue, not the placed prefix.
        self.deadline = deadline
        self.now = now if now is not None else _time.monotonic
        self._dra = None
        self._rm = None
        self._hostname_seq = 0
        for node in self.existing_nodes:
            self.topology.register(l.LABEL_HOSTNAME, node.name)

    def _build_rm(self):
        """Fresh per-round ReservationManager (scheduler.go:187) — None
        when the gate is off or no reserved offerings exist."""
        from karpenter_tpu.scheduling.reservations import ReservationManager

        if not self.reserved_capacity_enabled:
            return None
        seen: dict[str, object] = {}
        for t in self.templates:
            for it in t.instance_types:
                seen.setdefault(it.name, it)
        rm = ReservationManager(seen.values())
        # ids pinned by in-flight claims the provider hasn't launched yet
        for rid, n in self.reserved_in_use.items():
            if rid in rm.capacity:
                rm.capacity[rid] = max(rm.capacity[rid] - n, 0)
        return rm if rm.capacity else None

    def _reserve_for(
        self,
        hostname: str,
        remaining: "list[InstanceType]",
        tightened: Requirements,
        held_ids: frozenset,
    ) -> Optional[frozenset]:
        """Reserved-capacity accounting shared by the in-flight and
        new-claim paths (nodeclaim.go:256-262, 304-349): reserve every
        compatible reservable offering, release held ids the tightened
        requirements no longer reach. Returns the new held-id set, or None
        when Strict mode would lose reservations."""
        try:
            ofs = offerings_to_reserve(
                self._rm, hostname, remaining, tightened, held_ids, self.reserved_mode
            )
        except ReservedOfferingError:
            return None
        new_ids = frozenset(o.reservation_id for o in ofs)
        if self._rm is not None:
            self._rm.reserve(hostname, ofs)
            self._rm.release(hostname, *(held_ids - new_ids))
        return new_ids

    def _next_hostname(self) -> str:
        self._hostname_seq += 1
        return hostname_placeholder(self._hostname_seq)

    # -- tier 1: existing nodes (existingnode.go:84-135) ---------------------

    def _alternatives_for(self, pod: Pod) -> list:
        """The pod's volume-topology alternatives, or [None] when
        unconstrained (nodeclaim.go:140-147: a single nil entry)."""
        alts = self.volume_reqs.get(pod.uid)
        return list(alts) if alts else [None]

    def can_add_existing(
        self, node: ExistingSimNode, pod: Pod, pod_reqs: Requirements, strict: Requirements
    ) -> bool:
        from karpenter_tpu.scheduling import hostports as hp

        if tolerates_all(node.taints, pod.spec.tolerations) is not None:
            return False
        # CSI attach limits before anything stateful (existingnode.go:88)
        pod_vols = self.pod_volumes.get(pod.uid)
        if pod_vols and node.volume_usage is not None:
            if node.volume_usage.exceeds_limits(pod_vols) is not None:
                return False
        if hp.conflicts(node.host_ports, pod):
            return False
        total = res.merge(node.used, pod.total_requests())
        if not res.fits(total, node.available):
            return False
        # strict Compatible: no AllowUndefinedWellKnownLabels
        if node.requirements.compatible(pod_reqs) is not None:
            return False
        for volreq in self._alternatives_for(pod):
            if self._try_alternative_existing(node, pod, pod_reqs, strict, volreq, total):
                if pod_vols and node.volume_usage is not None:
                    node.volume_usage.add(pod.uid, pod_vols)
                return True
        return False

    def _try_alternative_existing(
        self,
        node: ExistingSimNode,
        pod: Pod,
        pod_reqs: Requirements,
        strict: Requirements,
        volreq,
        total: dict,
    ) -> bool:
        """One volume alternative against an existing node
        (existingnode.go:143-168 tryVolumeAlternative): the alternative
        tightens the NODE requirements only, never the pod's affinity, so
        TSC counting stays on the pod's own spec."""
        from karpenter_tpu.scheduling import hostports as hp

        base = node.requirements.copy()
        base.add(*pod_reqs.values())
        if volreq is not None:
            if base.compatible(volreq, l.WELL_KNOWN_LABELS) is not None:
                return False
            base.add(*volreq.values())
        alloc = None
        if self._dra is not None and pod.spec.resource_claims:
            # existing node: single collapsed instance type, published
            # (in-cluster) slices only (existingnode.go:81-135)
            alloc = self._dra.try_allocate_existing(pod, node.name, base)
            if alloc is None:
                return False
            if base.compatible(alloc.requirements, l.WELL_KNOWN_LABELS) is not None:
                return False
            base.add(*alloc.requirements.values())
        tightened = self.topology.add_requirements(pod, strict, base)
        if tightened is None or base.compatible(tightened) is not None:
            return False
        if alloc is not None:
            self._dra.commit(alloc, node.name, set(alloc.instance_types))
        node.requirements = tightened
        node.used = total
        node.pods.append(pod)
        node.host_ports.extend(hp.port_key(h) for h in pod.spec.host_ports)
        self.topology.record(pod, tightened)
        return True

    def can_add(
        self, claim: SimClaim, pod: Pod, pod_reqs: Requirements, strict: Requirements
    ) -> Optional[SimClaim]:
        """Feasibility of adding pod to claim (nodeclaim.go:124-242);
        returns the updated claim state or None. On success the topology
        counts are recorded — callers must commit the returned claim.
        Volume alternatives are tried in order, first success wins
        (nodeclaim.go:149-161)."""
        from karpenter_tpu.scheduling import hostports as hp

        if tolerates_all(claim.template.taints, pod.spec.tolerations) is not None:
            return None
        if hp.conflicts(claim.host_ports, pod):
            return None
        if claim.requirements.compatible(pod_reqs, l.WELL_KNOWN_LABELS) is not None:
            return None
        for volreq in self._alternatives_for(pod):
            updated = self._try_alternative_claim(claim, pod, pod_reqs, strict, volreq)
            if updated is not None:
                return updated
        return None

    def _try_alternative_claim(
        self, claim: SimClaim, pod: Pod, pod_reqs: Requirements, strict: Requirements, volreq
    ) -> Optional[SimClaim]:
        """One volume alternative against an in-flight claim
        (nodeclaim.go:163-242 tryVolumeAlternative)."""
        from karpenter_tpu.scheduling import hostports as hp

        combined = claim.requirements.copy()
        combined.add(*pod_reqs.values())
        if volreq is not None:
            if combined.compatible(volreq, l.WELL_KNOWN_LABELS) is not None:
                return None
            combined.add(*volreq.values())
        # DRA device allocation runs before topology so contributed device
        # topology feeds the full filtering pipeline (nodeclaim.go:179-192)
        alloc = None
        if self._dra is not None and pod.spec.resource_claims:
            alloc = self._dra.try_allocate(
                pod, claim.hostname, claim.template.nodepool_name, combined, claim.instance_types
            )
            if alloc is None:
                return None
            if combined.compatible(alloc.requirements, l.WELL_KNOWN_LABELS) is not None:
                return None
            combined.add(*alloc.requirements.values())
        # topology comes last: it may collapse a key to a single domain
        # (nodeclaim.go:199-210)
        tightened = self.topology.add_requirements(pod, strict, combined)
        if tightened is None or combined.compatible(tightened, l.WELL_KNOWN_LABELS) is not None:
            return None
        total = res.merge(claim.used, pod.total_requests())
        remaining = filter_instance_types(
            claim.instance_types, tightened, total,
            relax_min_values=self.min_values_policy == "BestEffort",
        )
        if alloc is not None:
            # only instance types whose device allocation succeeded survive
            # (nodeclaim.go:226-237)
            surviving = set(alloc.instance_types)
            remaining = [it for it in remaining if it.name in surviving]
        if not remaining:
            return None
        new_ids = self._reserve_for(claim.hostname, remaining, tightened, claim.reserved_ids)
        if new_ids is None:
            return None
        if alloc is not None:
            self._dra.commit(alloc, claim.hostname, {it.name for it in remaining})
        self.topology.record(pod, tightened)
        return SimClaim(
            template=claim.template,
            requirements=tightened,
            used=total,
            instance_types=remaining,
            pods=claim.pods + [pod],
            slot=claim.slot,
            hostname=claim.hostname,
            host_ports=claim.host_ports + [hp.port_key(h) for h in pod.spec.host_ports],
            reserved_ids=new_ids,
        )

    def _within_budget(self, tmpl: ClaimTemplate, its: list[InstanceType]) -> list[InstanceType]:
        """filterByRemainingResources (scheduler.go:1068): exclude types
        whose full capacity would breach the pool's remaining limits."""
        budget = self.budgets.get(tmpl.nodepool_name)
        if budget is None:
            return its
        return [
            it
            for it in its
            if all(it.capacity.get(k, 0.0) <= v for k, v in budget.items() if k != "nodes")
        ]

    def _charge_budget(self, tmpl: ClaimTemplate, its: list[InstanceType]) -> None:
        """subtractMax (scheduler.go:791): reserve the max capacity over the
        claim's viable types."""
        budget = self.budgets.get(tmpl.nodepool_name)
        if budget is None:
            return
        for k in list(budget):
            if k == "nodes":
                budget[k] -= 1.0
            else:
                budget[k] -= max((it.capacity.get(k, 0.0) for it in its), default=0.0)

    def try_new_claim(
        self, pod: Pod, pod_reqs: Requirements, strict: Requirements, slot: int
    ) -> Optional[SimClaim]:
        for tmpl in self.templates:  # weight order (scheduler.go:695)
            budget = self.budgets.get(tmpl.nodepool_name)
            if budget is not None and budget.get("nodes", 1.0) < 1.0:
                continue  # node limits exhausted (scheduler.go:711-714)
            if tolerates_all(tmpl.taints, pod.spec.tolerations) is not None:
                continue
            if tmpl.requirements.compatible(pod_reqs, l.WELL_KNOWN_LABELS) is not None:
                continue
            # every new claim gets a placeholder hostname so hostname
            # topologies see it as a fresh domain (nodeclaim.go:93-97)
            hostname = self._next_hostname()
            claim = None
            for volreq in self._alternatives_for(pod):
                claim = self._try_alternative_new(tmpl, pod, pod_reqs, strict, volreq, slot, hostname)
                if claim is not None:
                    break
            if claim is None:
                self._hostname_seq -= 1  # hostname not consumed
                continue
            return claim
        return None

    def _try_alternative_new(
        self,
        tmpl: ClaimTemplate,
        pod: Pod,
        pod_reqs: Requirements,
        strict: Requirements,
        volreq,
        slot: int,
        hostname: str,
    ) -> Optional[SimClaim]:
        combined = tmpl.requirements.copy()
        combined.add(Requirement.new(l.LABEL_HOSTNAME, Operator.IN, hostname))
        combined.add(*pod_reqs.values())
        if volreq is not None:
            if combined.compatible(volreq, l.WELL_KNOWN_LABELS) is not None:
                return None
            combined.add(*volreq.values())
        alloc = None
        if self._dra is not None and pod.spec.resource_claims:
            alloc = self._dra.try_allocate(
                pod, hostname, tmpl.nodepool_name, combined, tmpl.instance_types
            )
            if alloc is None or combined.compatible(alloc.requirements, l.WELL_KNOWN_LABELS) is not None:
                return None
            combined.add(*alloc.requirements.values())
        tightened = self.topology.add_requirements(pod, strict, combined)
        if tightened is None or combined.compatible(tightened, l.WELL_KNOWN_LABELS) is not None:
            return None
        total = res.merge(tmpl.daemon_requests, pod.total_requests())
        candidates = self._within_budget(tmpl, tmpl.instance_types)
        remaining = filter_instance_types(
            candidates, tightened, total,
            relax_min_values=self.min_values_policy == "BestEffort",
        )
        if alloc is not None:
            surviving = set(alloc.instance_types)
            remaining = [it for it in remaining if it.name in surviving]
        if not remaining:
            return None
        new_ids = self._reserve_for(hostname, remaining, tightened, frozenset())
        if new_ids is None:
            return None
        if alloc is not None:
            self._dra.commit(alloc, hostname, {it.name for it in remaining})
        self._charge_budget(tmpl, remaining)
        self.topology.register(l.LABEL_HOSTNAME, hostname)
        self.topology.record(pod, tightened)
        from karpenter_tpu.scheduling import hostports as hp

        return SimClaim(
            template=tmpl,
            requirements=tightened,
            used=total,
            instance_types=remaining,
            pods=[pod],
            slot=slot,
            hostname=hostname,
            host_ports=[hp.port_key(h) for h in pod.spec.host_ports],
            reserved_ids=new_ids,
        )

    def solve(self, pods: list[Pod]) -> SchedulingResult:
        """Solve with the shared preference relaxation ladder; per-round
        state (existing nodes, budgets, topology counts) is snapshotted so
        retries start pristine."""
        import copy as _copy

        from karpenter_tpu.controllers.provisioning import preferences as prefs

        base_existing = [n.clone() for n in self.existing_nodes]
        base_budgets = {k: dict(v) for k, v in self.budgets.items()}
        base_topology = _copy.deepcopy(self.topology)

        def solve_round(current: list[Pod]) -> SchedulingResult:
            self.existing_nodes = [n.clone() for n in base_existing]
            self.budgets = {k: dict(v) for k, v in base_budgets.items()}
            self.topology = _copy.deepcopy(base_topology)
            self._hostname_seq = 0
            return self._solve_once(current)

        def should_stop() -> bool:
            return self.deadline is not None and self.now() >= self.deadline

        return prefs.run_with_relaxation(list(pods), solve_round, should_stop)

    # -- gang placement (the host gang oracle; ops/solver.py solve_gang twin) --

    def _place_gang(
        self,
        gang,
        claims: list[SimClaim],
        assignments: dict[str, int],
        unschedulable: list[tuple[Pod, str]],
    ) -> None:
        """All-or-nothing slice placement: the gang's members land on
        ``ceil(size / f)`` freshly-opened dedicated claims of ONE
        weight-ordered template (rank r -> host r // f, contiguous rank
        blocks), or every member fails together. State mutated by a
        partial attempt (topology counts, budgets, hostnames,
        reservations) is rolled back, so no partial placement is ever
        observable."""
        import copy as _copy

        from karpenter_tpu.gang import GANG_SPILL_REASON, oracle as gang_oracle
        from karpenter_tpu.scheduling import hostports as hp

        pods = gang.pods_in_rank_order()
        count = len(pods)
        rep = pods[0]
        if self._dra is not None and rep.spec.resource_claims:
            for p in pods:
                unschedulable.append(
                    (p, "gang pods with resource claims are not supported")
                )
            return
        pod_reqs = Requirements.from_pod(rep)
        strict = Requirements.from_pod(rep, include_preferred=False)
        volalts = self.volume_reqs.get(rep.uid)
        relax_mv = self.min_values_policy == "BestEffort"
        chosen = None
        for tmpl in self.templates:  # weight order, like try_new_claim
            budget = self.budgets.get(tmpl.nodepool_name)
            if budget is not None and budget.get("nodes", 1.0) < 1.0:
                continue
            if tolerates_all(tmpl.taints, rep.spec.tolerations) is not None:
                continue
            if tmpl.requirements.compatible(pod_reqs, l.WELL_KNOWN_LABELS) is not None:
                continue
            combined = gang_oracle.gang_requirements(tmpl, pod_reqs)
            if volalts:
                alt = volalts[0]
                if combined.compatible(alt, l.WELL_KNOWN_LABELS) is not None:
                    continue
                combined.add(*alt.values())
            candidates = self._within_budget(tmpl, tmpl.instance_types)
            total1 = res.merge(tmpl.daemon_requests, rep.total_requests())
            remaining1 = filter_instance_types(
                candidates, combined, total1, relax_min_values=relax_mv
            )
            if not remaining1:
                continue
            chosen = (tmpl, combined, candidates, remaining1)
            break
        if chosen is None:
            for p in pods:
                unschedulable.append((p, "no compatible in-flight claim or template"))
            return
        tmpl, combined, candidates, remaining1 = chosen
        f = gang_oracle.slice_capacity(
            remaining1,
            combined,
            tmpl.daemon_requests,
            rep.total_requests(),
            host_ports=bool(rep.spec.host_ports),
        )
        want = gang_oracle.hosts_needed(count, f)
        if want == 0:
            for p in pods:
                unschedulable.append((p, "no compatible in-flight claim or template"))
            return
        budget = self.budgets.get(tmpl.nodepool_name)
        if budget is not None and budget.get("nodes", float("inf")) < want:
            # a constraint no slot escalation can fix: the whole gang spills
            for p in pods:
                unschedulable.append((p, GANG_SPILL_REASON))
            return
        # snapshot the state a partial attempt could dirty
        topo_snapshot = _copy.deepcopy(self.topology)
        budgets_snapshot = {k: dict(v) for k, v in self.budgets.items()}
        hostname_seq0 = self._hostname_seq
        new_claims: list[SimClaim] = []
        ok = True
        for block in gang_oracle.rank_blocks(pods, f):
            hostname = self._next_hostname()
            tightened = combined.copy()
            tightened.add(gang_oracle.hostname_requirement(hostname))
            for p in block:
                t2 = self.topology.add_requirements(p, strict, tightened)
                if t2 is None or tightened.compatible(t2, l.WELL_KNOWN_LABELS) is not None:
                    ok = False
                    break
                tightened = t2
            if not ok:
                break
            total = gang_oracle.merge_scaled(
                dict(tmpl.daemon_requests), rep.total_requests(), len(block)
            )
            remaining = filter_instance_types(
                candidates, tightened, total, relax_min_values=relax_mv
            )
            if not remaining:
                ok = False
                break
            new_ids = self._reserve_for(hostname, remaining, tightened, frozenset())
            if new_ids is None:
                ok = False
                break
            self.topology.register(l.LABEL_HOSTNAME, hostname)
            for p in block:
                self.topology.record(p, tightened)
            self._charge_budget(tmpl, remaining)
            new_claims.append(
                SimClaim(
                    template=tmpl,
                    requirements=tightened,
                    used=total,
                    instance_types=remaining,
                    pods=list(block),
                    slot=len(claims) + len(new_claims),
                    hostname=hostname,
                    host_ports=[
                        hp.port_key(h) for p in block for h in p.spec.host_ports
                    ],
                    reserved_ids=new_ids,
                    gang=gang.key,
                )
            )
        if not ok:
            # unwind: no partial gang is ever observable
            self.topology = topo_snapshot
            self.budgets = budgets_snapshot
            self._hostname_seq = hostname_seq0
            if self._rm is not None:
                for claim in new_claims:
                    self._rm.release(claim.hostname, *claim.reserved_ids)
            for p in pods:
                unschedulable.append((p, GANG_SPILL_REASON))
            return
        for claim in new_claims:
            claims.append(claim)
            for p in claim.pods:
                assignments[p.uid] = claim.slot

    def _solve_once(self, pods: list[Pod]) -> SchedulingResult:
        from karpenter_tpu.gang import GANG_WAITING_REASON, collect_gangs, order_gangs

        self._rm = self._build_rm()
        self._dra = self.dra_problem.fresh_round() if self.dra_problem is not None else None
        claims: list[SimClaim] = []
        unschedulable: list[tuple[Pod, str]] = []
        assignments: dict[str, int] = {}
        existing_assignments: dict[str, str] = {}
        expired = False
        # gangs place FIRST, largest slice first, all-or-nothing on fresh
        # dedicated claims; singleton pods then run the usual FFD cascade
        # (tier 2 skips gang claims — a slice is never shared)
        gangs, singles, invalid = collect_gangs(pods)
        for pod, reason in invalid:
            unschedulable.append((pod, reason))
        for gang in order_gangs(gangs):
            if self.deadline is not None and self.now() >= self.deadline:
                for p in gang.pods_in_rank_order():
                    unschedulable.append((p, SOLVE_TIMEOUT_REASON))
                continue
            if not gang.complete:
                # stragglers missing: the orchestration layer normally
                # holds these back (GangWaitTracker); a direct solve keeps
                # them pending as a unit
                for p in gang.pods_in_rank_order():
                    unschedulable.append((p, GANG_WAITING_REASON))
                continue
            self._place_gang(gang, claims, assignments, unschedulable)
        for pod in ffd_sort(singles):
            expired = expired or (
                self.deadline is not None and self.now() >= self.deadline
            )
            if expired:
                # deadline hit mid-queue: remaining pods fail with the
                # timeout error, placed prefix stands (reference ctx poll)
                unschedulable.append((pod, SOLVE_TIMEOUT_REASON))
                continue
            if self._dra is not None:
                err = self._dra.pod_error(pod)
                if err is not None:
                    # unresolved claim reference: no candidate can accept
                    # the pod this loop (scheduler.go:587-589)
                    unschedulable.append((pod, err))
                    continue
            # volume alternatives are tried inside can_add/can_add_existing
            # against the CANDIDATE's requirements, never merged here — the
            # pod's own affinity drives TSC counting (nodeclaim.go:168-173)
            pod_reqs = Requirements.from_pod(pod)
            strict = Requirements.from_pod(pod, include_preferred=False)
            # tier 1: existing nodes, earliest index wins (scheduler.go:594)
            placed = False
            for node in self.existing_nodes:
                if self.can_add_existing(node, pod, pod_reqs, strict):
                    existing_assignments[pod.uid] = node.name
                    placed = True
                    break
            if placed:
                continue
            # tier 2: in-flight claims, fewest pods first, earliest slot
            # tie-break (scheduler.go:598-599); gang claims are dedicated
            # slice hosts and never accept singleton adds
            for claim in sorted(
                (c for c in claims if c.gang is None),
                key=lambda c: (len(c.pods), c.slot),
            ):
                updated = self.can_add(claim, pod, pod_reqs, strict)
                if updated is not None:
                    claims[claims.index(claim)] = updated
                    assignments[pod.uid] = updated.slot
                    placed = True
                    break
            if placed:
                continue
            new_claim = self.try_new_claim(pod, pod_reqs, strict, slot=len(claims))
            if new_claim is not None:
                claims.append(new_claim)
                assignments[pod.uid] = new_claim.slot
            else:
                unschedulable.append((pod, "no compatible in-flight claim or template"))
        for claim in claims:
            finalize_reserved(claim)
            if self.min_values_policy == "BestEffort":
                finalize_min_values(claim)
        return SchedulingResult(
            claims=claims,
            unschedulable=unschedulable,
            assignments=assignments,
            existing=self.existing_nodes,
            existing_assignments=existing_assignments,
            dra=self._dra,
        )
