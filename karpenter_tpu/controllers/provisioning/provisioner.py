"""The Provisioner: pending pods -> NodeClaims.

Counterpart of reference provisioner.go:127-577: collect provisionable
pods (+ pods on deleting nodes), gate on cluster sync, build the scheduler
from Ready non-static NodePools in weight order, Solve (on TPU), then
create NodeClaims and nominate.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.controllers.provisioning.host_scheduler import (
    ExistingSimNode,
    SchedulingResult,
    SimClaim,
)
from karpenter_tpu.controllers.provisioning.nodeclaimtemplate import (
    MAX_INSTANCE_TYPES,
    build_templates,
)
from karpenter_tpu.controllers.provisioning.scheduler import TPUScheduler
from karpenter_tpu.cloudprovider.instancetype import order_by_price
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import NodeClaim, NodeClaimSpec
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.objects import ObjectMeta, new_uid
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock


class Provisioner:
    def __init__(
        self,
        store: ObjectStore,
        cluster: Cluster,
        cloud: CloudProvider,
        clock: Clock,
        ignore_preferences: bool = False,
        reserved_capacity_enabled: bool = True,
        min_values_policy: str = "Strict",
        dynamic_resources_enabled: bool = False,
        solve_timeout_seconds: float = 60.0,
        solver_endpoint: str = "",
        mesh_devices: int = 0,
        recorder=None,
        unavailable=None,
    ):
        self.store = store
        self.cluster = cluster
        self.cloud = cloud
        self.clock = clock
        # unavailable-offerings blackout cache (Manager shares one with
        # the lifecycle controller); the catalog every scheduler build
        # sees is filtered through it, so a just-ICE'd offering can't be
        # re-picked until its TTL lapses
        if unavailable is None:
            from karpenter_tpu.cloudprovider.unavailable import UnavailableOfferings

            unavailable = UnavailableOfferings(clock)
        self.unavailable = unavailable
        self.ignore_preferences = ignore_preferences  # PreferencePolicy=Ignore
        self.reserved_capacity_enabled = reserved_capacity_enabled
        self.min_values_policy = min_values_policy
        self.dynamic_resources_enabled = dynamic_resources_enabled
        # Solve timeout (provisioner.go:415, options solve_timeout_seconds):
        # a deadline on the injected clock so fake-clock tests can expire it
        self.solve_timeout_seconds = solve_timeout_seconds
        # Remote solver service address (rpc/client.RemoteScheduler);
        # empty = in-process TPUScheduler
        self.solver_endpoint = solver_endpoint
        self.mesh_devices = mesh_devices  # 0 = single device
        # deduped event recorder (events.Recorder); the explainer publishes
        # FailedScheduling provenance through it when wired
        self.recorder = recorder
        # DeviceAllocationController; wired by the manager when DRA is on
        self.device_allocation = None
        self._scheduler_cache: Optional[tuple[tuple, TPUScheduler]] = None
        self._buffer_pods: dict[tuple[str, int], list[Pod]] = {}
        from karpenter_tpu.gang import GangWaitTracker
        from karpenter_tpu.utils.logging import ChangeMonitor

        # straggler wait for partial gangs: incomplete gangs are held out
        # of the solve until every member arrives or the wait times out
        # (KTPU_GANG_WAIT_SECONDS); completion observes the wait histogram
        self.gang_wait = GangWaitTracker(clock)
        self._log_monitor = ChangeMonitor(clock=clock)

    # -- pod collection (provisioner.go:350-385) -------------------------------

    def pending_pods(self) -> list[Pod]:
        """Provisionable pods without a live nomination to an in-flight
        claim (prevents double-provisioning while nodes come up), plus
        virtual capacity-buffer pods (buffers.go:72-190)."""
        pods = [
            p
            for p in self.store.pods()
            if p.is_provisionable() and self.cluster.pod_nomination(p.uid) is None
        ]
        pods.extend(
            p for p in self._virtual_buffer_pods() if self.cluster.pod_nomination(p.uid) is None
        )
        if self.ignore_preferences:
            from karpenter_tpu.controllers.provisioning.preferences import strip_preferences

            pods = [strip_preferences(p) for p in pods]
        return pods

    def _virtual_buffer_pods(self) -> list[Pod]:
        """Synthetic headroom pods, cached per (buffer, replicas) so their
        uids are stable across reconciles (a fresh uid every pass would
        defeat nomination and double-provision the headroom)."""
        from karpenter_tpu.controllers.capacity_buffer import (
            resolved_pod_spec,
            resolved_replicas,
            virtual_pods,
        )

        out: list[Pod] = []
        buffers = self.store.list(self.store.CAPACITY_BUFFERS)
        live = {b.name for b in buffers}
        # drop cache entries for deleted buffers and stale generations
        self._buffer_pods = {k: v for k, v in self._buffer_pods.items() if k[0] in live}
        for buffer in buffers:
            # controller-resolved status when stamped; inline spec in the
            # bare harness (capacity_buffer.resolved_replicas). The key
            # carries the resolved SPEC content too — a re-pointed or
            # edited PodTemplate with an unchanged replica count must
            # regenerate the headroom pods
            spec = resolved_pod_spec(buffer, self.store)
            key = (buffer.name, resolved_replicas(buffer), hash(repr(spec)))
            if key not in self._buffer_pods:
                self._buffer_pods = {
                    k: v for k, v in self._buffer_pods.items() if k[0] != buffer.name
                }
                self._buffer_pods[key] = virtual_pods([buffer], self.store)
            out.extend(self._buffer_pods[key])
        return out

    # -- gang batching (gangs batch as units; stragglers wait) -------------------

    def _admit_gangs(self, pods: list[Pod]) -> list[Pod]:
        """Gang-aware batch admission: complete gangs enter the solve as
        units; partial gangs are held back until every member arrives or
        the wait times out (reported via metric + event, then the wait
        restarts). Also runs gang RECOVERY: when some members of a gang
        lost their claim (ICE, node death) while peers still hold live
        nominations to unbound claims, the peers' nominations are released
        so the WHOLE gang re-solves — all-or-nothing applies to re-placement
        too, never just the orphaned members."""
        from karpenter_tpu.gang import collect_gangs, gang_of, is_gang_pod
        from karpenter_tpu.utils import events, metrics

        if not any(is_gang_pod(p) for p in pods):
            return pods
        gangs, singles, invalid = collect_gangs(pods)
        # recovery: fold nominated-but-unbound peers into incomplete gangs
        if any(not g.complete for g in gangs):
            by_key = {g.key: g for g in gangs}
            for p in self.store.pods():
                if not p.is_pending() or self.cluster.pod_nomination(p.uid) is None:
                    continue
                parsed = gang_of(p)
                if parsed is None:
                    continue
                key, _size, rank = parsed
                g = by_key.get(key)
                if g is not None and not g.complete and rank not in g.members:
                    self.cluster.clear_pod_nomination(p.uid)
                    g.members[rank] = p
        ready, waiting, timed_out = self.gang_wait.admit(gangs)
        for g in timed_out:
            metrics.GANG_PLACEMENTS.inc(outcome="timeout")
            if self.recorder is not None:
                self.recorder.publish(
                    events.failed_scheduling(
                        g.key,
                        f"gang {g.key} waited past the straggler timeout: "
                        f"{g.missing}/{g.size} members still missing",
                    )
                )
        out = list(singles)
        out.extend(p for p, _ in invalid)  # engines report these loudly
        for g in ready:
            out.extend(g.pods_in_rank_order())
        return out

    def _record_gang_outcomes(self, result: SchedulingResult) -> None:
        """Per-gang outcome accounting over one solve result, and the
        no-partial-placement tripwire (outcome="partial" must stay zero —
        both engines commit gangs atomically by construction)."""
        from karpenter_tpu.gang import GANG_INVALID_REASON, gang_of
        from karpenter_tpu.utils import metrics

        placed: dict[str, int] = {}
        failed: dict[str, int] = {}
        invalid: dict[str, int] = {}
        sizes: dict[str, int] = {}
        for pod, reason in result.unschedulable:
            parsed = gang_of(pod)
            if parsed is None:
                continue
            key, size, _rank = parsed
            sizes[key] = size
            if reason.startswith(GANG_INVALID_REASON):
                invalid[key] = invalid.get(key, 0) + 1
            else:
                failed[key] = failed.get(key, 0) + 1
        for sim in result.claims:
            if sim.gang:
                sizes.setdefault(sim.gang, 0)
                placed[sim.gang] = placed.get(sim.gang, 0) + len(sim.pods)
        for key in sizes:
            n_placed = placed.get(key, 0)
            n_failed = failed.get(key, 0)
            if invalid.get(key):
                metrics.GANG_PLACEMENTS.inc(outcome="invalid")
            elif n_placed and not n_failed:
                metrics.GANG_PLACEMENTS.inc(outcome="placed")
            elif n_failed and not n_placed:
                metrics.GANG_PLACEMENTS.inc(outcome="spilled")
                metrics.GANG_SPILLS.inc()
            elif n_placed and n_failed:
                # invariant violation: should be impossible by construction
                from karpenter_tpu.utils.logging import get_logger

                get_logger().with_values(controller="provisioner").error(
                    "partial gang placement observed", gang=key,
                    placed=n_placed, failed=n_failed,
                )
                metrics.GANG_PLACEMENTS.inc(outcome="partial")

    # -- scheduling --------------------------------------------------------------

    def _ready_pools(self) -> list[NodePool]:
        """Non-static pools that pass runtime validation
        (provisioner.go:268-289 lists Ready pools). The condition is
        authoritative once the validation controller has stamped it; an
        UNSET condition is validated inline so the first reconcile after a
        pool appears can't race an invalid pool into a launch."""
        from karpenter_tpu.models.nodepool import CONDITION_VALIDATION_SUCCEEDED
        from karpenter_tpu.models.validation import validate_nodepool

        def schedulable(p: NodePool) -> bool:
            if p.conditions.has(CONDITION_VALIDATION_SUCCEEDED):
                return not p.conditions.is_false(CONDITION_VALIDATION_SUCCEEDED)
            return not validate_nodepool(p)

        return [p for p in self.store.nodepools() if not p.is_static and schedulable(p)]

    def _volume_context(self) -> tuple[dict, dict]:
        """(pvcs, storage classes) by name, scanned ONCE per solve entry
        point and threaded through every volume helper."""
        pvcs = {p.name: p for p in self.store.list(self.store.PVCS)}
        classes = {s.name: s for s in self.store.list(self.store.STORAGE_CLASSES)}
        return pvcs, classes

    def _volume_requirements(self, pods: list[Pod], volctx=None) -> dict:
        """pod uid -> PVC-implied topology alternatives
        (volumetopology.go:65-91 GetRequirements)."""
        from karpenter_tpu.scheduling.volumes import volume_requirement_alternatives

        pvcs, classes = volctx if volctx is not None else self._volume_context()
        if not pvcs:
            return {}
        out = {}
        for pod in pods:
            if not pod.spec.pvc_names:
                continue
            alts = volume_requirement_alternatives(pod, pvcs, classes)
            if alts:
                out[pod.uid] = alts
        return out

    def _pod_volumes(self, pods: list[Pod], volctx=None) -> dict:
        """pod uid -> CSI Volumes (driver -> pvc ids) for attach-limit
        checks (volumeusage.go:82-113 GetVolumes)."""
        from karpenter_tpu.scheduling.volumes import get_volumes

        pvcs, classes = volctx if volctx is not None else self._volume_context()
        if not pvcs:
            return {}
        out = {}
        for pod in pods:
            if not pod.spec.pvc_names:
                continue
            vols = get_volumes(pod, pvcs, classes)
            if vols:
                out[pod.uid] = vols
        return out

    def _bound_pods(self, excluded_nodes: Optional[set[str]] = None) -> list[tuple]:
        """(pod, node labels) for bound pods — seeds topology counts
        (topology.go:361-459 countDomains)."""
        out = []
        for sn in self.cluster.nodes():
            if sn.node is None or (excluded_nodes and sn.name in excluded_nodes):
                continue
            for pod in sn.pods.values():
                if not pod.is_terminal():
                    out.append((pod, sn.node.metadata.labels))
        return out

    def _bound_pods_named(self) -> list[tuple]:
        """(pod, node labels, node NAME) triples — the remote WhatIf ships
        these so the server can drop each scenario's excluded nodes from
        the topology seed by name."""
        out = []
        for sn in self.cluster.nodes():
            if sn.node is None:
                continue
            for pod in sn.pods.values():
                if not pod.is_terminal():
                    out.append((pod, sn.node.metadata.labels, sn.name))
        return out

    def _build_topology(self, pods, scheduler, excluded_nodes: Optional[set[str]] = None):
        from karpenter_tpu.controllers.provisioning.topology import (
            Topology,
            build_universe_domains,
        )
        from karpenter_tpu.tracing.tracer import TRACER

        with TRACER.span("topology.build", pods=len(pods)):
            # lazy universe: topology-free pod sets short-circuit inside
            # Topology.build without constructing the domain universe
            def universe():
                base = (
                    scheduler.universe_base()
                    if hasattr(scheduler, "universe_base")
                    else None
                )
                return build_universe_domains(
                    scheduler.templates,
                    self._existing_sim_nodes(excluded_nodes),
                    template_base=base,
                )

            return Topology.build(pods, universe, self._bound_pods(excluded_nodes))

    def _build_dra_problem(self, pods, extra_deleting_uids=None):
        """Per-loop DRA inputs (DynamicResources gate, off by default like
        the reference's feature flag); None when disabled or no pod uses
        resource claims. extra_deleting_uids marks pods migrating in a
        disruption what-if so their claims' devices re-allocate."""
        if not self.dynamic_resources_enabled:
            return None
        if not any(p.spec.resource_claims for p in pods):
            return None  # keep the no-DRA hot path free of catalog fetches
        from karpenter_tpu.scheduling.dra.integration import DRAProblem

        from karpenter_tpu.cloudprovider.errors import instance_types_or_none

        catalogs = {
            p.name: its
            for p in self.store.nodepools()
            if (its := instance_types_or_none(self.cloud, p)) is not None
        }
        return DRAProblem.build(self.store, pods, catalogs, extra_deleting_uids)

    def _reserved_in_use(self) -> dict[str, int]:
        """Reservation ids pinned by in-flight claims the provider has not
        launched yet — the catalog's capacities can't reflect them, so the
        schedulers subtract them from the per-solve snapshot."""
        from karpenter_tpu.cloudprovider.instancetype import RESERVATION_ID_LABEL

        out: dict[str, int] = {}
        for c in self.store.nodeclaims():
            if c.status.provider_id:
                continue  # launched: the provider's catalog already counts it
            for r in c.spec.requirements:
                if r.get("key") == RESERVATION_ID_LABEL and r.get("values"):
                    # a multi-id pin holds EVERY named reservation until the
                    # provider collapses it at launch (pessimistic, like the
                    # in-solve reservation manager) — counting only one id
                    # would let the next loop double-book the others
                    for rid in r["values"]:
                        out[rid] = out.get(rid, 0) + 1
        return out

    def simulate(
        self, excluded_node_names: set[str], extra_pods: list[Pod], deadline=None
    ):
        """Consolidation what-if (disruption helpers.go:53-154): schedule
        pending + displaced pods against the cluster minus the excluded
        nodes. Pure simulation: no claims created, no nominations. deadline
        is the CALLING disruption method's (the reference inherits the
        method context, not the 1m Solve timeout)."""
        scheduler = self._build_scheduler()
        if scheduler is None or not self.cluster.synced():
            return None
        extra_pods = list(extra_pods)
        if self.ignore_preferences:
            # the reference applies IgnorePreferences to the WHOLE
            # simulation, displaced pods included (disruption helpers.go)
            from karpenter_tpu.controllers.provisioning.preferences import strip_preferences

            extra_pods = [strip_preferences(p) for p in extra_pods]
        pods = self.pending_pods() + extra_pods
        if not pods:
            return SchedulingResult(claims=[], unschedulable=[], assignments={})
        volctx = self._volume_context()
        existing = self._existing_sim_nodes(excluded_node_names, volctx)
        # pods displaced off the excluded nodes are migrating: their claims'
        # devices are freed and re-allocated in the what-if
        dra_problem = self._build_dra_problem(
            pods, extra_deleting_uids={p.uid for p in extra_pods}
        )
        return scheduler.solve(
            pods,
            existing,
            self._remaining_budgets(),
            topology_factory=lambda ps: self._build_topology(ps, scheduler, excluded_node_names),
            volume_reqs=self._volume_requirements(pods, volctx),
            pod_volumes=self._pod_volumes(pods, volctx),
            reserved_in_use=self._reserved_in_use(),
            dra_problem=dra_problem,
            deadline=deadline,
            now=self.clock.now,
            bound_pods=(
                self._bound_pods(excluded_node_names)
                if getattr(scheduler, "wants_bound_pods", False)
                else None
            ),
        )

    def simulate_batch(self, scenarios: "list[list]") -> "Optional[list[tuple[bool, int]]]":
        """Batched consolidation what-ifs: one device dispatch evaluates
        every candidate set's feasibility (no displaced pod unscheduled) and
        replacement count (new claims opened). scenarios is a list of
        candidate lists (objects with .name and .reschedulable_pods).

        This is a PRE-FILTER, deliberately over-approximate: pods are
        fully preference-relaxed up front (the terminal rung of the shared
        relaxation ladder), so a scenario the sequential path could rescue
        by relaxing reads feasible here too. Callers confirm the chosen
        scenario with simulate() before acting. Returns None when gated
        (unsynced cluster, no scheduler, or DRA pods present — those solve
        on the host path)."""
        scheduler = self._build_scheduler()
        if scheduler is None or not self.cluster.synced() or not scenarios:
            return None
        from karpenter_tpu.controllers.provisioning.preferences import terminal_relaxed

        pending = self.pending_pods()
        union: dict[str, Pod] = {}
        specs: list[tuple[set, set, set]] = []
        for candidates in scenarios:
            excluded = {c.name for c in candidates}
            displaced = [p for c in candidates for p in c.reschedulable_pods]
            for p in displaced:
                union.setdefault(p.uid, p)
            displaced_uids = {p.uid for p in displaced}
            active = {p.uid for p in pending} | displaced_uids
            specs.append((excluded, active, displaced_uids))
        # terminal_relaxed (not strip_preferences): the batch must be a
        # sound over-approximation of EVERY rung of the sequential ladder,
        # including dropped required OR terms and the PreferNoSchedule
        # toleration, or batch-infeasible verdicts wrongly kill candidates
        all_pods = [terminal_relaxed(p) for p in pending + list(union.values())]
        if self.dynamic_resources_enabled and any(p.spec.resource_claims for p in all_pods):
            return None
        from karpenter_tpu.gang import is_gang_pod

        if any(is_gang_pod(p) for p in all_pods):
            # the batched what-if kernel has no gang atomicity — a partial
            # placement would read feasible; fall back to the sequential
            # simulate, whose engines solve gangs exactly
            return None
        volctx = self._volume_context()
        existing = self._existing_sim_nodes(volctx=volctx)
        return scheduler.whatif_batch(
            all_pods,
            existing,
            self._remaining_budgets(),
            specs,
            lambda ps, excluded: self._build_topology(ps, scheduler, excluded),
            volume_reqs=self._volume_requirements(all_pods, volctx),
            reserved_in_use=self._reserved_in_use(),
            bound_pods=(
                self._bound_pods_named()
                if getattr(scheduler, "wants_bound_pods", False)
                else None
            ),
            # displaced pods re-attach their PVCs against surviving nodes'
            # CSI caps inside the batched solve (volumeusage.go:201-208)
            pod_volumes=self._pod_volumes(all_pods, volctx),
        )

    def _existing_sim_nodes(
        self, excluded: Optional[set[str]] = None, volctx=None
    ) -> list[ExistingSimNode]:
        """Registered, schedulable cluster nodes as tier-1 candidates
        (scheduler.go:1060 calculateExistingNodeClaims), sorted by name for
        deterministic earliest-index-wins."""
        from karpenter_tpu.scheduling import Requirements
        from karpenter_tpu.utils import resources as res

        # requests of nominated-but-unbound pods, charged against their
        # target so successive passes don't double-book the same headroom
        reserved: dict[str, dict[str, float]] = {}
        for p in self.store.pods():
            if p.is_pending():
                target = self.cluster.pod_nomination(p.uid)
                if target is not None:
                    reserved[target] = res.merge(reserved.get(target), p.total_requests())

        from karpenter_tpu.scheduling.volumes import VolumeUsage, get_volumes

        pvcs, classes = volctx if volctx is not None else self._volume_context()
        out = []
        for sn in sorted(self.cluster.nodes(), key=lambda s: s.name):
            node = sn.node
            if node is None or sn.marked_for_deletion or sn.is_disrupted():
                continue
            if excluded and sn.name in excluded:
                continue
            if not sn.registered:
                continue
            reqs = Requirements.from_labels(dict(node.metadata.labels))
            available = sn.available()
            if node.name in reserved:
                available = res.subtract(available, reserved[node.name])
            usage = None
            if node.spec.csi_drivers:
                # CSINode-published attach limits + resident pods' volumes
                # (cluster.go:845-857 populateVolumeLimits)
                usage = VolumeUsage()
                for driver, count in node.spec.csi_drivers.items():
                    usage.add_limit(driver, count)
                for pod in sn.pods.values():
                    if pod.is_terminal() or not pod.spec.pvc_names:
                        continue
                    vols = get_volumes(pod, pvcs, classes)
                    if vols:
                        usage.add(pod.uid, vols)
            out.append(
                ExistingSimNode(
                    name=node.name,
                    index=len(out),
                    requirements=reqs,
                    available=available,
                    taints=list(node.spec.taints),
                    volume_usage=usage,
                )
            )
        return out

    def _remaining_budgets(self) -> dict[str, dict[str, float]]:
        """Per-pool remaining limits = spec.limits - current usage
        (scheduler.go:184, filterByRemainingResources)."""
        budgets: dict[str, dict[str, float]] = {}
        for pool in self._ready_pools():
            if pool.spec.limits is None:
                continue
            usage = self.cluster.nodepool_usage(pool.name)
            budgets[pool.name] = {
                k: v - usage.get(k, 0.0) for k, v in pool.spec.limits.resources.items()
            }
        return budgets

    def _daemon_pod_compatible(self, template, it, pod) -> bool:
        """isDaemonPodCompatible (scheduler.go:1020-1043): template taints
        tolerated (a PreferNoSchedule toleration is implicit — daemons
        ignore that preference), then strict pod requirements compatible
        with the template AND intersecting the instance type, retried with
        required node-affinity OR terms dropped front-first (the only
        relaxation daemon scheduling considers)."""
        from karpenter_tpu.models import labels as l
        from karpenter_tpu.models.taints import (
            PREFER_NO_SCHEDULE,
            TOLERATION_OP_EXISTS,
            Toleration,
        )
        from karpenter_tpu.scheduling import Requirements
        from karpenter_tpu.scheduling.requirements import node_selector_requirement
        from karpenter_tpu.scheduling.taints import tolerates_all

        tols = list(pod.spec.tolerations) + [
            Toleration(operator=TOLERATION_OP_EXISTS, effect=PREFER_NO_SCHEDULE)
        ]
        if tolerates_all(template.taints, tols) is not None:
            return False
        na = pod.spec.node_affinity
        terms = list(na.required) if na is not None else []
        for term_idx in range(max(1, len(terms))):
            reqs = Requirements.from_labels(dict(pod.spec.node_selector or {}))
            if terms:
                reqs.add(
                    *(
                        node_selector_requirement(
                            m["key"], m["operator"], m.get("values", ())
                        )
                        for m in terms[term_idx].match_expressions
                    )
                )
            # Intersects (not Compatible) against the instance type: custom
            # daemonset keys absent from the catalog must not disqualify it
            if (
                template.requirements.compatible(reqs, l.WELL_KNOWN_LABELS) is None
                and it.requirements.intersects(reqs) is None
            ):
                return True
        return False

    def _apply_daemon_overhead(self, templates):
        """buildDaemonOverheadGroups (scheduler.go:963-1043): per template,
        group instance types by their compatible-daemonset SET and emit one
        virtual template per group, so a nodeSelector'd daemonset never
        overcharges instance types it would not land on. Both engines and
        the RPC wire consume the split list unchanged — the group concept
        never leaks past this function. Group order follows first
        instance-type appearance (deterministic; the reference iterates an
        unordered Go map, so any fixed order is a valid refinement).
        Daemonset host ports are not modeled (harness daemonsets declare
        none)."""
        from dataclasses import replace

        from karpenter_tpu.utils import resources as res

        daemon_pods = [ds.as_pod() for ds in self.store.list(self.store.DAEMONSETS)]
        if not daemon_pods:
            for t in templates:
                t.daemon_requests = {}
            return templates
        out = []
        for t in templates:
            groups: dict[frozenset, list] = {}
            order: list[frozenset] = []
            for it in t.instance_types:
                key = frozenset(
                    i
                    for i, p in enumerate(daemon_pods)
                    if self._daemon_pod_compatible(t, it, p)
                )
                if key not in groups:
                    groups[key] = []
                    order.append(key)
                groups[key].append(it)
            for key in order:
                overhead: dict[str, float] = {}
                for i in sorted(key):
                    overhead = res.merge(overhead, daemon_pods[i].total_requests())
                if len(order) == 1:
                    t.daemon_requests = overhead
                    out.append(t)
                else:
                    out.append(
                        replace(t, instance_types=groups[key], daemon_requests=overhead)
                    )
        return out

    @staticmethod
    def _pool_objective(pools) -> Optional[str]:
        """The highest-weight pool's placement_objective (deterministic:
        weight desc, name asc — the template try-order's own tie-break);
        None when no pool sets one, deferring to KTPU_OBJECTIVE."""
        for p in sorted(pools, key=lambda p: (-p.spec.weight, p.name)):
            if p.spec.placement_objective:
                return p.spec.placement_objective
        return None

    def _build_scheduler(self) -> Optional[TPUScheduler]:
        pools = self._ready_pools()
        if not pools:
            return None
        from karpenter_tpu.cloudprovider.errors import instance_types_or_none

        # blackout filter: offerings that just ICE'd leave the catalog for
        # their TTL (expiries bump the generation, invalidating the cache
        # below so the offerings come back without a pool event)
        self.unavailable.prune()
        pool_catalogs = [
            (p, filtered)
            for p in pools
            if (its := instance_types_or_none(self.cloud, p)) is not None
            and (filtered := self.unavailable.filter_catalog(its))
        ]
        templates = build_templates(pool_catalogs)
        if not templates:
            return None
        # PRE-split full-content signature: any template/catalog/daemonset
        # change invalidates. Computed before the daemon-overhead grouping
        # so a cache hit skips the O(templates x types x daemonsets)
        # compatibility matrix entirely.
        from karpenter_tpu.controllers.provisioning.host_scheduler import (
            pod_content_sig,
        )

        sig = tuple(
            sorted(
                (
                    t.nodepool_name,
                    t.weight,
                    str(t.requirements),
                    tuple(sorted(t.labels.items())),
                    tuple((x.key, x.value, x.effect) for x in t.taints),
                    tuple(it.name for it in t.instance_types),
                )
                for t in templates
            )
        ) + tuple(
            sorted(
                (ds.name, pod_content_sig(ds.as_pod()))
                for ds in self.store.list(self.store.DAEMONSETS)
            )
        ) + (("blackout_generation", self.unavailable.generation),) + (
            ("placement_objective", self._pool_objective(pools)),
        )
        if self._scheduler_cache is not None and self._scheduler_cache[0] == sig:
            return self._scheduler_cache[1]
        templates = self._apply_daemon_overhead(templates)
        if self.solver_endpoint:
            from karpenter_tpu.rpc.client import RemoteScheduler

            sched = RemoteScheduler(
                self.solver_endpoint,
                templates,
                reserved_capacity_enabled=self.reserved_capacity_enabled,
                min_values_policy=self.min_values_policy,
            )
        else:
            mesh = None
            if self.mesh_devices:
                from karpenter_tpu.parallel import make_mesh

                mesh = make_mesh(self.mesh_devices)
            sched = TPUScheduler(
                templates,
                reserved_capacity_enabled=self.reserved_capacity_enabled,
                min_values_policy=self.min_values_policy,
                mesh=mesh,
                objective=self._pool_objective(pools),
            )
            from karpenter_tpu.controllers.provisioning.scheduler import (
                resident_enabled,
            )

            if resident_enabled():
                # service mode (ISSUE 7): SolverState stays resident across
                # reconcile rounds; steady-state deltas skip the snapshot
                # re-encode/re-solve. Every unsupported shape falls back to
                # a bit-identical full solve inside the session.
                sched = sched.resident_session()
        # close the REPLACED RemoteScheduler's channel only after the new
        # scheduler is successfully built — a failed rebuild must not leave
        # a closed channel live in the cache
        old = self._scheduler_cache[1] if self._scheduler_cache is not None else None
        self._scheduler_cache = (sig, sched)
        if old is not None and hasattr(old, "close"):
            old.close()
        return sched

    # -- claim creation (provisioner.go:169-221, :460-506) -----------------------

    def create_node_claims(self, result: SchedulingResult) -> list[NodeClaim]:
        from karpenter_tpu.tracing.tracer import TRACER
        from karpenter_tpu.utils import metrics

        with TRACER.span("claims.create", claims=len(result.claims)):
            return self._create_node_claims(result, metrics)

    def _create_node_claims(self, result: SchedulingResult, metrics) -> list[NodeClaim]:
        created = []
        for sim in result.claims:
            claim = self._to_node_claim(sim)
            metrics.NODECLAIMS_CREATED.inc(
                reason="provisioning",
                nodepool=sim.template.nodepool_name,
                min_values_relaxed="true" if sim.min_values_relaxed else "false",
            )
            self.store.create(ObjectStore.NODECLAIMS, claim)
            # state-ahead-of-cache update (provisioner.go:501-506)
            self.cluster.update_nodeclaim(claim)
            # nominate the scheduled pods so the next pass doesn't
            # re-provision for them (MarkPodSchedulingDecisions)
            for pod in sim.pods:
                self.cluster.nominate_pod(pod.uid, claim.name)
            if result.dra is not None and self.device_allocation is not None:
                self._register_device_allocations(result.dra, sim, claim)
            created.append(claim)
        if result.dra is not None and self.device_allocation is not None:
            self._register_existing_device_allocations(result)
            self._extend_claim_reservations(result)
        return created

    def _extend_claim_reservations(self, result: SchedulingResult) -> None:
        """Pods that joined a claim already allocated in-cluster never pass
        through the allocator (classified committed-in-place), so their
        consumer reservation (reservedFor) is extended directly."""
        placed = [p for sim in result.claims for p in sim.pods]
        placed += [p for node in result.existing for p in node.pods]
        for pod in placed:
            for name in pod.spec.resource_claims:
                rc = self.store.get(ObjectStore.RESOURCE_CLAIMS, name)
                if rc is None or rc.allocation is None:
                    continue  # pending collapse: deviceallocation stamps it
                if pod.uid not in rc.reserved_for:
                    rc.reserved_for.append(pod.uid)
                    self.store.update(ObjectStore.RESOURCE_CLAIMS, rc)

    def _register_device_allocations(self, dra_round, sim: SimClaim, claim: NodeClaim) -> None:
        """Hand the winning round's per-claim allocation metadata to the
        deviceallocation controller, keyed to the real NodeClaim (the
        simulation knows it only by placeholder hostname). The claim is
        annotated with the allocated DRA driver set (labels.go:56-59) so
        initialization waits for those drivers' ResourceSlices
        (initialization.go:148-178 — without the annotation the node
        would flip Initialized before its devices exist)."""
        from karpenter_tpu.controllers.device_allocation import PendingAllocation

        # the launched instance type is unknown until collapse, so per
        # resource claim only drivers that EVERY surviving IT's allocation
        # uses may gate initialization — a union could name a driver the
        # chosen IT never publishes and wedge the node uninitialized
        # forever; across claims the sets union (all must publish)
        drivers: set[str] = set()
        for claim_key, meta in dra_round.allocator.claim_allocation_metadata.items():
            if meta.nodeclaim_id != sim.hostname:
                continue
            claim_name = claim_key.split("/", 1)[1]
            pod_uids = [p.uid for p in sim.pods if claim_name in p.spec.resource_claims]
            claim_drivers: Optional[set[str]] = None
            for results in meta.devices.values():
                per_it = {r.device_id.driver for r in results}
                claim_drivers = (
                    per_it if claim_drivers is None else (claim_drivers & per_it)
                )
            drivers |= claim_drivers or set()
            self.device_allocation.register(
                PendingAllocation(
                    claim_name=claim_name,
                    nodeclaim_name=claim.name,
                    node_name="",
                    metadata=meta,
                    pod_uids=pod_uids,
                    it_slices={
                        it.name: list(getattr(it, "dra_slices", []) or [])
                        for it in sim.instance_types
                    },
                )
            )
        if drivers:
            claim.metadata.annotations[l.DRA_DRIVERS_ANNOTATION_KEY] = ",".join(
                sorted(drivers)
            )
            self.store.update(ObjectStore.NODECLAIMS, claim)

    def _register_existing_device_allocations(self, result: SchedulingResult) -> None:
        """Claims allocated against existing nodes collapse immediately —
        the node and its published devices already exist."""
        from karpenter_tpu.controllers.device_allocation import PendingAllocation

        nodes_by_name = {n.name: n for n in result.existing}
        for claim_key, meta in result.dra.allocator.claim_allocation_metadata.items():
            node = nodes_by_name.get(meta.nodeclaim_id)
            if node is None:
                continue
            claim_name = claim_key.split("/", 1)[1]
            pod_uids = [p.uid for p in node.pods if claim_name in p.spec.resource_claims]
            self.device_allocation.register(
                PendingAllocation(
                    claim_name=claim_name,
                    nodeclaim_name="",
                    node_name=meta.nodeclaim_id,
                    metadata=meta,
                    pod_uids=pod_uids,
                )
            )

    def _to_node_claim(self, sim: SimClaim) -> NodeClaim:
        tmpl = sim.template
        name = f"{tmpl.nodepool_name}-{new_uid('nc')}"
        annotations = {
            l.NODECLAIM_MIN_VALUES_RELAXED_ANNOTATION_KEY: (
                "true" if sim.min_values_relaxed else "false"
            )
        }
        if sim.gang:
            # every host claim of a slice carries the gang key so
            # disruption/lifecycle can treat the claim group atomically
            from karpenter_tpu.gang import GANG_CLAIM_ANNOTATION

            annotations[GANG_CLAIM_ANNOTATION] = sim.gang
        launchable = order_by_price(sim.instance_types, sim.requirements)[:MAX_INSTANCE_TYPES]
        requirements = []
        for r in sim.requirements.values():
            # the simulation-only placeholder hostname must not leak into
            # the persisted claim (nodeclaim.go:383-386 FinalizeScheduling)
            if r.key == l.LABEL_HOSTNAME:
                continue
            entry = {"key": r.key, "operator": r.operator().value}
            if r.values:
                entry["values"] = sorted(r.values)
            if r.min_values is not None:
                entry["minValues"] = r.min_values
            requirements.append(entry)
        # restrict launch flexibility to the viable, price-ordered types
        requirements.append(
            {
                "key": l.LABEL_INSTANCE_TYPE,
                "operator": "In",
                "values": [it.name for it in launchable],
            }
        )
        claim = NodeClaim(
            metadata=ObjectMeta(
                name=name,
                labels={**tmpl.labels, l.NODEPOOL_LABEL_KEY: tmpl.nodepool_name},
                annotations={
                    l.NODEPOOL_HASH_ANNOTATION_KEY: tmpl.nodepool_hash,
                    l.NODEPOOL_HASH_VERSION_ANNOTATION_KEY: "v1",
                    **annotations,
                },
            ),
            spec=NodeClaimSpec(
                taints=list(tmpl.taints),
                startup_taints=list(tmpl.startup_taints),
                requirements=requirements,
                requests=dict(sim.used),
                expire_after_seconds=tmpl.expire_after_seconds,
                termination_grace_period_seconds=tmpl.termination_grace_period_seconds,
            ),
        )
        return claim

    # -- the scheduling explainer ------------------------------------------------

    def _explain_result(self, result, templates) -> None:
        """Record per-pod decision provenance for the solve's failures:
        a SchedulingDecision on the live trace, a FailedScheduling event
        naming the failing requirement + the relaxation rungs attempted,
        and the ktpu_unschedulable_pods gauge by canonical reason."""
        from karpenter_tpu.tracing import MAX_EXPLAINED_PODS, TRACER, decision_for
        from karpenter_tpu.utils import events, metrics

        metrics.UNSCHEDULABLE_PODS.values.clear()
        if not result.unschedulable:
            return
        counts: dict[str, int] = {}
        for pod, reason in result.unschedulable[:MAX_EXPLAINED_PODS]:
            decision = decision_for(
                pod, reason, templates, result.relaxations.get(pod.uid, [])
            )
            counts[decision.slug] = counts.get(decision.slug, 0) + 1
            TRACER.add_decision(decision.as_dict())
            if self.recorder is not None:
                self.recorder.publish(
                    events.failed_scheduling(pod.name, decision.message())
                )
        # pods beyond the explainer cap still count toward their reason
        for pod, reason in result.unschedulable[MAX_EXPLAINED_PODS:]:
            from karpenter_tpu.tracing import reason_slug

            slug = reason_slug(reason)
            counts[slug] = counts.get(slug, 0) + 1
        for slug, n in counts.items():
            metrics.UNSCHEDULABLE_PODS.set(float(n), reason=slug)

    # -- the reconcile pass (provisioner.go:127-165) -------------------------------

    GATED = "gated"  # provisioning blocked (no pools / cluster unsynced); retry

    def reconcile(self):
        """SchedulingResult | None (nothing to do) | GATED (retry later)."""
        pods = self.pending_pods()
        from karpenter_tpu.utils import metrics

        if not pods:
            # drained queue: zero the families so dashboards don't read a
            # stale backlog (the reference gauges follow the live queue)
            metrics.SCHEDULER_QUEUE_DEPTH.set(0.0)
            metrics.SCHEDULER_UNFINISHED_WORK.set(0.0)
            metrics.SCHEDULER_IGNORED_PODS.set(0.0)
            metrics.PENDING_PODS_BY_ZONE.values.clear()
            metrics.UNSCHEDULABLE_PODS.values.clear()
            if not self.store.list(self.store.CAPACITY_BUFFERS):
                # no buffers -> no headroom anywhere: clear the emptiness
                # guard so ex-headroom nodes of a deleted buffer don't
                # stay protected forever (no solve runs to recompute it)
                self.cluster.buffer_pod_counts = {}
            return None
        if not self.cluster.synced():
            return self.GATED
        # gangs batch as units: partial gangs wait for stragglers (with a
        # timeout), orphaned members pull their nominated peers back so
        # the whole gang re-solves
        pods = self._admit_gangs(pods)
        if not pods:
            return None  # every pending pod is a gang still waiting
        scheduler = self._build_scheduler()
        if scheduler is None:
            return self.GATED

        # queue families (scheduling/metrics.go:52-100): depth = this
        # batch; unfinished work = oldest waiting pod's age; pending by
        # effective zone from each pod's zone restriction
        metrics.SCHEDULER_QUEUE_DEPTH.set(float(len(pods)))
        metrics.QUEUE_DEPTH_PODS.observe(float(len(pods)))
        metrics.SCHEDULER_IGNORED_PODS.set(
            float(
                sum(
                    1
                    for p in self.store.pods()
                    if p.is_pending() and not p.spec.node_name and not p.is_provisionable()
                )
            )
        )
        now = self.clock.now()
        metrics.SCHEDULER_UNFINISHED_WORK.set(
            max((now - p.metadata.creation_timestamp for p in pods), default=0.0)
        )
        metrics.PENDING_PODS_BY_ZONE.values.clear()
        for p in pods:
            from karpenter_tpu.scheduling import Requirements

            reqs = Requirements.from_pod(p)
            zones = (
                sorted(reqs.get(l.LABEL_TOPOLOGY_ZONE).values)
                if reqs.has(l.LABEL_TOPOLOGY_ZONE)
                else []
            )
            zone = ",".join(zones) if zones else "any"
            metrics.PENDING_PODS_BY_ZONE.set(
                metrics.PENDING_PODS_BY_ZONE.get(zone=zone) + 1.0, zone=zone
            )
        from karpenter_tpu.tracing.tracer import TRACER

        _solve_span = TRACER.span("solve", pods=len(pods))
        with _solve_span, metrics.SCHEDULING_DURATION.time():
            # regular provisioning disables reserved-capacity fallback
            # (provisioner.go:389 DisableReservedCapacityFallback): a pod
            # that can't get a reservation retries next loop instead of
            # launching paid capacity; disruption simulations keep the
            # fallback default (strict would stalemate drift)
            volctx = self._volume_context()
            result = scheduler.solve(
                pods,
                self._existing_sim_nodes(volctx=volctx),
                self._remaining_budgets(),
                topology_factory=lambda ps: self._build_topology(ps, scheduler),
                volume_reqs=self._volume_requirements(pods, volctx),
                pod_volumes=self._pod_volumes(pods, volctx),
                reserved_mode="strict",
                reserved_in_use=self._reserved_in_use(),
                dra_problem=self._build_dra_problem(pods),
                deadline=self.clock.now() + self.solve_timeout_seconds,
                now=self.clock.now,
                bound_pods=(
                    self._bound_pods()
                    if getattr(scheduler, "wants_bound_pods", False)
                    else None
                ),
            )
        metrics.SCHEDULING_UNSCHEDULABLE.set(float(len(result.unschedulable)))
        # per-gang outcome accounting + the partial-placement tripwire
        self._record_gang_outcomes(result)
        # per-pod scheduling explainer: provenance into the deduped event
        # stream + the trace, and the reasoned unschedulable-pods gauge
        self._explain_result(result, scheduler.templates)
        # solve summary, deduped like the reference's ChangeMonitor-guarded
        # provisioner logs (provisioner.go:226-256)
        from karpenter_tpu.utils.logging import get_logger

        summary = {
            "pods": len(pods),
            "new_claims": len(result.claims),
            "existing_placements": len(result.existing_assignments),
            "unschedulable": len(result.unschedulable),
        }
        if self._log_monitor.has_changed("solve", summary):
            get_logger().with_values(controller="provisioner").info(
                "computed new nodes to fit pods", **summary
            )
        self.create_node_claims(result)
        # nominate pods placed on existing nodes so the kube-scheduler (sim)
        # binds them and the next pass doesn't re-provision
        for pod_uid, node_name in result.existing_assignments.items():
            self.cluster.nominate_pod(pod_uid, node_name)
            sn = self.cluster.node_by_name(node_name)
            if sn is not None:
                sn.nominate(self.clock.now())
        # buffer Provisioning conditions + the emptiness guard's per-node
        # headroom counts (buffers.go:140-158)
        from karpenter_tpu.controllers.capacity_buffer import (
            update_provisioning_statuses,
        )

        self.cluster.buffer_pod_counts = update_provisioning_statuses(
            self.store, result, self.clock
        )
        return result
