"""Topology: spread constraints, pod affinity and pod anti-affinity.

Counterpart of reference topology.go / topologygroup.go. Every TSC and
(anti)affinity term becomes a TopologyGroup tracking a domain -> count map;
placement tightens a candidate's requirements to the valid domains
(AddRequirements) and commits counts on placement (Record).

Semantics preserved from the reference:
  * spread picks THE min-count valid domain (nextDomainTopologySpread);
    'count + self - globalMin <= maxSkew' gates validity; minDomains forces
    the global min to 0 while under-provisioned; hostname's global min is
    always 0 because a new node is always creatable (topologygroup.go:229+)
  * affinity allows any domain with a matching pod, with the bootstrap
    rule: a self-selecting pod may seed an empty (or incompatible) group
    (topologygroup.go:324+)
  * anti-affinity blocks every domain a matching pod could be in; owners
    record ALL their possible domains, and pods matched by someone else's
    anti-affinity selector inherit the restriction via inverse groups
    (topology.go:200-220)

Selector matching is matchLabels-based (our Pod model); namespaces default
to the pod's own.
"""

from __future__ import annotations

import enum
from collections import defaultdict
from typing import Iterable, Optional

from karpenter_tpu.models import labels as l
from karpenter_tpu.models.pod import Pod
from karpenter_tpu.scheduling import Operator, Requirement, Requirements

MAX_I32 = 2**31 - 1


class TopologyType(enum.Enum):
    SPREAD = "topology spread"
    AFFINITY = "pod affinity"
    ANTI_AFFINITY = "pod anti-affinity"


def _selects(selector: dict[str, str], pod: Pod) -> bool:
    if selector is None:
        return False
    return all(pod.metadata.labels.get(k) == v for k, v in selector.items())


class TopologyGroup:
    def __init__(
        self,
        ttype: TopologyType,
        key: str,
        selector: dict[str, str],
        max_skew: int = 1,
        min_domains: Optional[int] = None,
        namespaces: Optional[frozenset[str]] = None,
        initial_domains: Iterable[str] = (),
    ):
        self.type = ttype
        self.key = key
        self.selector = selector
        self.max_skew = max_skew
        self.min_domains = min_domains
        self.namespaces = namespaces or frozenset({"default"})
        self.domains: dict[str, int] = {d: 0 for d in initial_domains}
        self.owners: set[str] = set()  # pod uids

    # -- identity (topologygroup.go Hash) ---------------------------------

    def ident(self) -> tuple:
        return (
            self.type,
            self.key,
            tuple(sorted(self.selector.items())),
            self.max_skew,
            self.min_domains,
            tuple(sorted(self.namespaces)),
        )

    # -- bookkeeping -------------------------------------------------------

    def register(self, *domains: str) -> None:
        for d in domains:
            self.domains.setdefault(d, 0)

    def record(self, *domains: str) -> None:
        for d in domains:
            self.domains[d] = self.domains.get(d, 0) + 1

    def selects(self, pod: Pod) -> bool:
        return pod.metadata.namespace in self.namespaces and _selects(self.selector, pod)

    def is_empty(self) -> bool:
        return all(c == 0 for c in self.domains.values())

    # -- the domain chooser (topologygroup.go:150-400) ----------------------

    def get(self, pod: Pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        if self.type is TopologyType.SPREAD:
            return self._next_spread(pod, pod_domains, node_domains)
        if self.type is TopologyType.AFFINITY:
            return self._next_affinity(pod, pod_domains, node_domains)
        return self._next_anti_affinity(pod_domains, node_domains)

    def _domain_min_count(self, pod_domains: Requirement) -> int:
        if self.key == l.LABEL_HOSTNAME:
            return 0  # a new node is always creatable
        lo_count = MAX_I32
        supported = 0
        for domain, count in self.domains.items():
            if pod_domains.has(domain):
                supported += 1
                lo_count = min(lo_count, count)
        if self.min_domains is not None and supported < self.min_domains:
            return 0
        return lo_count

    def _next_spread(self, pod: Pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        min_count = self._domain_min_count(pod_domains)
        self_add = 1 if self.selects(pod) else 0

        # hostname with a single concrete node domain: new claims' domains
        # aren't registered yet; global min is 0 (topologygroup.go:229-246)
        if self.key == l.LABEL_HOSTNAME and node_domains.operator() is Operator.IN and len(node_domains.values) == 1:
            hostname = next(iter(node_domains.values))
            count = self.domains.get(hostname, 0) + self_add
            if count <= self.max_skew:
                return Requirement.new(self.key, Operator.IN, hostname)
            return Requirement.new(self.key, Operator.DOES_NOT_EXIST)

        best_domain, best_count = None, MAX_I32
        for domain in sorted(self.domains):  # sorted: deterministic tie-break
            if not node_domains.has(domain) or not pod_domains.has(domain):
                continue
            count = self.domains[domain] + self_add
            if count - min_count <= self.max_skew and count < best_count:
                best_domain, best_count = domain, count
        if best_domain is None:
            return Requirement.new(self.key, Operator.DOES_NOT_EXIST)
        return Requirement.new(self.key, Operator.IN, best_domain)

    def _any_compatible_pod_domain(self, pod_domains: Requirement) -> bool:
        return any(pod_domains.has(d) and c > 0 for d, c in self.domains.items())

    def _next_affinity(self, pod: Pod, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        options: list[str] = []
        if self.key == l.LABEL_HOSTNAME and node_domains.operator() is Operator.IN and len(node_domains.values) == 1:
            hostname = next(iter(node_domains.values))
            if not pod_domains.has(hostname):
                return Requirement.new(self.key, Operator.DOES_NOT_EXIST)
            if self.domains.get(hostname, 0) > 0:
                return Requirement.new(self.key, Operator.IN, hostname)
            if self.selects(pod) and (self.is_empty() or not self._any_compatible_pod_domain(pod_domains)):
                return Requirement.new(self.key, Operator.IN, hostname)
            return Requirement.new(self.key, Operator.DOES_NOT_EXIST)

        for domain in sorted(self.domains):
            if pod_domains.has(domain) and self.domains[domain] > 0 and node_domains.has(domain):
                options.append(domain)
        if options:
            return Requirement.new(self.key, Operator.IN, *options)
        # bootstrap: self-selecting first pod may seed a domain
        if self.selects(pod) and (self.is_empty() or not self._any_compatible_pod_domain(pod_domains)):
            for domain in sorted(self.domains):
                if pod_domains.has(domain) and node_domains.has(domain):
                    return Requirement.new(self.key, Operator.IN, domain)
            for domain in sorted(self.domains):
                if pod_domains.has(domain):
                    return Requirement.new(self.key, Operator.IN, domain)
        return Requirement.new(self.key, Operator.DOES_NOT_EXIST)

    def _next_anti_affinity(self, pod_domains: Requirement, node_domains: Requirement) -> Requirement:
        options = [
            d
            for d in sorted(self.domains)
            if pod_domains.has(d) and node_domains.has(d) and self.domains[d] == 0
        ]
        # hostname: a fresh node is always an empty domain; admit the node's
        # single concrete hostname if it has no count yet
        if self.key == l.LABEL_HOSTNAME and node_domains.operator() is Operator.IN:
            for hostname in node_domains.values:
                if hostname not in self.domains and pod_domains.has(hostname) and hostname not in options:
                    options.append(hostname)
        if not options:
            return Requirement.new(self.key, Operator.DOES_NOT_EXIST)
        return Requirement.new(self.key, Operator.IN, *options)


def template_universe_domains(templates) -> dict[str, set[str]]:
    """The template/catalog half of the domain universe — O(templates x
    instance-types x requirement-keys), so callers cache it per template
    set (it is immutable for a scheduler's lifetime) and merge the
    per-solve existing-node half on top."""
    domains: dict[str, set[str]] = defaultdict(set)
    for t in templates:
        for r in t.requirements:
            if r.operator() is Operator.IN:
                domains[r.key].update(r.values)
        for it in t.instance_types:
            for r in it.requirements:
                if r.operator() is not Operator.IN:
                    continue
                tmpl_req = t.requirements.get(r.key)
                domains[r.key].update(v for v in r.values if tmpl_req.has(v))
    return dict(domains)


def pods_declare_topology(pods: Iterable[Pod]) -> bool:
    """Whether ANY pod carries a TSC / (anti)affinity term — the gate for
    Topology.build's fast path. One short-circuiting attribute pass; the
    selector-only north-star workload answers False after three list
    truthiness checks per pod instead of running the full group loop."""
    for p in pods:
        s = p.spec
        if s.topology_spread_constraints or s.pod_affinity or s.pod_anti_affinity:
            return True
    return False


def build_universe_domains(
    templates, existing_nodes=(), template_base: "dict | None" = None
) -> dict[str, set[str]]:
    """key -> all REACHABLE domains (topology.go:105-145 buildDomainGroups):
    template In-requirement values, plus instance-type domain values that
    the template's requirements admit (NotIn exclusions and filtered-out
    instance-type domains must NOT enter the universe — a permanently-zero
    domain would pin the spread global min at 0). template_base: a cached
    template_universe_domains(templates) result to skip the catalog scan."""
    if template_base is None:
        template_base = template_universe_domains(templates)
    domains: dict[str, set[str]] = {k: set(v) for k, v in template_base.items()}
    for n in existing_nodes:
        for r in n.requirements:
            if r.operator() is Operator.IN:
                domains.setdefault(r.key, set()).update(r.values)
    return domains


class Topology:
    """All topology groups for one Solve, seeded from the live cluster."""

    def __init__(self) -> None:
        self.groups: list[TopologyGroup] = []
        self.inverse_groups: list[TopologyGroup] = []
        self._by_ident: dict[tuple, TopologyGroup] = {}

    # -- construction (topology.go:68-145) ----------------------------------

    @staticmethod
    def build(
        pods: list[Pod],
        universe_domains: "dict[str, set[str]] | callable",
        bound_pods: Optional[list[tuple[Pod, dict[str, str]]]] = None,
    ) -> "Topology":
        """universe_domains: key -> all known domains (from nodepools +
        instance types + live nodes; buildDomainGroups), or a zero-arg
        callable producing it — evaluated only when some pod actually
        declares topology. bound_pods: pods already placed, with their
        node's labels — seeds initial counts (topology.go:361-459
        countDomains).

        Fast path: a topology-free pod set (no TSC / (anti)affinity terms
        on any pending pod, no anti-affinity on any bound pod) yields an
        EMPTY Topology without touching the domain universe at all — the
        group loop, universe construction, and downstream domain-tensor
        encoding are all skipped (ops/topology.py caches the empty
        tensors)."""
        if not pods_declare_topology(pods) and not any(
            entry[0].spec.pod_anti_affinity for entry in bound_pods or ()
        ):
            return Topology()
        if callable(universe_domains):
            universe_domains = universe_domains()
        topo = Topology()
        for pod in pods:
            for tsc in pod.spec.topology_spread_constraints:
                # ScheduleAnyway constraints are enforced here like the
                # reference does; the relaxation ladder strips them from the
                # pod spec when they prove unsatisfiable (preferences.go:82)
                g = topo._ensure(
                    TopologyType.SPREAD,
                    tsc.topology_key,
                    tsc.label_selector,
                    tsc.max_skew,
                    tsc.min_domains,
                    pod,
                    universe_domains.get(tsc.topology_key, set()),
                )
                g.owners.add(pod.uid)
            for term in pod.spec.pod_affinity:
                g = topo._ensure(
                    TopologyType.AFFINITY,
                    term.topology_key,
                    term.label_selector,
                    1,
                    None,
                    pod,
                    universe_domains.get(term.topology_key, set()),
                )
                g.owners.add(pod.uid)
            for term in pod.spec.pod_anti_affinity:
                g = topo._ensure(
                    TopologyType.ANTI_AFFINITY,
                    term.topology_key,
                    term.label_selector,
                    1,
                    None,
                    pod,
                    universe_domains.get(term.topology_key, set()),
                )
                g.owners.add(pod.uid)
                # the inverse group records where THIS pod lands so future
                # pods matching the selector avoid it (topology.go:330-356)
                ig = topo._ensure_inverse(
                    term.topology_key,
                    term.label_selector,
                    universe_domains.get(term.topology_key, set()),
                    pod.metadata.namespace,
                )
                ig.owners.add(pod.uid)
        # seed counts from already-bound pods
        for pod, node_labels in bound_pods or []:
            for g in topo.groups:
                domain = node_labels.get(g.key)
                if domain is not None and g.selects(pod):
                    g.record(domain)
            # a bound pod with an anti-affinity term blocks its domain for
            # every pod matching that selector (updateInverseAffinities)
            for term in pod.spec.pod_anti_affinity:
                ig = topo._ensure_inverse(
                    term.topology_key,
                    term.label_selector,
                    universe_domains.get(term.topology_key, set()),
                    pod.metadata.namespace,
                )
                ig.owners.add(pod.uid)
                domain = node_labels.get(term.topology_key)
                if domain is not None:
                    ig.record(domain)
        return topo

    def _ensure(self, ttype, key, selector, max_skew, min_domains, pod, domains) -> TopologyGroup:
        g = TopologyGroup(
            ttype,
            key,
            selector,
            max_skew,
            min_domains,
            frozenset({pod.metadata.namespace}),
            domains,
        )
        existing = self._by_ident.get(g.ident())
        if existing is not None:
            return existing
        self._by_ident[g.ident()] = g
        self.groups.append(g)
        return g

    def _ensure_inverse(self, key, selector, domains, namespace: str) -> TopologyGroup:
        g = TopologyGroup(
            TopologyType.ANTI_AFFINITY, key, selector, 1, None, frozenset({namespace}), domains
        )
        ident = ("inverse",) + g.ident()
        existing = self._by_ident.get(ident)
        if existing is not None:
            return existing
        self._by_ident[ident] = g
        self.inverse_groups.append(g)
        return g

    def register(self, key: str, domain: str) -> None:
        for g in self.groups + self.inverse_groups:
            if g.key == key:
                g.register(domain)

    # -- the per-candidate hook (topology.go:226-250) ------------------------

    @staticmethod
    def still_declared(g: TopologyGroup, pod: Pod) -> bool:
        """Whether the pod's CURRENT spec still declares this group — the
        preference relaxation ladder strips ScheduleAnyway TSCs from the
        spec, and a shed constraint must stop binding even when the group
        object (keyed by the pod's uid) predates the relaxation."""
        if g.type is TopologyType.SPREAD:
            return any(
                t.topology_key == g.key
                and t.label_selector == g.selector
                and t.max_skew == g.max_skew
                for t in pod.spec.topology_spread_constraints
            )
        terms = (
            pod.spec.pod_affinity if g.type is TopologyType.AFFINITY else pod.spec.pod_anti_affinity
        )
        return any(
            t.topology_key == g.key and t.label_selector == g.selector for t in terms
        )

    def matching_groups(self, pod: Pod) -> list[TopologyGroup]:
        """Direct groups the pod owns + inverse groups whose anti-affinity
        selector matches the pod (getMatchingTopologies, topology.go:561)."""
        out = [g for g in self.groups if pod.uid in g.owners and self.still_declared(g, pod)]
        out.extend(g for g in self.inverse_groups if g.selects(pod))
        return out

    def add_requirements(
        self, pod: Pod, pod_reqs: Requirements, node_reqs: Requirements
    ) -> Optional[Requirements]:
        """Tighten node_reqs with each matching group's valid domains;
        None if any group has no valid domain (candidate infeasible)."""
        requirements = node_reqs.copy()
        for g in self.matching_groups(pod):
            pod_domains = pod_reqs.get(g.key)
            node_domains = requirements.get(g.key)
            domains = g.get(pod, pod_domains, node_domains)
            if len(domains) == 0:
                return None
            requirements.add(domains)
        return requirements

    # -- commit (topology.go:190-220 Record) ---------------------------------

    def record(self, pod: Pod, requirements: Requirements) -> None:
        """Commit the placed pod's domains (topology.go:190-220): any group
        whose selector matches the pod counts it — anti-affinity records
        every possible domain, others only a collapsed single domain; the
        pod's own inverse groups record all its candidate domains."""
        for g in self.groups:
            if g.selects(pod):
                domains = requirements.get(g.key)
                if g.type is TopologyType.ANTI_AFFINITY:
                    g.record(*sorted(domains.values))
                elif domains.operator() is Operator.IN and len(domains.values) == 1:
                    g.record(next(iter(domains.values)))
        for g in self.inverse_groups:
            if pod.uid in g.owners:
                g.record(*sorted(requirements.get(g.key).values))
