"""NodeClaim templates: NodePool -> solvable template.

Counterpart of reference nodeclaimtemplate.go:55-150: template requirements
are the pool's spec requirements + its labels (including the
karpenter.sh/nodepool label), and the instance-type options are pre-filtered
to those compatible with the template (scheduler.go:154-171).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from karpenter_tpu.cloudprovider.instancetype import InstanceType
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodepool import NodePool
from karpenter_tpu.models.taints import Taint
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.scheduling.requirements import node_selector_requirement

# Launch-time instance-type truncation (nodeclaimtemplate.go:50)
MAX_INSTANCE_TYPES = 600


@dataclass
class ClaimTemplate:
    nodepool_name: str
    weight: int
    requirements: Requirements
    instance_types: list[InstanceType]
    taints: list[Taint] = field(default_factory=list)
    startup_taints: list[Taint] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    daemon_requests: dict[str, float] = field(default_factory=dict)
    is_static: bool = False
    expire_after_seconds: "float | None" = None
    termination_grace_period_seconds: "float | None" = None
    nodepool_hash: str = ""  # drift-detection hash (nodepool.go:334-344)


def build_template(pool: NodePool, instance_types: list[InstanceType]) -> ClaimTemplate:
    tmpl = pool.spec.template
    labels = dict(tmpl.labels)
    labels[l.NODEPOOL_LABEL_KEY] = pool.name
    reqs = Requirements()
    for r in tmpl.spec.requirements:
        reqs.add(
            node_selector_requirement(
                r["key"], r["operator"], r.get("values", ()), r.get("minValues")
            )
        )
    reqs.add(*Requirements.from_labels(labels).values())
    # pre-filter the catalog to types compatible with the template: the type
    # must intersect the template requirements and have >=1 available
    # offering compatible with them (scheduler.go:154-171)
    compatible = [
        it
        for it in instance_types
        if it.requirements.intersects(reqs) is None and it.has_compatible_offering(reqs)
    ]
    return ClaimTemplate(
        nodepool_name=pool.name,
        weight=pool.spec.weight,
        requirements=reqs,
        instance_types=compatible,
        taints=list(tmpl.spec.taints),
        startup_taints=list(tmpl.spec.startup_taints),
        labels=labels,
        is_static=pool.is_static,
        expire_after_seconds=tmpl.spec.expire_after_seconds,
        termination_grace_period_seconds=tmpl.spec.termination_grace_period_seconds,
        nodepool_hash=pool.static_hash(),
    )


def build_templates(
    pools: list[tuple[NodePool, list[InstanceType]]],
) -> list[ClaimTemplate]:
    """Templates in weight-priority order, heaviest first
    (provisioner.go:268-289); static pools are excluded from dynamic
    provisioning. Ties keep input (name) order for determinism."""
    out = [build_template(p, its) for p, its in pools if not p.is_static]
    out = [t for t in out if t.instance_types]
    out.sort(key=lambda t: -t.weight)
    return out
