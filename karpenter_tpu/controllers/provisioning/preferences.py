"""The preference relaxation ladder.

Exact counterpart of reference preferences.go:38-146. Each relaxation
round removes exactly ONE preference, trying rungs in the reference's
order (Relax, preferences.go:39-44):

  1. a required node-affinity OR term (first term dropped; at least one
     term is always kept)
  2. a preferred pod-affinity term (heaviest first)
  3. a preferred pod-anti-affinity term (heaviest first)
  4. a preferred node-affinity term (heaviest first)
  5. a ScheduleAnyway topology spread constraint (one per round)
  6. a toleration for PreferNoSchedule taints (single final rung)

Relaxation derives a relaxed COPY of the pod (same uid) so every
downstream consumer — Requirements.from_pod, topology group matching,
toleration checks — sees the relaxed spec. Per-pod state is how many rungs
of that pod's ladder have been applied.
"""

from __future__ import annotations

import copy

from karpenter_tpu.models.pod import Pod
from karpenter_tpu.models.taints import PREFER_NO_SCHEDULE, TOLERATION_OP_EXISTS, Toleration

RUNG_OR_TERM = "required-or-term"
RUNG_PREF_POD_AFFINITY = "preferred-pod-affinity"
RUNG_PREF_POD_ANTI = "preferred-pod-anti-affinity"
RUNG_PREF_NODE = "preferred-node-affinity"
RUNG_SOFT_TSC = "schedule-anyway-tsc"
RUNG_TOLERATE = "tolerate-prefer-no-schedule"


def strip_preferences(pod: Pod) -> Pod:
    """PreferencePolicy=Ignore (options.go:33-45): drop preferred node
    affinity, preferred pod (anti)affinity and ScheduleAnyway spread
    constraints up front — required OR terms and tolerations untouched."""
    relaxed = copy.copy(pod)
    relaxed.__dict__.pop("_ktpu_sig", None)  # content changes: drop kind-sig cache
    relaxed.__dict__.pop("_ktpu_ffd", None)
    relaxed.spec = copy.deepcopy(pod.spec)
    if relaxed.spec.node_affinity is not None:
        relaxed.spec.node_affinity.preferred = []
    relaxed.spec.preferred_pod_affinity = []
    relaxed.spec.preferred_pod_anti_affinity = []
    relaxed.spec.topology_spread_constraints = [
        t
        for t in relaxed.spec.topology_spread_constraints
        if t.when_unsatisfiable != "ScheduleAnyway"
    ]
    return relaxed


def terminal_relaxed(pod: Pod) -> Pod:
    """A pod at (or beyond) the END of its relaxation ladder — the sound
    over-approximation the batched what-if prefilter needs.

    strip_preferences alone is NOT enough: the sequential ladder can also
    drop required node-affinity OR terms (trying term k after term k-1
    fails) and add a PreferNoSchedule Exists toleration. Here multi-term
    required affinity is removed ENTIRELY (a superset of every OR branch,
    since Requirements.from_pod binds only required[0]) and the terminal
    toleration is always added, so anything schedulable at ANY rung is
    schedulable for this pod."""
    relaxed = strip_preferences(pod)
    na = relaxed.spec.node_affinity
    if na is not None and len(na.required) > 1:
        na.required = []
    relaxed.spec.tolerations = list(relaxed.spec.tolerations) + [
        Toleration(operator=TOLERATION_OP_EXISTS, effect=PREFER_NO_SCHEDULE)
    ]
    return relaxed


def rungs(pod: Pod) -> list[str]:
    """The pod-specific ladder in reference order; each entry removes one
    preference."""
    out: list[str] = []
    na = pod.spec.node_affinity
    if na is not None and len(na.required) > 1:
        out.extend([RUNG_OR_TERM] * (len(na.required) - 1))
    out.extend([RUNG_PREF_POD_AFFINITY] * len(pod.spec.preferred_pod_affinity))
    out.extend([RUNG_PREF_POD_ANTI] * len(pod.spec.preferred_pod_anti_affinity))
    if na is not None:
        out.extend([RUNG_PREF_NODE] * len(na.preferred))
    out.extend(
        [RUNG_SOFT_TSC]
        * sum(
            1
            for t in pod.spec.topology_spread_constraints
            if t.when_unsatisfiable == "ScheduleAnyway"
        )
    )
    out.append(RUNG_TOLERATE)
    return out


def can_relax(pod: Pod, applied: int) -> bool:
    return applied < len(rungs(pod))


def relax_pod(pod: Pod, applied: int) -> Pod:
    """A copy of pod with the first `applied` rungs of its ladder applied."""
    if applied <= 0:
        return pod
    steps = rungs(pod)[:applied]
    relaxed = copy.copy(pod)
    relaxed.__dict__.pop("_ktpu_sig", None)  # content changes: drop kind-sig cache
    relaxed.__dict__.pop("_ktpu_ffd", None)
    relaxed.spec = copy.deepcopy(pod.spec)
    na = relaxed.spec.node_affinity

    dropped_or = steps.count(RUNG_OR_TERM)
    if dropped_or and na is not None:
        na.required = na.required[dropped_or:]

    n = steps.count(RUNG_PREF_POD_AFFINITY)
    if n:
        relaxed.spec.preferred_pod_affinity = relaxed.spec.preferred_pod_affinity[n:]
    n = steps.count(RUNG_PREF_POD_ANTI)
    if n:
        relaxed.spec.preferred_pod_anti_affinity = relaxed.spec.preferred_pod_anti_affinity[n:]

    n = steps.count(RUNG_PREF_NODE)
    if n and na is not None:
        # heaviest first (preferences.go:67: sort desc by weight)
        ordered = sorted(na.preferred, key=lambda t: -t.weight)
        na.preferred = ordered[n:]

    n = steps.count(RUNG_SOFT_TSC)
    if n:
        kept, removed = [], 0
        for t in relaxed.spec.topology_spread_constraints:
            if t.when_unsatisfiable == "ScheduleAnyway" and removed < n:
                removed += 1
                continue
            kept.append(t)
        relaxed.spec.topology_spread_constraints = kept

    if RUNG_TOLERATE in steps:
        relaxed.spec.tolerations = list(relaxed.spec.tolerations) + [
            Toleration(operator=TOLERATION_OP_EXISTS, effect=PREFER_NO_SCHEDULE)
        ]
    return relaxed


def run_with_relaxation(pods: list[Pod], solve_round, should_stop=None):
    """The outer relax-and-retry loop shared by both engines: each failing
    pod sheds one rung per round and the whole problem re-solves.

    solve_round(current_pods) -> SchedulingResult; it must be safe to call
    repeatedly (fresh state per call). should_stop() is polled after each
    round — when it reports True (the Solve deadline expired,
    provisioner.go:415) the current result is returned without further
    relaxation, mirroring the reference's context-cancelled Solve loop.
    """
    # the per-pod bookkeeping is built lazily: the all-scheduled happy
    # path (the north star) must not pay two 100k-entry dicts up front
    originals = None
    applied: dict = {}
    current = list(pods)

    def _with_provenance(result):
        # relaxation-ladder provenance for the explainer: which rungs each
        # pod shed before the final result (only pods that ever failed a
        # round have entries, so the happy path attaches nothing)
        if originals is not None:
            result.relaxations = {
                uid: rungs(originals[uid])[:n] for uid, n in applied.items() if n
            }
        return result

    while True:
        result = solve_round(current)
        if should_stop is not None and should_stop():
            return _with_provenance(result)
        if not result.unschedulable:
            return _with_provenance(result)
        if originals is None:
            originals = {p.uid: p for p in pods}
            applied = {p.uid: 0 for p in pods}
        relaxed_any = False
        for p, _reason in result.unschedulable:
            orig = originals.get(p.uid)
            if orig is not None and can_relax(orig, applied[p.uid]):
                applied[p.uid] += 1
                relaxed_any = True
        if not relaxed_any:
            return _with_provenance(result)
        current = [relax_pod(originals[p.uid], applied[p.uid]) for p in pods]
