"""Pod batching: debounce window before each provisioning pass.

Counterpart of reference batcher.go:33-100: the window extends while pods
keep arriving within BatchIdleDuration (1s) and is capped at
BatchMaxDuration (10s). In our synchronous manager the batcher decides
WHEN a provisioning pass should run given trigger timestamps.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.utils.clock import Clock

BATCH_IDLE_SECONDS = 1.0  # options.go:129
BATCH_MAX_SECONDS = 10.0  # options.go:130


class Batcher:
    def __init__(self, clock: Clock, idle: float = BATCH_IDLE_SECONDS, max_duration: float = BATCH_MAX_SECONDS):
        self.clock = clock
        self.idle = idle
        self.max_duration = max_duration
        self._window_start: Optional[float] = None
        self._last_trigger: Optional[float] = None

    def trigger(self) -> None:
        now = self.clock.now()
        if self._window_start is None:
            self._window_start = now
        self._last_trigger = now

    @property
    def pending(self) -> bool:
        return self._window_start is not None

    @property
    def window_start(self) -> Optional[float]:
        """When the open debounce window began (injected-clock time);
        None when no window is open. The manager reads this to record the
        batcher-wait span and the batch-window histogram."""
        return self._window_start

    def ready(self) -> bool:
        """The window closed: idle elapsed since last trigger, or max hit."""
        if self._window_start is None:
            return False
        now = self.clock.now()
        if now - self._window_start >= self.max_duration:
            return True
        return now - self._last_trigger >= self.idle

    def reset(self) -> None:
        self._window_start = None
        self._last_trigger = None
