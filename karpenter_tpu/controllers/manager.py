"""The controller manager: informer wiring + synchronous reconcile loops.

Counterpart of the reference's operator/manager + informer controllers
(pkg/controllers/state/informer, controllers.go:85-194), collapsed into a
deterministic in-process engine: ObjectStore watch events update the
Cluster mirror synchronously, and `run_until_idle` drains reconcile work
until the system reaches a fixed point — the in-process analog of
controller-runtime's event loop that envtest-style tests can step.
"""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.controllers.nodeclaim_disruption import NodeClaimDisruptionController
from karpenter_tpu.controllers.nodeclaim_lifecycle import NodeClaimLifecycleController
from karpenter_tpu.controllers.provisioning.batcher import Batcher
from karpenter_tpu.controllers.provisioning.provisioner import Provisioner
from karpenter_tpu.models import labels as l
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.scheduling.taints import tolerates_all
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.store import EventType, ObjectStore
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.clock import Clock


def _find_overlay_provider(cloud):
    """Walk the decorator chain (metrics -> overlay -> provider) for the
    overlay decorator the nodeoverlay controller manages."""
    from karpenter_tpu.cloudprovider.overlay import OverlayCloudProvider

    seen = 0
    while cloud is not None and seen < 8:
        if isinstance(cloud, OverlayCloudProvider):
            return cloud
        cloud = getattr(cloud, "inner", None)
        seen += 1
    return None


class Manager:
    def __init__(
        self,
        store: ObjectStore,
        cloud: CloudProvider,
        clock: Optional[Clock] = None,
        options=None,
    ):
        from karpenter_tpu.utils.options import Options

        self.store = store
        self.cloud = cloud
        self.clock = clock or store.clock
        self.options = options or Options()
        self.cluster = Cluster(self.clock)
        from karpenter_tpu.utils.events import Recorder

        # the deduped event stream (recorder.go:47-110): the scheduling
        # explainer and controllers publish domain events through it
        self.recorder = Recorder(self.clock)
        self.batcher = Batcher(
            self.clock,
            idle=self.options.batch_idle_seconds,
            max_duration=self.options.batch_max_seconds,
        )
        # ONE blackout cache shared by the lifecycle controller (marks on
        # ICE) and the Provisioner (filters the catalog): the loop that
        # makes a failed launch stop being re-picked for the TTL
        from karpenter_tpu.cloudprovider.unavailable import UnavailableOfferings

        self.unavailable = UnavailableOfferings(self.clock)
        self.provisioner = Provisioner(
            store,
            self.cluster,
            cloud,
            self.clock,
            unavailable=self.unavailable,
            ignore_preferences=self.options.preference_policy == "Ignore",
            reserved_capacity_enabled=self.options.feature_gates.reserved_capacity,
            min_values_policy=self.options.min_values_policy,
            dynamic_resources_enabled=self.options.feature_gates.dynamic_resources,
            solve_timeout_seconds=self.options.solve_timeout_seconds,
            solver_endpoint=self.options.solver_endpoint,
            mesh_devices=self.options.mesh_devices,
            recorder=self.recorder,
        )
        self.device_allocation = None
        if self.options.feature_gates.dynamic_resources:
            from karpenter_tpu.controllers.device_allocation import DeviceAllocationController

            self.device_allocation = DeviceAllocationController(store, self.clock)
            self.provisioner.device_allocation = self.device_allocation
        self.lifecycle = NodeClaimLifecycleController(
            store, cloud, self.clock, unavailable=self.unavailable
        )
        self.nodeclaim_disruption = NodeClaimDisruptionController(store, cloud, self.clock)
        from karpenter_tpu.controllers.disruption import DisruptionController
        from karpenter_tpu.controllers.garbage_collection import (
            ExpirationController,
            GarbageCollectionController,
            NodeHealthController,
        )

        self.disruption = DisruptionController(
            store,
            self.cluster,
            self.provisioner,
            cloud,
            self.clock,
            spot_to_spot_enabled=self.options.feature_gates.spot_to_spot_consolidation,
            cost_ledger=None,
        )
        self.garbage_collection = GarbageCollectionController(store, cloud, self.clock)
        self.expiration = ExpirationController(store, self.clock)
        self.health = NodeHealthController(store, cloud, self.clock)
        from karpenter_tpu.controllers.static_capacity import StaticCapacityController
        from karpenter_tpu.state.cost import ClusterCost, NodePoolHealth

        self.static_capacity = StaticCapacityController(store, self.cluster, cloud, self.clock)
        from karpenter_tpu.controllers.capacity_buffer import CapacityBufferController
        from karpenter_tpu.controllers.metrics_state import PodMetricsController

        # buffer status controller: template resolution + replica targets
        # + ReadyForProvisioning (capacitybuffer/controller.go)
        self.capacity_buffer = CapacityBufferController(
            store, self.clock, trigger=self.batcher
        )
        # stateful: owns the bound/startup latency dedup sets
        self._pod_metrics = PodMetricsController(store, self.clock)
        self.cost = ClusterCost()
        self.pool_health = NodePoolHealth()
        self.disruption.cost_ledger = self.cost
        self._launched_claims: set[str] = set()
        self._launch_recorded: set[str] = set()  # one ring entry per launch
        self._catalog_by_name: dict = {}
        self._dirty_claims: set[str] = set()
        self._claim_by_pid: dict[str, str] = {}  # provider_id -> claim name
        self._gated_passes = 0
        # nodeoverlay runtime controller: wired only when the cloud chain
        # carries the overlay decorator (nodeoverlay/controller.go:62-140)
        self.nodeoverlay = None
        overlay_cp = _find_overlay_provider(cloud)
        if overlay_cp is not None:
            from karpenter_tpu.controllers.nodeoverlay import (
                EvaluatedOverlayStore,
                NodeOverlayController,
            )

            evaluated = EvaluatedOverlayStore()
            overlay_cp.evaluated_store = evaluated
            self.nodeoverlay = NodeOverlayController(
                store, overlay_cp.inner, self.clock, evaluated
            )
            # first evaluation lifts the UnevaluatedNodePoolError gate for
            # pools already present; later pools re-trigger via informers
            self.nodeoverlay.reconcile()
        self._wire_informers()

    # -- informers (state/informer/*.go) ---------------------------------------

    def _wire_informers(self) -> None:
        self.store.watch(ObjectStore.PODS, self._on_pod)
        self.store.watch(ObjectStore.NODES, self._on_node)
        self.store.watch(ObjectStore.NODECLAIMS, self._on_nodeclaim)
        self.store.watch(ObjectStore.NODEPOOLS, self._on_nodepool)
        # overlay changes reprice the catalog: drop the price cache and
        # revalidate (controller.go:146 watches NodeOverlay events)
        self.store.watch(ObjectStore.NODE_OVERLAYS, self._on_overlay)
        # buffer / template / scalable events re-resolve replica targets
        # and trigger a provisioning pass (controller.go:106-118)
        for kind in (
            ObjectStore.CAPACITY_BUFFERS,
            ObjectStore.POD_TEMPLATES,
            ObjectStore.SCALABLES,
        ):
            self.store.watch(kind, self._on_buffer_event)
        self.store.watch(ObjectStore.RESOURCE_SLICES, self._on_resource_slice)
        self.store.watch(ObjectStore.VOLUME_ATTACHMENTS, self._on_volume_attachment)
        # daemonset informer (state/informer/daemonset.go): overhead groups
        # are rebuilt per solve, so correctness never depended on this —
        # the watch exists so pods that now fit differently get a pass NOW
        # instead of waiting for the next unrelated trigger
        self.store.watch(ObjectStore.DAEMONSETS, self._on_daemonset)

    def _on_daemonset(self, event: EventType, ds) -> None:
        if any(p.is_provisionable() for p in self.store.pods()):
            self.batcher.trigger()

    def _on_volume_attachment(self, event: EventType, va) -> None:
        # the attach-detach controller deleting an attachment can unblock a
        # terminating claim's volume-detach await
        # (termination/controller.go:236-277)
        if event is EventType.DELETED:
            for claim in self.store.nodeclaims():
                if claim.metadata.deleting:
                    self._dirty_claims.add(claim.name)

    def _on_buffer_event(self, event: EventType, obj) -> None:
        self.capacity_buffer.reconcile()

    def _on_resource_slice(self, event: EventType, obj) -> None:
        # a driver publishing its pool can unblock initialization
        # (initialization.go:148-178 draDriverPoolsPublished)
        from karpenter_tpu.models.nodeclaim import COND_INITIALIZED

        for claim in self.store.nodeclaims():
            if not claim.conditions.is_true(COND_INITIALIZED):
                self._dirty_claims.add(claim.name)

    def _on_overlay(self, event: EventType, overlay) -> None:
        self._catalog_by_name.clear()
        if self.nodeoverlay is not None:
            self.nodeoverlay.reconcile()
        # pricing informer analog (state/informer/pricing.go): an overlay
        # price change must re-derive every live claim's ledger price —
        # Balanced scoring divides by pool_cost, and a stale denominator
        # approves/rejects moves against prices that no longer exist
        self._reprice_claims()

    def _on_nodepool(self, event: EventType, pool) -> None:
        self._catalog_by_name = {}  # pool changes can reshape the catalog
        if self.nodeoverlay is not None:
            # evaluate the new/changed pool BEFORE provisioning sees it, so
            # the unevaluated gate lifts within the same event turn
            # (controller.go:147 watches NodePool events)
            self.nodeoverlay.reconcile()
        # pool template/requirement changes reshape offerings and therefore
        # the prices the ledger carries (pricing.go re-sync analog)
        self._reprice_claims()
        # a new/changed pool may unblock gated provisioning
        if any(p.is_provisionable() for p in self.store.pods()):
            self.batcher.trigger()

    def _reprice_claims(self) -> None:
        """Re-derive every launched claim's hourly price into ClusterCost
        from the CURRENT catalog (informer/pricing.go: a pricing change
        re-syncs state without waiting for claim churn)."""
        for claim in self.store.nodeclaims():
            if claim.status.provider_id and claim.nodepool_name:
                self.cost.set_claim(
                    claim.nodepool_name, claim.name, self._claim_price(claim)
                )

    def _on_pod(self, event: EventType, pod) -> None:
        if event is EventType.DELETED:
            self.cluster.delete_pod(pod)
            return
        self.cluster.update_pod(pod)
        if pod.is_provisionable():
            self.batcher.trigger()

    def _on_node(self, event: EventType, node) -> None:
        if event is EventType.DELETED:
            self.cluster.delete_node(node.name)
            self.cluster.clear_nominations_for(node.name)
            self.health.clear(node.name)  # stale entries would jam the breaker
            if any(p.is_provisionable() for p in self.store.pods()):
                self.batcher.trigger()
            return
        self.cluster.update_node(node)
        # node changes can unblock registration/initialization
        claim_name = self._claim_by_pid.get(node.spec.provider_id)
        if claim_name is not None:
            self._dirty_claims.add(claim_name)

    def _claim_price(self, claim) -> float:
        from karpenter_tpu.models import labels as l

        name = claim.metadata.labels.get(l.LABEL_INSTANCE_TYPE, "")
        if name not in self._catalog_by_name:
            from karpenter_tpu.cloudprovider.errors import instance_types_or_none

            # rebuild on miss: pools/overlays may have changed the catalog
            self._catalog_by_name = {}
            for pool in self.store.nodepools():
                for it in instance_types_or_none(self.cloud, pool) or ():
                    self._catalog_by_name.setdefault(it.name, it)
        it = self._catalog_by_name.get(name)
        if it is None:
            return 0.0
        price = it.offering_price(
            claim.metadata.labels.get(l.LABEL_TOPOLOGY_ZONE, ""),
            claim.metadata.labels.get(l.CAPACITY_TYPE_LABEL_KEY, ""),
        )
        return price or 0.0

    def _on_nodeclaim(self, event: EventType, claim) -> None:
        from karpenter_tpu.models.nodeclaim import COND_LAUNCHED, COND_REGISTERED

        if event is EventType.DELETED:
            self.cluster.delete_nodeclaim(claim.name)
            self.cluster.clear_nominations_for(claim.name)
            self.cost.remove_claim(claim.nodepool_name, claim.name)
            if (
                claim.name in self._launched_claims
                and claim.name not in self._launch_recorded
                and not claim.conditions.is_true(COND_REGISTERED)
            ):
                # launched but never registered: a failed launch for the
                # pool-health ring buffer (liveness.go:115)
                self.pool_health.record(claim.nodepool_name or "", False)
            self._launched_claims.discard(claim.name)
            self._launch_recorded.discard(claim.name)
            if claim.status.provider_id:
                self._claim_by_pid.pop(claim.status.provider_id, None)
            # pods that were counting on this claim need a fresh pass
            if any(p.is_provisionable() for p in self.store.pods()):
                self.batcher.trigger()
            return
        self.cluster.update_nodeclaim(claim)
        if claim.status.provider_id:
            self._claim_by_pid[claim.status.provider_id] = claim.name
            if claim.nodepool_name:
                self.cost.set_claim(claim.nodepool_name, claim.name, self._claim_price(claim))
        if claim.conditions.is_true(COND_LAUNCHED):
            self._launched_claims.add(claim.name)
        # exactly ONE ring entry per launch (tracker.go): success recorded
        # on the first registration, never again on routine updates
        if (
            claim.conditions.is_true(COND_REGISTERED)
            and claim.name in self._launched_claims
            and claim.name not in self._launch_recorded
        ):
            self.pool_health.record(claim.nodepool_name or "", True)
            self._launch_recorded.add(claim.name)
        self._dirty_claims.add(claim.name)

    # -- the loop ----------------------------------------------------------------

    def step(self) -> bool:
        """One pass over all due work; True if anything happened."""
        from karpenter_tpu.tracing.tracer import TRACER

        worked = False
        # nodeclaim lifecycle
        dirty, self._dirty_claims = self._dirty_claims, set()
        if dirty:
            from karpenter_tpu.cloudprovider.errors import TransientError

            with TRACER.span("lifecycle.drain", claims=len(dirty)):
                for name in sorted(dirty):
                    claim = self.store.get(ObjectStore.NODECLAIMS, name)
                    if claim is not None:
                        try:
                            self.lifecycle.reconcile(claim)
                        except TransientError:
                            # a flaky apiserver write mid-reconcile:
                            # requeue the claim (idempotent reconcilers
                            # make the retry safe) instead of crashing
                            # the whole drain pass
                            from karpenter_tpu.utils import metrics

                            metrics.TRANSIENT_RETRIES.inc(
                                controller="nodeclaim.lifecycle"
                            )
                            self._dirty_claims.add(name)
                        worked = True
        # device allocation collapse (DRA): claims whose NodeClaim launched
        if self.device_allocation is not None:
            worked = bool(self.device_allocation.reconcile_once()) or worked
        # provisioning batch window
        if self.batcher.ready():
            from karpenter_tpu.utils import metrics

            window_start = self.batcher.window_start
            wait = (
                self.clock.now() - window_start if window_start is not None else 0.0
            )
            with TRACER.span("provisioning"):
                # the debounce window the solve waited out, as a
                # retroactive child span (measured on the injected clock)
                TRACER.record_span("batcher.wait", wait)
                outcome = self.provisioner.reconcile()
            if outcome == Provisioner.GATED:
                # keep the trigger alive: gating (unsynced cluster, missing
                # pools) usually clears after other reconciles; give up
                # after a few idle passes — pool/pod events re-trigger
                self._gated_passes += 1
                if self._gated_passes >= 3:
                    self.batcher.reset()
                    self._gated_passes = 0
            else:
                # one histogram entry per CLOSED window (gated retries
                # re-enter with the same window open)
                metrics.BATCH_WINDOW_SECONDS.observe(wait)
                self._gated_passes = 0
                self.batcher.reset()
                worked = worked or outcome is not None
        return worked

    def run_disruption_once(self):
        """One disruption poll (the 10s singleton loop's body) followed by
        an orchestration-queue pass and a drain of resulting work."""
        self._last_disruption_poll = self.clock.now()
        command = self.disruption.reconcile()
        self.run_until_idle()
        self.disruption.queue.process()
        self.run_until_idle()
        return command

    def maybe_run_disruption(self):
        """Poll-paced disruption (controller.go:71, options
        disruption_poll_seconds): a no-op until the interval elapses."""
        last = getattr(self, "_last_disruption_poll", None)
        if last is not None and (
            self.clock.now() - last < self.options.disruption_poll_seconds
        ):
            return None
        return self.run_disruption_once()

    def run_maintenance(self) -> dict:
        """One pass of the periodic housekeeping controllers (GC,
        expiration, health), then drain resulting work."""
        from karpenter_tpu.controllers.status_controllers import (
            ConsistencyController,
            NodePoolStatusController,
            NodePoolValidationController,
        )

        from karpenter_tpu.controllers.status_controllers import HydrationController

        out = {
            # the 6h overlay revalidation requeue (controller.go:140)
            "overlay_eval": (
                self.nodeoverlay.maybe_reconcile()
                if self.nodeoverlay is not None
                else None
            ),
            # the 30s buffer-resolution requeue (capacitybuffer
            # controller.go:103)
            "buffers": self.capacity_buffer.maybe_reconcile(),
            "invalid_pools": NodePoolValidationController(self.store, self.clock).reconcile(),
            "hydrated": HydrationController(self.store).reconcile(),
            "expired": self.expiration.reconcile(),
            "garbage_collected": self.garbage_collection.reconcile(),
            "repaired": self.health.reconcile(),
            "static_delta": self.static_capacity.reconcile(),
            "inconsistent": ConsistencyController(self.store, self.clock).reconcile(),
        }
        # re-drive deleting claims whose drain is blocked on TGP expiry —
        # the event-driven loop won't see a clock advance (the requeue
        # analog of termination/controller.go's retry)
        for claim in self.store.nodeclaims():
            if claim.metadata.deleting:
                self._dirty_claims.add(claim.name)
        self.run_until_idle()
        # nodepool usage/limit gauges (controllers/metrics/nodepool analog):
        # the status controller just computed usage into pool.status; clear
        # the whole family first so series for vanished pools/resources
        # don't linger at stale values
        NodePoolStatusController(self.store, self.cluster, self.clock).reconcile()
        # per-object state gauges (controllers/metrics/{pod,node} analogs)
        from karpenter_tpu.controllers.metrics_state import (
            NodeMetricsController,
            StatusConditionMetricsController,
        )

        self._pod_metrics.reconcile()
        NodeMetricsController(self.store, self.cluster).reconcile()
        StatusConditionMetricsController(self.store).reconcile()
        from karpenter_tpu.utils import metrics

        metrics.NODEPOOL_USAGE.values.clear()
        metrics.NODEPOOL_LIMIT.values.clear()
        for pool in self.store.nodepools():
            for resource, value in pool.status.resources.items():
                metrics.NODEPOOL_USAGE.set(value, nodepool=pool.name, resource_type=resource)
            if pool.spec.limits is not None:
                for resource, value in pool.spec.limits.resources.items():
                    metrics.NODEPOOL_LIMIT.set(
                        value, nodepool=pool.name, resource_type=resource
                    )
        return out

    def mark_drift(self) -> int:
        """Run the drift-detection pass over all claims; returns how many
        transitioned (nodeclaim.disruption controller)."""
        changed = 0
        for claim in self.store.nodeclaims():
            changed += bool(self.nodeclaim_disruption.reconcile(claim))
        self.run_until_idle()
        return changed

    def run_until_idle(self, max_iterations: int = 1000) -> None:
        """Drain reconcile work to a fixed point; advances the fake clock
        past the batch window when provisioning is pending."""
        for _ in range(max_iterations):
            if not self.step():
                if self.batcher.pending:
                    # let the batch window close (fake clock jumps; real
                    # clock sleeps the remaining idle time)
                    self.clock.sleep(self.batcher.idle)
                    continue
                if not self._dirty_claims:
                    return
        raise RuntimeError("manager did not reach a fixed point")


class KubeSchedulerSim:
    """Minimal kube-scheduler stand-in for the e2e harness: binds pending
    pods to Ready, registered, untainted-compatible nodes (the reference
    relies on the real kube-scheduler + KWOK for this).

    Nominated pods bind to their nominated target first — the solver's
    topology-aware placement must not be scrambled by greedy first-fit
    (the real kube-scheduler re-evaluates TSC itself; this sim trusts the
    solver's decision instead)."""

    def __init__(self, store: ObjectStore, cluster: Cluster, dra_aware: bool = True):
        self.store = store
        self.cluster = cluster
        # The real kube-scheduler always enforces DRA allocation before
        # binding (and can allocate in-cluster claims itself, which this sim
        # cannot). Harnesses running with the DynamicResources gate OFF but
        # claim-bearing pods should pass dra_aware=False — the analog of the
        # reference's IgnoreDRARequests (scheduler.go:584) — or claim pods
        # will wait forever for an allocation nothing is going to write.
        self.dra_aware = dra_aware

    def _bindable(self, sn, pod, pod_reqs) -> bool:
        node = sn.node
        if node is None or not node.status.ready or sn.marked_for_deletion:
            return False
        if tolerates_all(node.spec.taints, pod.spec.tolerations) is not None:
            return False
        node_reqs = Requirements.from_labels(node.metadata.labels)
        if node_reqs.compatible(pod_reqs, l.WELL_KNOWN_LABELS) is not None:
            return False
        if not self._dra_bindable(node, pod, node_reqs):
            return False
        return res.fits(pod.total_requests(), sn.available())

    def _dra_bindable(self, node, pod, node_reqs) -> bool:
        """The real kube-scheduler's DRA plugin refuses to bind a pod whose
        ResourceClaims aren't allocated and reserved for it on a node the
        allocation's selector admits; mirror that here so unallocated DRA
        pods wait instead of landing deviceless."""
        if not self.dra_aware or not pod.spec.resource_claims:
            return True
        for name in pod.spec.resource_claims:
            rc = self.store.get(ObjectStore.RESOURCE_CLAIMS, name)
            if rc is None or rc.allocation is None:
                return False
            if pod.uid not in rc.reserved_for:
                return False
            terms = rc.allocation.node_selector_terms
            if terms and not any(
                node_reqs.is_compatible(term, l.WELL_KNOWN_LABELS) for term in terms
            ):
                return False
        return True

    def _node_for_target(self, target: str):
        """A nomination target is a node name or a claim name."""
        sn = self.cluster.node_by_name(target)
        if sn is not None and sn.node is not None:
            return sn
        claim = self.store.get(ObjectStore.NODECLAIMS, target)
        if claim is not None and claim.status.node_name:
            return self.cluster.node_by_name(claim.status.node_name)
        return None

    def bind_pending(self) -> int:
        from karpenter_tpu.tracing.tracer import TRACER

        with TRACER.span("bind.pending") as sp:
            bound = self._bind_pending()
            sp.set(bound=bound)
        return bound

    def _gang_gate(self, pod, ready_cache: dict) -> bool:
        """The gang bind gate: a gang member binds ONLY when every member
        of its gang is bindable too — already bound, or pending with a
        live nomination whose target node exists, is Ready, and admits it.
        This is what makes "no partial gang ever binds" hold end-to-end:
        a slice host lost to an ICE or node failure un-readies the whole
        gang until the full gang re-places (the real deployment's
        coscheduling gate; the reference leans on scheduler plugins)."""
        from karpenter_tpu.gang import gang_of

        parsed = gang_of(pod)
        if parsed is None:
            return True
        key, size, _rank = parsed
        ready = ready_cache.get(key)
        if ready is None:
            members = [
                p
                for p in self.store.pods()
                if (g := gang_of(p)) is not None and g[0] == key
            ]
            ready = len(members) >= size
            if ready:
                for m in members:
                    if m.spec.node_name:
                        continue  # already bound
                    target = self.cluster.pod_nomination(m.uid)
                    sn = self._node_for_target(target) if target is not None else None
                    if sn is None or not self._bindable(
                        sn, m, Requirements.from_pod(m)
                    ):
                        ready = False
                        break
            ready_cache[key] = ready
        return ready

    def _bind_pending(self) -> int:
        bound = 0
        gang_ready: dict[str, bool] = {}
        from karpenter_tpu.gang import is_gang_pod

        for pod in self.store.pods():
            if not pod.is_pending():
                continue
            pod_reqs = Requirements.from_pod(pod)
            # nominated target first
            target = self.cluster.pod_nomination(pod.uid)
            if target is not None:
                sn = self._node_for_target(target)
                if sn is not None and self._bindable(sn, pod, pod_reqs):
                    if not self._gang_gate(pod, gang_ready):
                        continue  # all-or-nothing: wait for the full slice
                    self.store.bind_pod(pod.name, sn.node.name)
                    bound += 1
                    continue
                continue  # target not ready yet: wait instead of scrambling
            if is_gang_pod(pod):
                # gang members bind only through their slice nomination —
                # greedy placement would scramble the rank layout
                continue
            # greedy fallback must not consume capacity OTHER pods' live
            # nominations reserved
            reserved = self.cluster.nomination_targets()
            for sn in self.cluster.nodes():
                if sn.name in reserved or (
                    sn.node_claim is not None and sn.node_claim.name in reserved
                ):
                    continue
                if self._bindable(sn, pod, pod_reqs):
                    self.store.bind_pod(pod.name, sn.node.name)
                    bound += 1
                    break
        return bound
