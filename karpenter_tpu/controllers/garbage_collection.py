"""Reconciling cloud truth: garbage collection, expiration, node repair.

Counterparts of reference pkg/controllers/nodeclaim/garbagecollection
(controller.go:64-133), nodeclaim/expiration (controller.go:58-107), and
node/health (controller.go:110-215 with the 20% circuit breaker).
"""

from __future__ import annotations

from karpenter_tpu.cloudprovider.spi import CloudProvider
from karpenter_tpu.models import labels as l
from karpenter_tpu.models.nodeclaim import COND_LAUNCHED
from karpenter_tpu.state.store import ObjectStore
from karpenter_tpu.utils.clock import Clock

UNHEALTHY_CIRCUIT_BREAKER_FRACTION = 0.20  # health/controller.go:110-215


class GarbageCollectionController:
    """Deletes claims whose instance vanished and nodes without claims.

    Pods bound to collected nodes are evicted first so they reschedule —
    without this, a vanished instance would strand its pods Running with a
    dangling node_name forever.
    """

    def __init__(self, store: ObjectStore, cloud: CloudProvider, clock: Clock):
        from karpenter_tpu.controllers.node_termination import Terminator

        self.store = store
        self.cloud = cloud
        self.clock = clock
        self.terminator = Terminator(store, clock)

    def _evict_bound_pods(self, node_name: str) -> None:
        for pod in self.store.pods():
            if pod.spec.node_name == node_name and not pod.is_terminal():
                self.terminator._evict(pod)

    def reconcile(self) -> int:
        removed = 0
        live_pids = {c.status.provider_id for c in self.cloud.list()}
        for claim in list(self.store.nodeclaims()):
            if not claim.conditions.is_true(COND_LAUNCHED) or not claim.status.provider_id:
                continue
            if claim.status.provider_id not in live_pids:
                node = self.store.node_by_provider_id(claim.status.provider_id)
                if node is not None:
                    self._evict_bound_pods(node.name)
                elif claim.status.node_name:
                    self._evict_bound_pods(claim.status.node_name)
                claim.metadata.finalizers = []
                self.store.delete(ObjectStore.NODECLAIMS, claim.name)
                removed += 1
        claim_pids = {
            c.status.provider_id for c in self.store.nodeclaims() if c.status.provider_id
        }
        for node in list(self.store.nodes()):
            managed = l.NODEPOOL_LABEL_KEY in node.metadata.labels
            if managed and node.spec.provider_id not in claim_pids:
                self._evict_bound_pods(node.name)
                node.metadata.finalizers = []
                self.store.delete(ObjectStore.NODES, node.name)
                removed += 1
        return removed


class ExpirationController:
    """Forcefully deletes claims older than expireAfter
    (expiration/controller.go:58-107)."""

    def __init__(self, store: ObjectStore, clock: Clock):
        self.store = store
        self.clock = clock

    def reconcile(self) -> int:
        expired = 0
        for claim in list(self.store.nodeclaims()):
            after = claim.spec.expire_after_seconds
            if after is None or claim.metadata.deleting:
                continue
            if self.clock.now() - claim.metadata.creation_timestamp >= after:
                claim.metadata.annotations["karpenter.sh/termination-reason"] = "expired"
                self.store.delete(ObjectStore.NODECLAIMS, claim.name)
                expired += 1
        return expired


class NodeHealthController:
    """Force-deletes unhealthy nodes per provider RepairPolicies, with a
    cluster-wide >20%-unhealthy circuit breaker (health/controller.go).

    Condition feed contract: callers observe() when an unhealthy condition
    appears and resolve() when it recovers — repair requires the condition
    to PERSIST for the policy's toleration window, so a recovered blip must
    be resolved or the node would be repaired spuriously.
    """

    def __init__(self, store: ObjectStore, cloud: CloudProvider, clock: Clock):
        self.store = store
        self.cloud = cloud
        self.clock = clock
        self._unhealthy_since: dict[str, float] = {}

    def observe(self, node_name: str, condition_type: str, status: str) -> None:
        """Record a node condition (the harness's kubelet-condition feed)."""
        key = f"{node_name}/{condition_type}={status}"
        self._unhealthy_since.setdefault(key, self.clock.now())

    def resolve(self, node_name: str, condition_type: str) -> None:
        """The condition recovered: drop its timer."""
        prefix = f"{node_name}/{condition_type}="
        self._unhealthy_since = {
            k: v for k, v in self._unhealthy_since.items() if not k.startswith(prefix)
        }

    def clear(self, node_name: str) -> None:
        self._unhealthy_since = {
            k: v for k, v in self._unhealthy_since.items() if not k.startswith(node_name + "/")
        }

    def reconcile(self) -> int:
        policies = self.cloud.repair_policies()
        if not policies:
            return 0
        nodes = self.store.nodes()
        if not nodes:
            return 0
        # prune entries for nodes that no longer exist — stale timers must
        # not inflate the circuit breaker
        live = {n.name for n in nodes}
        self._unhealthy_since = {
            k: v for k, v in self._unhealthy_since.items() if k.split("/", 1)[0] in live
        }
        # EVERY unhealthy node counts toward the breaker — including
        # unmanaged ones repair can't touch (health/controller.go:249-263:
        # a mostly-unhealthy cluster means something systemic, so repairs
        # must stop) — but only claim-backed nodes are repairable
        claim_by_pid = {
            c.status.provider_id: c for c in self.store.nodeclaims() if c.status.provider_id
        }
        unhealthy_nodes = set()
        for policy in policies:
            key_suffix = f"/{policy.condition_type}={policy.condition_status}"
            for key, since in self._unhealthy_since.items():
                if key.endswith(key_suffix) and self.clock.now() - since >= policy.toleration_seconds:
                    unhealthy_nodes.add(key.split("/", 1)[0])
        if not unhealthy_nodes:
            return 0
        # circuit breaker: never repair when >20% of the fleet is unhealthy
        if len(unhealthy_nodes) / len(nodes) > UNHEALTHY_CIRCUIT_BREAKER_FRACTION and len(nodes) > 1:
            return 0
        repaired = 0
        for node in nodes:
            if node.name not in unhealthy_nodes:
                continue
            claim = claim_by_pid.get(node.spec.provider_id)
            if claim is not None:
                claim.metadata.annotations["karpenter.sh/termination-reason"] = "unhealthy"
                self.store.delete(ObjectStore.NODECLAIMS, claim.name)
                self.clear(node.name)
                repaired += 1
        return repaired
